#!/usr/bin/env python
"""Benchmark harness — fluid_benchmark.py analog (reference:
benchmark/fluid/fluid_benchmark.py:296-300 examples/sec metric).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` compares against the last recorded value in BENCH_HISTORY.json
(the reference publishes no numbers — BASELINE.md — so the baseline is our own
trajectory; >1.0 means faster than the previous record).

Usage: python bench.py [--smoke] [--model mnist_mlp]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _ledger_flops(program, fn, *args, n_partitions=1, **kwargs):
    """FLOPs of one dispatch of ``fn(*args)`` — the same XLA cost-model
    number ``utils.flops.lowered_flops`` reads, but REGISTERED in the
    telemetry cost ledger under ``program`` so report_line can audit
    the emitted mfu against the registry record (ride the name along as
    ``extras["ledger_program"]`` plus ``ledger_dispatches`` /
    ``ledger_window_s``). None when the backend won't cost the module
    (the provenance-only record still registers)."""
    from paddle_tpu.telemetry import costs as _tcosts

    try:
        return _tcosts.analyze_callable(
            program, fn, *args, n_partitions=n_partitions,
            **kwargs).get("flops")
    except Exception:
        return None


def bench_mnist_mlp(steps: int, batch_size: int, warmup: int = 5,
                    steps_per_call: int = 8, dp: int = 1, amp=None):
    """BASELINE config 1. ``steps_per_call`` fuses K optimizer steps into
    one dispatch (Trainer.train_steps lax.scan) — through the remote-device
    tunnel the per-dispatch round trip dominates a step this small.
    ``dp``: data-parallel device count (fluid_benchmark's --gpus analog);
    the batch shards over the dp mesh axis and XLA inserts the gradient
    all-reduce."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import optimizer, parallel
    from paddle_tpu.models import mnist as M

    pt.seed(0)
    assert batch_size >= dp > 0, f"batch {batch_size} must be >= dp {dp}"
    mesh = pt.build_mesh(dp=dp, devices=jax.devices()[:dp])
    model = M.MnistMLP(hidden1=512, hidden2=256)
    if _MODE == "infer":
        _rng = np.random.default_rng(0)
        return _infer_bench(
            model, lambda bs: (jnp.asarray(
                _rng.normal(size=(bs, 784)).astype(np.float32)),),
            steps, batch_size, amp=amp)
    trainer = parallel.Trainer.supervised(
        model, optimizer.Adam(1e-3), M.loss_fn, mesh=mesh, amp=amp)
    rng = np.random.default_rng(0)
    batch_size -= batch_size % max(dp, 1)
    x = jnp.asarray(rng.normal(size=(batch_size, 784)).astype(np.float32))
    label = jnp.asarray(rng.integers(0, 10, batch_size))
    batch = {"x": x, "label": label}
    if dp > 1:
        sh = trainer.data_sharding()
        batch = {k: jax.device_put(v, sh) for k, v in batch.items()}
    k = max(1, steps_per_call)
    outer = max(1, steps // k)
    # FLOPs of the module that is ACTUALLY dispatched (the k-step scan
    # when k>1) — lowered before any call donates buffers, and the AOT
    # compile inside the fallback is the same executable the timed loop
    # reuses via the persistent cache. Registered in the telemetry cost
    # ledger so the emitted mfu is auditable against the registry.
    ledger_program = "bench.mnist_mlp.step"
    step_flops = _ledger_flops(
        ledger_program, trainer.steps_jit(k) if k > 1 else
        trainer._jit_step, trainer.params, trainer.buffers,
        trainer.opt_state, trainer._rng, batch, n_partitions=dp)
    if step_flops and k > 1:
        step_flops /= k
    for _ in range(warmup):
        loss, _ = (trainer.train_steps(batch, k) if k > 1
                   else trainer.train_step(batch))
    float(loss)  # host fetch = the only reliable fence (see _train_bench)
    t0 = time.perf_counter()
    for i in range(outer):
        loss, _ = (trainer.train_steps(batch, k) if k > 1
                   else trainer.train_step(batch))
        if i % 4 == 3:
            float(loss)
    float(loss)
    dt = time.perf_counter() - t0
    extras = {"step_time_ms": round(dt / (outer * k) * 1e3, 3)}
    if step_flops:
        extras["flops_per_sec"] = step_flops * outer * k / dt
        extras.update(ledger_program=ledger_program,
                      ledger_dispatches=outer, ledger_window_s=dt)
    return outer * k * batch_size / dt, "examples/sec", extras


HEADLINE_STEPS = 100  # the full-length measurement; shorter runs (fast
# sweep) fork the workload fingerprint and never claim headline records

_STEPS_PER_CALL = None  # CLI override consumed by _train_bench
_EXPLICIT_BATCH = False  # set by main() when --batch-size is given
_MODE = "train"  # "train" | "infer" (--infer): per-model bench fns keep
# their model/batch construction; _train_bench routes to _infer_bench


def _cap(batch_size: int, cap: int) -> int:
    """Clamp the harness-wide default batch (8192) to the model's
    headline config; an EXPLICIT --batch-size is honored as given so
    knob sweeps (e.g. bert_base --batch-size 64) actually run what the
    label says."""
    return batch_size if _EXPLICIT_BATCH else min(batch_size, cap)


def _train_bench(model, loss_fn, make_batch, steps, batch_size, warmup=3,
                 lr=1e-3, amp=None, method="forward", steps_per_call=None,
                 infer_batch=None, aux_loss_fn=None,
                 flops_scale: float = 1.0):
    """Shared harness: jitted value_and_grad+Adam step, timed post-warmup.

    Timing blocks on the FULL output state, not just the loss scalar — the
    device queue can resolve a scalar d2h long before the update chain
    drains, which inflates throughput ~30x.

    ``amp``: dtype policy name (e.g. "mixed_bf16") applied at trace time;
    params/opt state stay fp32 masters. Buffers donate so param/opt updates
    are in-place in HBM. ``steps_per_call`` fuses K update steps into one
    dispatch via lax.scan (identical math — the Trainer.train_steps
    pattern), amortizing the per-dispatch tunnel round trip.
    ``aux_loss_fn(new_buffers) -> scalar`` adds buffer-carried auxiliary
    objectives (the MoE load-balance loss) to the optimized loss.
    """
    import contextlib

    import jax
    import jax.numpy as jnp
    from jax import lax
    import paddle_tpu as pt
    from paddle_tpu.core.dtypes import policy_scope

    from paddle_tpu import optimizer

    if _MODE == "infer":
        # the fused-loss training method needs labels; inference runs the
        # plain forward (real serving materializes the logits). The train
        # batch tuple may carry trailing label args the forward doesn't
        # take — truncate to the forward's positional arity. A model
        # whose label args would ALIAS optional forward params (BERT:
        # nsp_label landing in attention_mask) must pass ``infer_batch``
        # explicitly instead.
        import inspect as _inspect

        infer_method = ("forward" if method.endswith("_loss") else method)
        if infer_batch is None:
            fwd_params = list(_inspect.signature(
                getattr(type(model), infer_method)).parameters.values())[1:]
            n_pos = sum(1 for p in fwd_params
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD))
            infer_batch = lambda bs: make_batch(bs)[:n_pos]
        return _infer_bench(model, infer_batch, steps, batch_size,
                            amp=amp, method=infer_method)

    params = model.named_parameters()
    buffers = model.named_buffers()
    opt = optimizer.Adam(lr)
    state = opt.init(params)
    batch = make_batch(batch_size)
    k = max(1, steps_per_call or _STEPS_PER_CALL or 1)

    def one_step(params, buffers, state, batch):
        scope = policy_scope(amp) if amp else contextlib.nullcontext()

        def loss(p):
            with scope:
                out, new_buf = model.functional_call(
                    p, *batch, buffers=buffers, training=True,
                    method=method)
                l = loss_fn(out, batch)
                if aux_loss_fn is not None:
                    l = l + aux_loss_fn(new_buf)
                return l, new_buf

        (l, new_buf), g = jax.value_and_grad(loss, has_aux=True)(params)
        params, state = opt.apply(params, g, state)
        return params, new_buf, state, l

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, buffers, state, batch):
        if k == 1:
            return one_step(params, buffers, state, batch)

        def body(carry, _):
            p, b, st = carry
            p, b, st, l = one_step(p, b, st, batch)
            return (p, b, st), l

        (params, buffers, state), ls = lax.scan(
            body, (params, buffers, state), None, length=k)
        return params, buffers, state, ls[-1]

    from paddle_tpu.core.profiler import RecordEvent

    # model FLOPs per STEP from XLA's cost model, measured on a k=1
    # probe (lower-only, never executed) and scaled by k explicitly:
    # the cost analysis counts a lax.scan/while BODY ONCE regardless of
    # trip count, so analyzing the fused k-step dispatch under-reports
    # by k (observed on-chip: rn50 spc8 printed 2.8% MFU at a true
    # ~22.7%). ``flops_scale`` is the same correction for bodies the
    # MODEL scans internally (scan_layers -> num_layers). Must happen
    # BEFORE the first call donates these buffers.
    # k == 1: analyze ``step`` itself — its AOT fallback compile is the
    # same program the first dispatch reuses from the cache; a separate
    # donation-free probe jit would pay a second full (remote) compile
    ledger_program = f"bench.{type(model).__name__}.step"
    dispatch_flops = _ledger_flops(
        ledger_program, step if k == 1 else jax.jit(one_step), params,
        buffers, state, batch)
    if dispatch_flops:
        dispatch_flops *= k * flops_scale

    outer = max(1, steps // k)
    for _ in range(warmup):
        params, buffers, state, l = step(params, buffers, state, batch)
    float(l)  # host fetch = the only reliable fence on this backend
    t0 = time.perf_counter()
    for i in range(outer):
        with RecordEvent(f"train_step[{k}]"):  # --profile span per dispatch
            params, buffers, state, l = step(params, buffers, state, batch)
        # fence every few steps: a loss fetch serializes the whole update
        # chain (honest timing) while keeping the dispatch queue shallow;
        # block_until_ready alone does NOT block through the async tunnel
        if i % 4 == 3:
            float(l)
    float(l)
    dt = time.perf_counter() - t0
    extras = {"step_time_ms": round(dt / (outer * k) * 1e3, 3)}
    if dispatch_flops:
        extras["flops_per_sec"] = dispatch_flops * outer / dt
        extras.update(ledger_program=ledger_program,
                      ledger_scale=k * flops_scale,
                      ledger_dispatches=outer, ledger_window_s=dt)
    return outer * k * batch_size / dt, "examples/sec", extras


def _infer_bench(model, make_batch, steps, batch_size, warmup=5, amp=None,
                 method="forward"):
    """Inference harness (reference: the per-model inference latency
    analyzer tests, inference/tests/api/): jitted forward only, no
    grads/optimizer.

    Two numbers, two disciplines:
    - latency_ms_p50/p99: one dispatch at a time, host-fenced per call —
      end-to-end serving latency including the device round trip;
    - value (examples/sec): pipelined dispatches fenced every few calls —
      saturated-server throughput.
    """
    import contextlib

    import jax
    from paddle_tpu.core.dtypes import policy_scope

    params = model.named_parameters()
    buffers = model.named_buffers()
    batch = make_batch(batch_size)

    @jax.jit
    def fwd(params, buffers, batch):
        scope = policy_scope(amp) if amp else contextlib.nullcontext()
        with scope:
            out, _ = model.functional_call(
                params, *batch, buffers=buffers, training=False,
                method=method)
        return out

    def _fence(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        idx = (0,) * getattr(leaf, "ndim", 0)
        float(jax.device_get(leaf[idx] if idx else leaf).real
              if hasattr(leaf, "real") else leaf)

    for _ in range(warmup):
        out = fwd(params, buffers, batch)
    _fence(out)

    # latency: serialize every dispatch
    lats = []
    for _ in range(min(steps, 50)):
        t0 = time.perf_counter()
        out = fwd(params, buffers, batch)
        _fence(out)
        lats.append(time.perf_counter() - t0)
    lats.sort()
    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]

    # throughput: keep the queue full, fence periodically
    t0 = time.perf_counter()
    for i in range(steps):
        out = fwd(params, buffers, batch)
        if i % 8 == 7:
            _fence(out)
    _fence(out)
    dt = time.perf_counter() - t0
    extras = {"latency_ms_p50": round(p50 * 1e3, 3),
              "latency_ms_p99": round(p99 * 1e3, 3),
              "step_time_ms": round(dt / steps * 1e3, 3)}
    return steps * batch_size / dt, "examples/sec", extras


def bench_resnet50(steps: int, batch_size: int, smoke: bool = False,
                   amp=None, layout: str = "NHWC"):
    """BASELINE config 2 (image 224 is the headline; smoke uses 64).
    NHWC is the TPU-native layout default; pass layout=NCHW to compare."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import resnet

    pt.seed(0)
    size = 64 if smoke else 224
    batch_size = _cap(batch_size, 8 if smoke else 128)
    model = resnet.resnet50(num_classes=1000, data_format=layout)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        return (jnp.asarray(rng.normal(size=(bs, 3, size, size))
                            .astype(np.float32)),)

    def loss_fn(logits, batch):
        labels = jnp.zeros((logits.shape[0],), jnp.int32)
        return resnet.loss_fn(logits, labels)

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_bert_base(steps: int, batch_size: int, amp=None,
                    fused_ce: bool = True, remat=False,
                    scan_layers: bool = False):
    """BASELINE config 3: BERT-base MLM pretrain step, seq 128.

    ``fused_ce`` routes the MLM head through the chunked
    linear-cross-entropy (ops/fused_loss.py) so the (B, T, 30k) logits
    tensor never materializes — the HBM-bound hot spot of this config.
    ``remat`` checkpoints each block (False | "full" | "dots" — "dots"
    saves matmul outputs, recomputing only the elementwise tail);
    ``scan_layers`` folds the stack
    into one lax.scan body (forces dropout 0 — noted so numbers stay
    comparable)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import bert as B

    pt.seed(0)
    batch_size = _cap(batch_size, 32)
    cfg = B.BertConfig.base()
    cfg.remat, cfg.scan_layers = bool(remat), scan_layers
    cfg.remat_policy = "dots" if remat == "dots" else None
    if scan_layers:
        cfg.dropout = 0.0  # scan body shares one RNG stream
    model = B.BertForPretraining(cfg)
    rng = np.random.default_rng(0)
    T = 128

    if fused_ce:
        def make_batch(bs):
            ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, T)))
            nsp = jnp.asarray(rng.integers(0, 2, (bs,)))
            return (ids, ids, nsp)  # MLM over every position: predict ids

        def loss_fn(out, batch):
            return out  # forward_fused_loss returns the scalar loss

        return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                            amp=amp, method="forward_fused_loss",
                            infer_batch=lambda bs: make_batch(bs)[:1],
                            flops_scale=(cfg.num_layers
                                         if scan_layers else 1))

    def make_batch(bs):
        return (jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, T))),)

    def loss_fn(out, batch):
        from paddle_tpu.ops import loss as L

        mlm_logits, _ = out  # MLM over every position: predict input ids
        return jnp.mean(L.softmax_with_cross_entropy(mlm_logits, batch[0]))

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_gpt(steps: int, batch_size: int, smoke: bool = False,
              amp=None, seq_len: int = 1024):
    """Decoder-only causal LM (models/gpt.py — RoPE + GQA 12q/4kv +
    SwiGLU, head_dim 64 so the causal flash kernel engages, fused
    linear-CE head): the modern long-context training workload the
    reference era lacks. Next-token loss over random ids; remat per
    block keeps seq 1024 activations in HBM."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import gpt as G

    pt.seed(0)
    batch_size = _cap(batch_size, 2 if smoke else 8)
    cfg = G.GPTConfig.small()
    if smoke:
        cfg.vocab_size, cfg.num_layers = 1024, 2
        seq_len = min(seq_len, 128)
    cfg.max_position = seq_len
    cfg.remat = True
    model = G.GPTForCausalLM(cfg)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        ids = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (bs, seq_len)))
        return (ids,)

    return _train_bench(model, lambda out, batch: out, make_batch,
                        steps, batch_size, amp=amp,
                        method="forward_loss", infer_batch=make_batch)


def bench_bert_moe(steps: int, batch_size: int, amp=None,
                   experts: int = 8):
    """Switch-MoE BERT (green-field config — the reference has no MoE):
    bert_base geometry with each block's FFN replaced by an
    ``experts``-way Switch FFN (top-1, cf 1.25); the optimized loss adds
    0.01 x the per-layer load-balance aux. Single-chip this measures the
    dense dispatch/combine einsum cost; on a mesh the experts shard over
    'ep' (tests/test_moe.py golden HLO)."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import bert as B

    pt.seed(0)
    batch_size = _cap(batch_size, 16)
    cfg = B.BertConfig.base()
    cfg.dropout = 0.0
    cfg.moe_experts = experts
    model = B.BertForPretraining(cfg)
    rng = np.random.default_rng(0)
    T = 128

    def make_batch(bs):
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, T)))
        mlm = jnp.asarray(np.where(
            rng.random((bs, T)) < 0.15,
            rng.integers(0, cfg.vocab_size, (bs, T)), -100))
        nsp = jnp.asarray(rng.integers(0, 2, (bs,)))
        return (ids, mlm, nsp)

    def aux(new_buf):
        return 0.01 * sum(v for k, v in new_buf.items()
                          if k.endswith("ffn.aux_loss"))

    # --infer: only input_ids reaches the forward (mlm/nsp labels would
    # alias token_type_ids/attention_mask — the _train_bench docstring
    # hazard bench_bert_base guards the same way)
    return _train_bench(model, lambda out, batch: out, make_batch, steps,
                        batch_size, amp=amp, method="forward_fused_loss",
                        aux_loss_fn=aux,
                        infer_batch=lambda bs: make_batch(bs)[:1])


def bench_transformer_nmt(steps: int, batch_size: int, amp=None,
                          fused_ce: bool = True):
    """BASELINE config 4: Transformer NMT train step, seq 64. ``fused_ce``
    routes the generator head through the chunked linear-cross-entropy."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as TR

    pt.seed(0)
    batch_size = _cap(batch_size, 64)
    cfg = TR.NMTConfig.base()
    model = TR.TransformerNMT(cfg)
    rng = np.random.default_rng(0)
    T = 64

    if fused_ce:
        def make_batch(bs):
            src = jnp.asarray(rng.integers(3, cfg.src_vocab, (bs, T)))
            tgt = jnp.asarray(rng.integers(3, cfg.tgt_vocab, (bs, T)))
            return (src, tgt, tgt)

        return _train_bench(model, lambda out, batch: out, make_batch,
                            steps, batch_size, amp=amp,
                            method="forward_fused_loss")

    def make_batch(bs):
        src = jnp.asarray(rng.integers(3, cfg.src_vocab, (bs, T)))
        tgt = jnp.asarray(rng.integers(3, cfg.tgt_vocab, (bs, T)))
        return (src, tgt)

    def loss_fn(out, batch):
        logits = out[0] if isinstance(out, tuple) else out
        from paddle_tpu.ops import loss as L

        return jnp.mean(L.softmax_with_cross_entropy(logits, batch[1]))

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_bert_long(steps: int, batch_size: int, amp=None,
                    seq_len: int = 2048, window: int = None):
    """Long-context BERT MLM step at seq 2048 — the SURVEY §5.7
    long-sequence showcase: attention cost is O(T^2), so this is where
    the flash-attention kernel path engages on TPU (T % 128 == 0, head
    dim 64) and remat at block boundaries keeps activations inside HBM.
    Compare against --model bert_base (seq 128) for the scaling story."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import bert as B

    pt.seed(0)
    batch_size = _cap(batch_size, 4)
    cfg = B.BertConfig.base()
    cfg.max_position = seq_len
    cfg.remat = True
    cfg.attn_window = window  # --window: O(T*W) local attention
    model = B.BertForPretraining(cfg)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, seq_len)))
        nsp = jnp.asarray(rng.integers(0, 2, (bs,)))
        return (ids, ids, nsp)

    def loss_fn(out, batch):
        return out  # forward_fused_loss returns the scalar loss

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp, method="forward_fused_loss",
                        infer_batch=lambda bs: make_batch(bs)[:1])


def bench_bert_packed(steps: int, batch_size: int, amp=None,
                      seq_len: int = 128):
    """BERT MLM over PACKED batches (data.bucketing.pack_sequences):
    variable-length documents share fixed (B, T) rows with segment-ids
    attention (the Pallas packed-batch kernel path) and per-segment
    positions — zero padding waste vs the padded bert_base config. Same
    row shape as bert_base, so examples/sec is directly comparable; at
    this config's doc-length distribution (uniform 16..128) packed rows
    carry ~1.6-1.8x the real tokens a padded ragged batch of the same
    documents would."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.data.bucketing import pack_sequences
    from paddle_tpu.models import bert as B

    pt.seed(0)
    batch_size = _cap(batch_size, 32)
    cfg = B.BertConfig.base()
    model = B.BertForPretraining(cfg)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        # documents: lengths 16..seq_len, enough to fill bs rows
        def docs():
            while True:
                n = int(rng.integers(16, seq_len + 1))
                yield rng.integers(3, cfg.vocab_size, n)

        gen = pack_sequences(docs, capacity=seq_len, batch_size=bs)
        batch = next(iter(gen()))
        tokens = jnp.asarray(batch["tokens"])
        return (tokens, jnp.asarray(batch["positions"]),
                jnp.asarray(batch["segment_ids"]), tokens)

    return _train_bench(model, lambda out, batch: out, make_batch, steps,
                        batch_size, amp=amp, method="forward_packed_loss")


def bench_nmt_decode(steps: int, batch_size: int, amp=None,
                     cached: bool = True, max_len: int = 64):
    """Autoregressive decode throughput (tokens/sec) for the NMT
    transformer — the serving-side counterpart of --infer. ``cached``
    uses the per-layer K/V caches (O(T) per step); --no-kv-cache runs
    the full-prefix re-run greedy_decode for the honest comparison
    (identical tokens, pinned by tests)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as TR

    import contextlib

    from paddle_tpu.core.dtypes import policy_scope

    pt.seed(0)
    batch_size = _cap(batch_size, 32)
    cfg = TR.NMTConfig.base()
    model = TR.TransformerNMT(cfg).eval()
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(3, cfg.src_vocab, (batch_size, 64)))

    from paddle_tpu.nn.layer import inject_state

    decode = (model.greedy_decode_cached if cached
              else model.greedy_decode)
    # params ride as jit ARGUMENTS (inject_state): a closure over the
    # model would bake every weight into the program as constants,
    # which the axon relay's remote-compile POST rejects (HTTP 413)
    params = dict(model.named_parameters())

    def _decode(p, s):
        scope = policy_scope(amp) if amp else contextlib.nullcontext()
        with scope, inject_state((model, p)):
            return decode(s, max_len=max_len)

    fn = jax.jit(_decode)

    def _fence(out):
        float(jax.device_get(out[0, 0]))

    for _ in range(2):
        out = fn(params, src)
    _fence(out)
    outer = max(1, steps // 4)
    t0 = time.perf_counter()
    for i in range(outer):
        out = fn(params, src)
        _fence(out)
    dt = time.perf_counter() - t0
    return (outer * batch_size * max_len / dt, "tokens/sec",
            {"step_time_ms": round(dt / outer * 1e3, 3)})


def bench_vit(steps: int, batch_size: int, smoke: bool = False,
              amp=None, layout: str = "NHWC"):
    """ViT-B/16 @224 (models/vit.py — green-field next to the conv zoo;
    ~17.6 GFLOP fwd/img lands almost entirely on the MXU as big
    matmuls): supervised CE over random images. remat per block keeps
    b128 activations in HBM."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import vit as V

    pt.seed(0)
    batch_size = _cap(batch_size, 8 if smoke else 128)
    cfg = V.ViTConfig.tiny() if smoke else V.ViTConfig.base()
    cfg.layout = layout
    cfg.remat = not smoke
    model = V.ViT(cfg)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        if layout == "NHWC":
            shape = (bs, cfg.image_size, cfg.image_size,
                     cfg.num_channels)
        else:
            shape = (bs, cfg.num_channels, cfg.image_size,
                     cfg.image_size)
        return (jnp.asarray(rng.normal(size=shape).astype(np.float32)),)

    def loss_fn(logits, batch):
        labels = jnp.asarray(
            np.arange(logits.shape[0]) % cfg.num_classes)
        return V.loss_fn(logits, labels)

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_gpt_decode(steps: int, batch_size: int, amp=None,
                     max_len: int = 128, gamma: int = 0,
                     weight_only: bool = False, smoke: bool = False):
    """GPT KV-cached decode throughput (tokens/sec, generated positions
    only). Default is greedy decode on the 12-layer small config.
    ``--gamma g`` > 0 switches to speculative decoding against a
    2-layer draft sharing the target's geometry (fresh init): the
    output distribution is the target's regardless of the draft, so
    this measures the MACHINERY cost honestly — the emitted
    accept-per-round extra turns the number into the real speedup
    formula (tokens per target pass = 1 + accepted/round) for any
    better-trained draft pair."""
    import contextlib

    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.dtypes import policy_scope
    from paddle_tpu.models import gpt as G
    from paddle_tpu.models.speculative import speculative_generate

    pt.seed(0)
    batch_size = _cap(batch_size, 2 if smoke else 16)
    cfg = G.GPTConfig.small()
    if smoke:
        cfg.vocab_size, cfg.num_layers = 1024, 2
        max_len = min(max_len, 32)
    cfg.max_position = max_len + max(gamma, 0)
    model = G.GPTForCausalLM(cfg).eval()
    if weight_only:
        # W8A16: halve the weight HBM stream of the bandwidth-bound
        # decode loop (logit accuracy pinned in tests/test_weight_only)
        from paddle_tpu.quant import apply_weight_only_int8

        apply_weight_only_int8(model)
    rng = np.random.default_rng(0)
    prompt_len = min(16, max_len // 2)
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch_size, prompt_len)))

    from paddle_tpu.nn.layer import inject_state

    # params/buffers ride as jit ARGUMENTS (inject_state): closures
    # would bake the weights into the program as constants and the axon
    # relay rejects such remote-compile bodies (HTTP 413). Buffers
    # matter too: --weight-only stores the int8 weights AS buffers.
    tstate = (dict(model.named_parameters()),
              dict(model.named_buffers()))
    if gamma > 0:
        dcfg = dataclasses.replace(cfg, num_layers=2)
        pt.seed(1)
        draft = G.GPTForCausalLM(dcfg).eval()
        dstate = (dict(draft.named_parameters()),
                  dict(draft.named_buffers()))

        def _decode(tp, tb, dp, db, p):
            scope = policy_scope(amp) if amp else contextlib.nullcontext()
            with scope, inject_state((model, tp, tb), (draft, dp, db)):
                return speculative_generate(
                    model, draft, p, max_len, gamma=gamma,
                    temperature=0.0, return_stats=True)

        fn = jax.jit(_decode)
        args = (*tstate, *dstate, prompt)
    else:
        def _decode(tp, tb, p):
            scope = policy_scope(amp) if amp else contextlib.nullcontext()
            with scope, inject_state((model, tp, tb)):
                return model.greedy_decode(p, max_len), None

        fn = jax.jit(_decode)
        args = (*tstate, prompt)

    def _fence(out):
        float(jax.device_get(out[0][0, 0]))

    for _ in range(2):
        out = fn(*args)
    _fence(out)
    outer = max(1, steps // 4)
    t0 = time.perf_counter()
    for i in range(outer):
        out = fn(*args)
        _fence(out)
    dt = time.perf_counter() - t0
    extras = {"step_time_ms": round(dt / outer * 1e3, 3)}
    if gamma > 0:
        stats = jax.device_get(out[1])
        rounds = float(np.mean(stats["rounds"]))
        extras = {"accept_per_round":
                  round(float(np.mean(stats["accepted_drafts"])) /
                        max(rounds, 1.0), 3),
                  "rounds": round(rounds, 1)}
    gen = max_len - prompt_len
    return outer * batch_size * gen / dt, "tokens/sec", extras


def bench_gpt_serve(steps: int, batch_size: int, amp=None,
                    max_new: int = 64, smoke: bool = False,
                    weight_only: bool = False, paged: bool = False,
                    gamma: int = 0, prefill_chunk=None,
                    decode_steps: int = 1, kv_dtype=None):
    """Continuous-batching serving throughput (serving.BatchedDecoder):
    2x``batch_size`` requests with MIXED prompt lengths over a
    ``batch_size``-slot arena — generated tokens/sec across the whole
    workload, admission/refill included (the slot machinery's win over
    pad-to-slowest static batching). --weight-only composes W8A16;
    --gamma g serves SPECULATIVELY (per-row drafts + one per-row verify
    chunk per round, 2-layer draft — accept_per_round extra gives the
    real-pair speedup formula); --prefill-chunk C smooths admission by
    prefilling C tokens per serving tick instead of a whole prompt;
    --kv-dtype int8 serves over the QUANTIZED page pool (implies
    --paged) and additionally measures the serving-DENSITY A/B: max
    concurrent sessions before admission backpressure at ONE page-pool
    HBM budget, fp32 KV vs int8 KV, plus the greedy-decode parity
    agreement (the density acceptance gate's evidence)."""
    import contextlib

    import paddle_tpu as pt
    from paddle_tpu.core.dtypes import policy_scope
    from paddle_tpu.models import gpt as G
    from paddle_tpu.serving import BatchedDecoder

    if kv_dtype is not None:
        paged = True  # quantized KV lives in the page pool
    pt.seed(0)
    slots = _cap(batch_size, 2 if smoke else 8)
    cfg = G.GPTConfig.small()
    if smoke:
        cfg.vocab_size, cfg.num_layers = 1024, 2
        max_new = min(max_new, 8)
    cap = 256 if not smoke else 64
    cfg.max_position = cap
    model = G.GPTForCausalLM(cfg).eval()
    if weight_only:
        from paddle_tpu.quant import apply_weight_only_int8

        apply_weight_only_int8(model)
    rng = np.random.default_rng(0)
    n_req = 2 * slots
    lens = [int(8 + (i * 7) % 24) for i in range(n_req)]  # mixed
    # ONE decoder across warmup + timed runs: its jitted step and
    # prefill-bucket functions cache per-instance, so a fresh decoder
    # per run would re-trace inside the timed loop. --paged serves over
    # the shared page pool (memory ~ live tokens) instead of the
    # slots x capacity arena.
    kw = {}
    if paged:
        kw = dict(pages=max(slots * (cap // 64) // 2, slots),
                  page_size=64)
        if kv_dtype is not None:
            kw["kv_dtype"] = kv_dtype
    if gamma > 0:
        dcfg = dataclasses.replace(cfg, num_layers=2)
        pt.seed(1)
        kw["draft"] = G.GPTForCausalLM(dcfg).eval()
        kw["gamma"] = gamma
    if prefill_chunk:
        kw["prefill_chunk"] = prefill_chunk
    if decode_steps > 1:
        kw["decode_steps"] = decode_steps
    dec = BatchedDecoder(model, slots=slots, capacity=cap, **kw)

    def run_all():
        scope = policy_scope(amp) if amp else contextlib.nullcontext()
        with scope:  # trace-time policy, same contract as gpt_decode
            for n in lens:
                dec.submit(rng.integers(1, cfg.vocab_size, (n,))
                           .astype(np.int32), max_new)
            return dec.run()

    # warmup compiles the step + prefill buckets — with telemetry on
    # for just this run so the serving dispatch sites register their
    # programs in the cost ledger (the serve row's mfu/roofline source)
    from paddle_tpu.telemetry import costs as _tcosts
    from paddle_tpu.telemetry import metrics as _tmetrics

    telem_was_on = _tmetrics.enabled()
    _tmetrics.enable()
    try:
        run_all()
    finally:
        if not telem_was_on:
            _tmetrics.disable()
    step_rec = next((r for name, r in sorted(_tcosts.ledger().items())
                     if name.startswith("serving.step[")), None)
    ticks0, tok0, cap0 = dec.tick_count, dec.tick_tokens, \
        dec.tick_capacity
    outer = max(1, steps // 50)
    t0 = time.perf_counter()
    total = 0
    for _ in range(outer):
        outs = run_all()
        total += sum(len(v) for v in outs.values())
    dt = time.perf_counter() - t0
    extras = {"requests": n_req, "slots": slots,
              "step_time_ms": round(dt / outer * 1e3, 3)}
    # goodput: tokens emitted / slot-token capacity over the timed
    # ticks, from the decoder's unconditional tick counters
    cap_delta = dec.tick_capacity - cap0
    if cap_delta > 0:
        extras["goodput_ratio"] = round(
            (dec.tick_tokens - tok0) / cap_delta, 4)
    if step_rec is not None and step_rec.get("flops"):
        # decode-dispatch FLOPs only (prefill excluded): a lower bound,
        # audited in report_line against the same ledger record
        n_ticks = dec.tick_count - ticks0
        if n_ticks > 0:
            extras["flops_per_sec"] = \
                step_rec["flops"] * n_ticks / dt
            extras.update(ledger_program=step_rec["program"],
                          ledger_dispatches=n_ticks,
                          ledger_window_s=dt)
    if gamma > 0:
        extras["accept_per_round"] = round(
            dec.spec_accepted / max(1, dec.spec_row_rounds), 3)
    if kv_dtype is not None:
        extras["kv_dtype"] = kv_dtype
        extras.update(_kv_serve_density(model, cap, smoke))
        extras.update(_kv_decode_step_time(model, cap, smoke))
    return total / dt, "tokens/sec", extras


def _router_replica_spec(smoke=False, kv_dtype=None, slots=4,
                         seed=0, prefill_chunk=None):
    """Replica model contract for the router bench + worker processes
    (``python -m paddle_tpu.serving_router --worker --spec
    bench:_router_replica_spec``): every replica builds the SAME
    weights (fixed seed), so placement is invisible in the output."""
    import paddle_tpu as pt
    from paddle_tpu.models import gpt as G
    from paddle_tpu.serving import BatchedDecoder

    pt.seed(seed)
    cfg = G.GPTConfig.small()
    cap = 256
    if smoke:
        # 3 layers (not the usual smoke 2): the router A/B's signal is
        # the absolute ms a monolithic long-prompt prefill steals from
        # decode — one extra layer grows that effect past CI timing
        # noise at still-smoke cost
        cfg.vocab_size, cfg.num_layers = 1024, 3
        cap, slots = 128, max(2, slots // 2)
    cfg.max_position = cap
    model = G.GPTForCausalLM(cfg).eval()
    kw = {}
    if prefill_chunk:
        kw["prefill_chunk"] = prefill_chunk
    return BatchedDecoder(
        model, slots=slots, capacity=cap,
        pages=slots * (cap // 64) + 8, page_size=64,
        kv_dtype=kv_dtype, **kw)


def _router_aot_ttfr_ab(spec_kw):
    """TTFR (time-to-first-ready) A/B for the aot compiled-program
    plane: boot the SAME replica twice — once through the ordinary
    trace path (construct model, trace, compile, warm) and once
    trace-free from the serialized artifact the first boot exported —
    and gate ``ttfr_aot_ms < ttfr_traced_ms`` (the artifact exists to
    delete trace+compile from elastic scale-up; if it doesn't, the
    plane is a regression and the bench must say so). The AOT replica
    then serves a real request end-to-end, so the number is a SERVING
    boot, not a load microbenchmark. Artifact export/load failures
    raise :class:`_SkipBench` (skipped row, cause
    ``artifact_load_failed``) — never a fake 0.0 TTFR."""
    import shutil
    import tempfile

    from paddle_tpu import aot
    from paddle_tpu.core.enforce import enforce
    from paddle_tpu.serving_router import LocalReplica

    def boot(mk):
        t0 = time.perf_counter()
        rep = LocalReplica(mk(), name="ttfr").start()
        rep.warmup()
        return rep, (time.perf_counter() - t0) * 1e3

    rep, ttfr_traced = boot(lambda: _router_replica_spec(**spec_kw))
    tmp = tempfile.mkdtemp(prefix="pt-aot-bench-")
    art = os.path.join(tmp, "artifact")
    try:
        try:
            aot.export_decoder(rep.decoder, art)
        except aot.AotError as e:
            raise _SkipBench(f"aot artifact export failed: {e}",
                             cause="artifact_load_failed")
        finally:
            rep.close()

        def load():
            try:
                return aot.load_decoder(art)
            except aot.AotError as e:
                raise _SkipBench(f"aot artifact load failed: {e}",
                                 cause="artifact_load_failed")

        rep2, ttfr_aot = boot(load)
        try:
            # end-to-end through the trace-free replica: the stub
            # booby-traps every trace entry point, so tokens coming
            # back prove the serialized programs served the request
            rid = rep2.submit(np.asarray([1, 2], np.int32), 4)
            deadline = time.time() + 300.0
            done = {}
            while rid not in done and time.time() < deadline:
                done.update(rep2.drain_results())
                time.sleep(0.01)
            enforce(rid in done and len(done[rid]["tokens"]) > 0,
                    "aot-booted replica served no tokens")
            info = getattr(rep2.decoder, "aot_info", {})
        finally:
            rep2.close()
        enforce(ttfr_aot < ttfr_traced,
                "aot cold start (%.0f ms) must beat the traced boot "
                "(%.0f ms) — the artifact plane exists to delete "
                "trace+compile from scale-up", ttfr_aot, ttfr_traced)
        return {"ttfr_traced_ms": round(ttfr_traced, 1),
                "ttfr_aot_ms": round(ttfr_aot, 1),
                "aot_artifact_id": info.get("artifact_id")}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _open_loop(router, prompts, max_new: int, rate_rps: float,
               rng, timeout_s: float = 900.0, stream: bool = False):
    """Seeded Poisson OPEN-loop load: arrivals are exponential gaps at
    ``rate_rps`` independent of completions (the closed-loop bench
    hides queueing collapse; open-loop is how serving studies measure
    TTFT under load). Returns (tickets, wall_s) with wall measured
    submit-of-first to completion-of-last non-shed request.
    ``stream=True`` submits streaming tickets — TTFT is then the
    router-side FIRST-TOKEN stamp, and the client-side inter-token
    gaps land on the tickets via :func:`_drain_streams`."""
    gaps = rng.exponential(1.0 / rate_rps, size=len(prompts))
    arrivals = np.cumsum(gaps)
    t0 = time.perf_counter()
    tickets = []
    for i, p in enumerate(prompts):
        while time.perf_counter() - t0 < arrivals[i]:
            time.sleep(0.0005)
        tickets.append(router.submit(p, max_new, session=f"s{i}",
                                     stream=stream))
    router.wait(tickets, timeout=timeout_s)
    wall = time.perf_counter() - t0
    if stream:
        _drain_streams(tickets)
    return tickets, wall


def _drain_streams(tickets):
    """Read each streamed ticket's client records and REPLACE its
    ``itl_p99_s`` with the CLIENT-side inter-token gap p99 (arrival
    stamps at the router fan-in — the latency a streaming consumer
    actually experiences, network hop included), so ``_arm_stats``
    reports streaming ITL from the same field."""
    for t in tickets:
        if t.shed or t.stream is None:
            continue
        stamps = [r["t"] for r in t.stream
                  if r.get("t") is not None and "i" in r]
        gaps = (np.diff(np.asarray(stamps)) if len(stamps) > 1
                else np.asarray([0.0]))
        t.itl_p99_s = float(np.quantile(gaps, 0.99))


def _arm_stats(tickets, wall_s: float, short_lt=None):
    served = [t for t in tickets if not t.shed]
    ttfts = np.asarray([t.ttft_s for t in served])
    toks = sum(len(t.tokens) for t in served)
    itls = np.asarray([t.itl_p99_s for t in served])
    out = {
        "ttft_p50_ms": round(float(np.quantile(ttfts, 0.5)) * 1e3, 2),
        "ttft_p99_ms": round(float(np.quantile(ttfts, 0.99)) * 1e3, 2),
        "itl_p99_ms": round(float(np.quantile(itls, 0.99)) * 1e3, 2),
        "tokps": round(toks / wall_s, 2),
        "shed_rate": round(1.0 - len(served) / len(tickets), 4),
        "requests": len(tickets),
    }
    if short_lt is not None:
        # the interactive tail: TTFT of SHORT prompts only. A long
        # prompt's own TTFT is prefill-dominated either way; what
        # disaggregation structurally removes is shorts waiting behind
        # someone ELSE's monolithic prefill
        s = np.asarray([t.ttft_s for t in served
                        if len(t.prompt) < short_lt])
        if len(s):
            out["ttft_short_p99_ms"] = round(
                float(np.quantile(s, 0.99)) * 1e3, 2)
            # the gate statistic: a mean over all shorts averages
            # scheduler noise that a 12-sample p99 (= max) cannot
            out["ttft_short_mean_ms"] = round(
                float(s.mean()) * 1e3, 2)
    return out


def _piecewise_open_loop(router, prompts, max_new: int, phases, rng,
                         timeout_s: float = 900.0):
    """:func:`_open_loop` over a piecewise-rate schedule — the
    diurnal/spiky traffic trace the autoscale A/B drives. ``phases``
    is ``[(rate_rps, n_requests), ...]``; arrivals inside each phase
    are seeded-Poisson at that phase's rate, so the whole arrival
    vector is a deterministic function of (rng seed, phases)."""
    gaps = np.concatenate([rng.exponential(1.0 / rate, size=n)
                           for rate, n in phases])
    enforce_n = sum(n for _, n in phases)
    assert enforce_n == len(prompts), (enforce_n, len(prompts))
    arrivals = np.cumsum(gaps)
    t0 = time.perf_counter()
    tickets = []
    for i, p in enumerate(prompts):
        while time.perf_counter() - t0 < arrivals[i]:
            time.sleep(0.0005)
        tickets.append(router.submit(p, max_new, session=f"s{i}"))
    router.wait(tickets, timeout=timeout_s)
    return tickets, time.perf_counter() - t0


def _gray_failure_ab(spec_kw, smoke):
    """The ``--gray-failure`` A/B: the SAME seeded open-loop trace
    against a 3-replica fleet, three arms —

    1. ``clean``: no fault, reliability plane on (the baseline the
       gate compares against);
    2. ``off``: one replica wedged ~10x slow (a seeded
       ``replica.wedge`` delay rule — the in-process SIGSTOP/GC-stall
       stand-in) with NO reliability plane: the counterfactual,
       recorded unasserted — requests keep landing on the gray
       replica and its queue melts the tail;
    3. ``on``: the same wedge with the reliability plane on —
       dispatch-latency EWMA + queue outlier trip the breaker, the
       victim leaves placement, stuck in-flight work hedges to a
       healthy replica.

    Gate (ISSUE 20 acceptance): arm 3's p99 TTFT <= 1.5x arm 1's
    (plus a small absolute slack — an 18-sample p99 is nearly a max
    across separately-timed arms), and the victim was actually
    quarantined. Arm 2 rides along as evidence, never asserted."""
    from paddle_tpu.core.enforce import enforce
    from paddle_tpu.resilience import ReliabilityConfig
    from paddle_tpu.resilience.faults import FaultInjector
    from paddle_tpu.serving_router import LocalReplica, Router

    n_rep = 3
    n_req = 18 if smoke else 36
    max_new = 6 if smoke else 8
    wedge_s = 0.12  # per-tick freeze: ~10x a warm CPU serve tick
    vocab = 1024 if smoke else 50257
    reps = [LocalReplica(_router_replica_spec(**spec_kw),
                         name=f"g{i}").start() for i in range(n_rep)]
    victim = reps[-1].name

    def mk_prompts(n, seed):
        r = np.random.default_rng(seed)
        return [r.integers(1, vocab,
                           (int(8 + (i * 5) % 16),)).astype(np.int32)
                for i in range(n)]

    def drive(rep, rids, timeout_s=600.0):
        deadline = time.time() + timeout_s
        seen = {}
        while time.time() < deadline:
            seen.update(rep.drain_results())
            if all(r in seen for r in rids):
                return seen
            time.sleep(0.01)
        raise TimeoutError(f"replica {rep.name}: warm requests "
                           f"incomplete after {timeout_s}s")

    def rel_cfg():
        # hedging arms after 6 fleet completions (the run is short);
        # the cooldown parks the victim for the whole arm — a mid-run
        # half-open probe against a still-wedged replica would only
        # churn the placement the gate is measuring
        return ReliabilityConfig(hedge_min_samples=6,
                                 quarantine_cooldown_s=600.0)

    try:
        # warm every jit path the load will hit (all prompts pad into
        # the short bucket; max_new covers the step)
        for rep in reps:
            drive(rep, [rep.submit(p, 2)
                        for p in (mk_prompts(1, 99)[0],
                                  np.ones(24, np.int32))])
        # rate calibration: one replica's closed-loop service rate;
        # 0.8x of it across a 3-replica fleet keeps the healthy
        # majority unloaded, so the tail movement IS the gray replica
        cal = mk_prompts(8, 1)
        t0 = time.perf_counter()
        drive(reps[0], [reps[0].submit(p, max_new) for p in cal])
        rate = 0.8 * len(cal) / (time.perf_counter() - t0)

        # arm 1: clean fleet, reliability on
        router = Router(reps, poll_interval_s=0.02,
                        reliability=rel_cfg())
        clean = _arm_stats(*_open_loop(
            router, mk_prompts(n_req, 7), max_new, rate,
            np.random.default_rng(300)))
        router.close()

        # arm 2: wedged victim, NO reliability (the counterfactual)
        with FaultInjector().on("replica.wedge", delay_s=wedge_s,
                                match=victim):
            router = Router(reps, poll_interval_s=0.02)
            off = _arm_stats(*_open_loop(
                router, mk_prompts(n_req, 7), max_new, rate,
                np.random.default_rng(300)))
            router.close()

        # arm 3: the same wedge, reliability on
        with FaultInjector().on("replica.wedge", delay_s=wedge_s,
                                match=victim):
            router = Router(reps, poll_interval_s=0.02,
                            reliability=rel_cfg())
            on_tickets, on_wall = _open_loop(
                router, mk_prompts(n_req, 7), max_new, rate,
                np.random.default_rng(300))
            stats = router.stats()
            router.close()
        on = _arm_stats(on_tickets, on_wall)

        # -- the gates -------------------------------------------------
        enforce(victim in (stats.get("quarantined") or []),
                "the wedged replica %s was never quarantined "
                "(quarantined=%s)", victim, stats.get("quarantined"))
        enforce(on["ttft_p99_ms"]
                <= 1.5 * clean["ttft_p99_ms"] + 250.0,
                "reliability-on p99 TTFT %.1f ms under one wedged "
                "replica blew the clean-arm bound %.1f ms (clean "
                "%.1f ms)", on["ttft_p99_ms"],
                1.5 * clean["ttft_p99_ms"] + 250.0,
                clean["ttft_p99_ms"])
    finally:
        for rep in reps:
            rep.close()

    rel = stats.get("reliability") or {}
    extras = dict(on)
    extras.update({
        "replicas": n_rep,
        "rate_rps": round(rate, 3),
        "gray_wedge_s": wedge_s,
        "gray_clean_ttft_p50_ms": clean["ttft_p50_ms"],
        "gray_clean_ttft_p99_ms": clean["ttft_p99_ms"],
        "gray_clean_itl_p99_ms": clean["itl_p99_ms"],
        "gray_clean_tokps": clean["tokps"],
        # the counterfactual, recorded but never asserted: CPU timing
        # noise must not flake the gate, the blowup speaks for itself
        "gray_off_ttft_p99_ms": off["ttft_p99_ms"],
        "gray_off_itl_p99_ms": off["itl_p99_ms"],
        "gray_off_tokps": off["tokps"],
        "gray_on_ttft_p99_ms": on["ttft_p99_ms"],
        "gray_hedges": rel.get("hedges"),
        "gray_hedge_wins": rel.get("hedge_wins"),
        "gray_quarantines": rel.get("quarantines"),
        "gray_retry_budget": (rel.get("budget") or {}).get("tokens"),
    })
    return extras.pop("tokps"), "tokens/sec", extras


def _autoscale_spike_ab(spec_kw, autoscale, smoke):
    """The ``--autoscale MIN,MAX`` A/B: the SAME seeded spiky trace
    (base rate, a 3x spike, base again) against two fleets —

    1. ``static``: MAX replicas up for the whole run (the
       over-provisioned baseline an autoscaler must justify itself
       against);
    2. ``autoscaled``: MIN replicas + a live :class:`~paddle_tpu.
       autoscale.Scaler` growing the fleet on the spike and draining
       it back on sustained headroom.

    The replicas beyond MIN are pre-built and pre-warmed before the
    timed run — the in-process stand-in for the AOT artifact shelf
    (scale-up without trace+compile; production spawns hit the same
    shape via ``spawn_replicas(..., from_artifact=...)``), so the
    measured TTFR is the artifact-boot analog, not a compile.

    Gates (ISSUE 18 acceptance):

    - strictly fewer replica-seconds than static max over the serving
      window;
    - short-prompt p99 TTFT and p99 ITL within the static arm's
      bounds (a CPU-noise slack factor — a 32-sample p99 is nearly a
      max across two separately-timed arms) and shed no worse;
    - the fleet actually grew (the spike forced at least one scale-up)
      and came back to MIN (sustained headroom drained it);
    - no flap: scale events <= the policy's cooldown-implied ceiling;
    - replaying the recorded signal trace through a fresh policy
      reproduces the live decision list bit-identically."""
    from paddle_tpu.autoscale import AutoscalePolicy, Scaler, replay
    from paddle_tpu.core.enforce import enforce
    from paddle_tpu.serving_router import LocalReplica, Router

    amin, amax = int(autoscale[0]), int(autoscale[1])
    enforce(1 <= amin < amax,
            "--autoscale needs 1 <= MIN < MAX, got %s,%s", amin, amax)
    long_len, max_new = (112, 8) if smoke else (192, 16)
    short_lt = long_len // 2
    vocab = 1024 if smoke else 50257

    def mk_prompts(n, seed):
        # the router bench's mix: every 3rd prompt LONG, so the spike
        # carries prefill weight too, not just decode ticks
        r = np.random.default_rng(seed)
        out = []
        for i in range(n):
            ln = long_len if i % 3 == 2 else int(8 + (i * 5) % 16)
            out.append(r.integers(1, vocab, (ln,)).astype(np.int32))
        return out

    def drive(rep, rids, timeout_s=600.0):
        seen = {}
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            seen.update(rep.drain_results())
            if all(r in seen for r in rids):
                return seen
            time.sleep(0.01)
        raise TimeoutError(f"replica {rep.name}: warm requests "
                           f"incomplete after {timeout_s}s")

    # the whole MAX fleet, pre-warmed (short + long jit paths) BEFORE
    # any timed arm: the static arm uses all of it; the autoscaled arm
    # starts with [:amin] and pops the rest off the "artifact shelf"
    reps = [LocalReplica(_router_replica_spec(**spec_kw),
                         name=f"as{i}").start() for i in range(amax)]
    warm = mk_prompts(2, 99)
    for rep in reps:
        drive(rep, [rep.submit(p, 2)
                    for p in (warm[0], warm[1],
                              np.ones(long_len, np.int32))])
    scaler = None
    try:
        # rate calibration on ONE replica (the autoscaled arm's floor
        # capacity): base load a single replica absorbs with headroom,
        # spike 3x that — beyond one replica, inside MAX
        cal = mk_prompts(8, 1)
        t0 = time.perf_counter()
        drive(reps[0], [reps[0].submit(p, max_new) for p in cal])
        cal_rps = len(cal) / (time.perf_counter() - t0)
        # base at 30% of one replica's closed-loop rate puts the 3x
        # spike at 0.9x aggregate capacity. That ratio is the whole
        # experiment: in-process replicas SHARE the host's compute
        # (one XLA executable already saturates it), so growing the
        # fleet buys decode SLOTS (concurrency -> queue wait), not
        # throughput — a spike above aggregate capacity builds a
        # backlog no fleet size can drain and the A/B would measure
        # queueing collapse, while at 0.9x the MIN fleet is slot-
        # starved (arrivals queue behind 2 busy slots) and the spawns
        # visibly collapse the wait. Production TPU replicas add both
        # axes; the slot axis is the one this host can exhibit.
        base = 0.30 * cal_rps
        spike = 3.0 * base
        n_base = 8 if smoke else 12
        n_spike = 16 if smoke else 24
        phases = [(base, n_base), (spike, n_spike), (base, n_base)]
        n_req = 2 * n_base + n_spike

        # arm A: static max
        router = Router(reps, poll_interval_s=0.02)
        st_tickets, st_wall = _piecewise_open_loop(
            router, mk_prompts(n_req, 11), max_new, phases,
            np.random.default_rng(200))
        router.close()
        static = _arm_stats(st_tickets, st_wall, short_lt=short_lt)
        static_rs = amax * st_wall

        # arm B: autoscaled, same arrival schedule (same seed+phases)
        shelf = list(reps[amin:])
        fresh = iter(range(amax, 1_000_000))

        def spawn():
            if shelf:
                return shelf.pop(0)
            # shelf exhausted (retire_fn repools drained replicas, so
            # only MAX-1 spawns can ever be in flight at once — this
            # is a belt-and-braces path): a real cold boot
            rep = LocalReplica(_router_replica_spec(**spec_kw),
                               name=f"as{next(fresh)}").start()
            reps.append(rep)
            rep.warmup()
            return rep

        router = Router(reps[:amin], poll_interval_s=0.02)
        # proactive up (a 100ms dispatch wait or 1.5x slots of
        # in-flight votes up — real queueing, not the momentary
        # all-slots-busy of two base arrivals overlapping), patient
        # down (Poisson base traffic has multi-second quiet gaps; the
        # headroom hold + down cooldown must outlast them or the
        # scaler drains mid-base and pays a spawn on the next burst);
        # the cooldowns (plus the measured TTFR) bound the event rate
        policy = AutoscalePolicy(
            min_replicas=amin, max_replicas=amax,
            up_queue_wait_s=0.1, up_load=1.5,
            down_queue_wait_s=0.05, down_load=0.5,
            headroom_hold_s=2.5, cooldown_up_s=0.25,
            cooldown_down_s=4.0, ttfr_hint_s=0.25)
        # retired replicas go BACK on the shelf still warm: scale-down
        # destroys the instance, not the artifact it boots from
        scaler = Scaler(router, policy, spawn, interval_s=0.05,
                        retire_fn=shelf.append)
        t_run0 = time.monotonic()
        scaler.start()
        as_tickets, as_wall = _piecewise_open_loop(
            router, mk_prompts(n_req, 11), max_new, phases,
            np.random.default_rng(200))
        serve_end = time.monotonic()
        auto = _arm_stats(as_tickets, as_wall, short_lt=short_lt)
        auto_rs = scaler.replica_seconds(until=serve_end)
        # post-trace idle tail: give sustained headroom room to drain
        # the spike's replicas back to MIN (bounded — the no-flap
        # cooldowns make each down step take hold+cooldown)
        deadline = time.monotonic() + 30.0
        while (time.monotonic() < deadline
               and scaler._live_count() > amin):
            time.sleep(0.05)
        scaler.stop()
        router.close()
        total_wall = time.monotonic() - t_run0

        ups = [e for e in scaler.scale_events()
               if e["event"] == "scale_up"]
        downs = [e for e in scaler.scale_events()
                 if e["event"] == "scale_down"]
        peak = max(n for _, n in scaler.timeline)
        final = scaler.timeline[-1][1]

        # -- the gates -------------------------------------------------
        enforce(len(ups) >= 1 and peak > amin,
                "the 3x spike never forced a scale-up (peak fleet "
                "%s from %s)", peak, amin)
        enforce(len(downs) >= 1 and final == amin,
                "sustained headroom never drained the fleet back to "
                "MIN (final %s, want %s)", final, amin)
        enforce(auto_rs < static_rs,
                "autoscaling must cost strictly fewer replica-seconds "
                "than static max (%.1f vs %.1f)", auto_rs, static_rs)
        # SLO within the static arm's bounds. Two-level, the router
        # bench gate's precedent: the MEAN short TTFT carries the
        # tight bound (a ~20-sample p99 is the max — it always
        # captures the one short that arrived in the spike's onset
        # window before the spawns landed, pure scale-up physics, not
        # a provisioning regression), while the p99 rides with a
        # collapse bound that a fleet stuck at MIN through the spike
        # blows by an order of magnitude
        enforce(auto["ttft_short_mean_ms"]
                <= 1.5 * static["ttft_short_mean_ms"] + 150.0,
                "autoscaled mean short-prompt TTFT %.1f ms blew the "
                "static-max bound %.1f ms",
                auto["ttft_short_mean_ms"],
                static["ttft_short_mean_ms"])
        enforce(auto["ttft_short_p99_ms"]
                <= 2.5 * static["ttft_short_p99_ms"] + 250.0,
                "autoscaled short-prompt p99 TTFT %.1f ms collapsed "
                "vs the static-max bound %.1f ms",
                auto["ttft_short_p99_ms"],
                static["ttft_short_p99_ms"])
        enforce(auto["itl_p99_ms"]
                <= 1.5 * static["itl_p99_ms"] + 100.0,
                "autoscaled p99 ITL %.1f ms blew the static-max "
                "bound %.1f ms", auto["itl_p99_ms"],
                static["itl_p99_ms"])
        enforce(auto["shed_rate"] <= static["shed_rate"] + 0.02,
                "autoscaled shed rate %.3f worse than static %.3f",
                auto["shed_rate"], static["shed_rate"])
        ceiling = policy.max_events(total_wall, scaler.ttfr_s)
        enforce(len(scaler.scale_events()) <= ceiling,
                "flap: %s scale events exceed the cooldown-implied "
                "ceiling %s over %.1fs",
                len(scaler.scale_events()), ceiling, total_wall)
        twin = replay(AutoscalePolicy(**policy.knobs()),
                      scaler.trace.rows)
        enforce(json.dumps(twin, sort_keys=True)
                == json.dumps(scaler.decisions, sort_keys=True),
                "replaying the recorded signal trace diverged from "
                "the live decisions")
    finally:
        if scaler is not None:
            scaler.stop()
        for rep in reps:
            rep.close()

    tl0 = scaler.timeline[0][0]
    extras = dict(auto)
    extras.update({
        "autoscale_min": amin, "autoscale_max": amax,
        "autoscale_peak": int(peak),
        "rate_rps": round(base, 3),
        "spike_rate_rps": round(spike, 3),
        "replica_seconds": round(auto_rs, 2),
        "replica_timeline": [[round(t - tl0, 2), n]
                             for t, n in scaler.timeline],
        "autoscale_scale_ups": len(ups),
        "autoscale_scale_downs": len(downs),
        "autoscale_events_ceiling": int(ceiling),
        "autoscale_ttfr_s": (round(scaler.ttfr_s, 3)
                             if scaler.ttfr_s is not None else None),
        "static_replica_seconds": round(static_rs, 2),
        "static_ttft_p50_ms": static["ttft_p50_ms"],
        "static_ttft_p99_ms": static["ttft_p99_ms"],
        "static_ttft_short_p99_ms": static.get("ttft_short_p99_ms"),
        "static_ttft_short_mean_ms": static.get("ttft_short_mean_ms"),
        "static_itl_p99_ms": static["itl_p99_ms"],
        "static_shed_rate": static["shed_rate"],
        "static_tokps": static["tokps"],
    })
    return extras.pop("tokps"), "tokens/sec", extras


def bench_gpt_router(steps: int, batch_size: int, amp=None,
                     smoke: bool = False, replicas: int = 2,
                     prefill_workers: int = 1, overload: float = 2.0,
                     kv_dtype=None, router_procs: bool = False,
                     stream: bool = False, from_artifact: bool = False,
                     autoscale=None, gray_failure: bool = False):
    """Production-serving A/B (serving_router.Router): a seeded Poisson
    OPEN-loop load with long prompts mixed in, three arms on the same
    replicas —

    1. ``mono``: single replica, monolithic whole-prompt prefill (the
       pre-router baseline: a long admission stalls every decode tick);
    2. headline: ``replicas`` decode replicas behind the router with
       ``prefill_workers`` dedicated prefill workers (long prompts
       prefill OFF the decode loop and hand off KV pages) at the SAME
       offered rate — the p99-TTFT win at equal aggregate tok/s;
    3. ``overload``: the same topology at ``overload``x the rate with
       the SLO shed policy on — p99 TTFT stays bounded (sheds absorb
       the excess) instead of queue collapse.

    The offered rate self-calibrates to 85% of the mono replica's
    closed-loop service rate (high enough that arrivals collide with
    monolithic long-prompt prefills, below mono saturation), so the
    numbers transfer across backends.
    ``--router-procs`` runs the replicas as real worker processes over
    HTTP (the deployment shape); default is in-process replica threads
    (same router code path, deterministic for the gate test)."""
    from paddle_tpu.serving_router import (LocalReplica, Router,
                                           SLOPolicy, spawn_replicas)

    if autoscale is not None:
        # the autoscaling spike A/B is its own workload (piecewise
        # rate, elastic fleet): it replaces the disagg arms entirely
        return _autoscale_spike_ab({"smoke": smoke,
                                    "kv_dtype": kv_dtype},
                                   autoscale, smoke)
    if gray_failure:
        # the gray-failure reliability A/B likewise: one wedged
        # replica, three arms, its own gate
        return _gray_failure_ab({"smoke": smoke,
                                 "kv_dtype": kv_dtype}, smoke)

    n_req = 18 if smoke else max(18, min(steps, 48))
    long_len, max_new = (112, 8) if smoke else (192, 16)
    disagg_min = long_len // 2
    rng = np.random.default_rng(0)
    vocab = 1024 if smoke else 50257
    spec_kw = {"smoke": smoke, "kv_dtype": kv_dtype}
    # the AOT TTFR A/B boots its own pair of replicas BEFORE the fleet
    # spawns (no shared page pools, so neither boot is flattered by a
    # pre-warmed process) and gates ttfr_aot < ttfr_traced
    aot_cols = _router_aot_ttfr_ab(spec_kw) if from_artifact else {}

    def mk_prompts(n, seed):
        # every 3rd prompt is LONG — the mix that makes monolithic
        # admission visibly steal decode ticks (the disagg motivation)
        r = np.random.default_rng(seed)
        out = []
        for i in range(n):
            ln = long_len if i % 3 == 2 else int(8 + (i * 5) % 16)
            out.append(r.integers(1, vocab, (ln,)).astype(np.int32))
        return out

    def drive(rep, rids, timeout_s=600.0):
        # transport-agnostic completion wait: ACCUMULATE drained
        # results locally (HttpReplica's /drain consumes server-side;
        # a keep=True peek only exists on LocalReplica)
        seen = {}
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            seen.update(rep.drain_results())
            if all(r in seen for r in rids):
                return seen
            time.sleep(0.01)
        raise TimeoutError(f"replica {rep.name}: warm/calibration "
                           f"requests incomplete after {timeout_s}s")

    if router_procs:
        spec = "bench:_router_replica_spec"
        reps = spawn_replicas(spec, replicas, spec_kw=spec_kw)
        pfs = spawn_replicas(spec, prefill_workers, role="prefill",
                             spec_kw=spec_kw) if prefill_workers else []
    else:
        reps = [LocalReplica(_router_replica_spec(**spec_kw),
                             name=f"r{i}").start()
                for i in range(replicas)]
        pfs = [LocalReplica(_router_replica_spec(**spec_kw),
                            name=f"pf{i}")
               for i in range(prefill_workers)]
        # warm every jit path the load will hit (short + long prompt
        # buckets, the serving step, the prefill worker's long bucket)
        warm = mk_prompts(2, 99)
        for rep in reps:
            drive(rep, [rep.submit(p, 2)
                        for p in (warm[0], warm[1],
                                  np.ones(long_len, np.int32))])
        for pw in pfs:
            pw.decoder.prefill_export(np.ones(long_len, np.int32))
            pw.decoder._warmed = True
        if pfs:
            # one full disagg round trip per decode replica: compiles
            # the page-import executables so the first TIMED handoff
            # isn't a cold trace
            h = pfs[0].prefill(np.ones(long_len, np.int32))
            for rep in reps:
                drive(rep, [rep.inject(h, 2)])
    try:
        # rate calibration: closed-loop service rate of ONE replica
        cal = mk_prompts(8, 1)
        t0 = time.perf_counter()
        drive(reps[0], [reps[0].submit(p, max_new) for p in cal])
        cal_rps = len(cal) / (time.perf_counter() - t0)
        # 85% of the MONO closed-loop service rate: high enough that
        # arrivals collide with monolithic long-prompt prefills (the
        # tail the router exists to fix), below mono saturation so the
        # baseline arm still drains
        rate = 0.85 * cal_rps

        # arms 1+2 (+ the streaming arm) INTERLEAVED in alternating
        # blocks over the same replicas: every arm samples the same
        # machine-load epochs, so slow background drift between
        # sequentially-timed arms can't masquerade as (or mask) the
        # disaggregation/streaming effect
        mono_router = Router(reps[:1], poll_interval_s=0.02)
        head_router = Router(reps, prefill_workers=pfs,
                             disagg_min_tokens=disagg_min,
                             poll_interval_s=0.02)
        cycle = (("mono", "head", "stream") * 2 if stream
                 else ("mono", "head", "mono", "head"))
        n_arms = len(set(cycle))
        arm_tickets = {a: [] for a in set(cycle)}
        arm_wall = {a: 0.0 for a in set(cycle)}
        half = max(6, n_req // 2)
        for b, arm in enumerate(cycle):
            router = mono_router if arm == "mono" else head_router
            # prompt seed advances per ROUND (b // n_arms), so every
            # arm samples the IDENTICAL prompt sets — a seed-dependent
            # long-prompt skew can't masquerade as an arm effect
            tickets, wall = _open_loop(
                router, mk_prompts(half, 10 + b // n_arms), max_new,
                rate, np.random.default_rng(100 + b),
                stream=(arm == "stream"))
            arm_tickets[arm].extend(tickets)
            arm_wall[arm] += wall
        mono = _arm_stats(arm_tickets["mono"], arm_wall["mono"],
                          short_lt=disagg_min)
        head = _arm_stats(arm_tickets["head"], arm_wall["head"],
                          short_lt=disagg_min)
        stream_arm = (_arm_stats(arm_tickets["stream"],
                                 arm_wall["stream"],
                                 short_lt=disagg_min)
                      if stream else None)
        mono_router.close()
        head_router.close()

        # arm 3: overload with the SLO shed policy. The overload rate
        # anchors on the CLOSED-LOOP service rate (saturation), not the
        # 70% offered rate — "2x overload" must actually exceed
        # capacity or no queue ever builds; the arm runs 2x as many
        # requests so the queue demonstrably grows without the policy
        router = Router(reps, prefill_workers=pfs,
                        disagg_min_tokens=disagg_min,
                        policy=SLOPolicy(degrade_at=1.0, shed_at=1.5),
                        poll_interval_s=0.02)
        over = _arm_stats(*_open_loop(router, mk_prompts(2 * n_req, 3),
                                      max_new, overload * cal_rps,
                                      rng))
        router.close()
    finally:
        for rep in reps + pfs:
            rep.close()
    extras = dict(head)
    extras.update({
        "replicas": replicas, "prefill_workers": prefill_workers,
        "rate_rps": round(rate, 3),
        "mono_ttft_p50_ms": mono["ttft_p50_ms"],
        "mono_ttft_p99_ms": mono["ttft_p99_ms"],
        "mono_ttft_short_p99_ms": mono.get("ttft_short_p99_ms"),
        "mono_ttft_short_mean_ms": mono.get("ttft_short_mean_ms"),
        "mono_itl_p99_ms": mono["itl_p99_ms"],
        "mono_tokps": mono["tokps"],
        "overload_ttft_p99_ms": over["ttft_p99_ms"],
        "overload_shed_rate": over["shed_rate"],
        "overload_tokps": over["tokps"],
        # provisioning-cost accounting on EVERY router row (the
        # autoscale A/B's comparison substrate): a static fleet's
        # replica-seconds are just count x wall, and its timeline one
        # flat change-point — same columns, same meaning, as the
        # elastic rows
        "replica_seconds": round(replicas * arm_wall["head"], 2),
        "replica_timeline": [[0.0, replicas]],
        "mono_replica_seconds": round(arm_wall["mono"], 2),
        "mono_replica_timeline": [[0.0, 1]],
    })
    extras.update(aot_cols)
    if stream_arm is not None:
        # the streaming arm, one column family apart: TTFT here is the
        # router-side FIRST-TOKEN stamp and ITL the client-side
        # inter-token gaps (_drain_streams) — same load, same replicas
        extras.update({
            "stream_ttft_p50_ms": stream_arm["ttft_p50_ms"],
            "stream_ttft_p99_ms": stream_arm["ttft_p99_ms"],
            "stream_ttft_short_mean_ms":
                stream_arm.get("ttft_short_mean_ms"),
            "stream_itl_p99_ms": stream_arm["itl_p99_ms"],
            "stream_tokps": stream_arm["tokps"],
        })
        # shared-system-prompt routing A/B (in-process by design: the
        # signal is the ROUTING logic's hit rate, counter-verified
        # from pool stats, not a transport latency)
        extras.update(_prefix_routing_ab())
    return extras.pop("tokps"), "tokens/sec", extras


def _prefix_routing_ab(seed: int = 0, n_req: int = 12):
    """Shared-system-prompt routing A/B: the SAME workload (two
    64-token system prompts, each carried by several requests) against
    prefix-hash routing vs session-only affinity, over 2 fresh
    prefix-cache replicas per arm. The reported hit rates are
    COUNTER-VERIFIED from the replicas' own pool stats
    (``decoder.prefix_hits`` / ``prefix_lookups``), never inferred
    from routing decisions.

    Determinism: the session arm pre-pins its sessions with a blocking
    wave of 2 x slots unique requests (slot caps force an exact split
    — the best session-only routing can do), and every session serves
    BOTH system prompts over the run, so ANY 2/2 session split makes
    both replicas prefill both prefixes: misses = 2 per prefix. The
    hash arm's fresh-session requests follow the prefix home: misses
    = 1 per prefix. Strictly higher hit rate, by construction."""
    import paddle_tpu as pt
    from paddle_tpu.models import gpt as G
    from paddle_tpu.serving import BatchedDecoder
    from paddle_tpu.serving_router import LocalReplica, Router

    rng = np.random.default_rng(seed)
    sys_prompts = [rng.integers(1, 500, (64,)).astype(np.int32)
                   for _ in range(2)]
    suffixes = [rng.integers(1, 500, (8,)).astype(np.int32)
                for _ in range(n_req)]
    seeds_p = [rng.integers(1, 500, (8,)).astype(np.int32)
               for _ in range(4)]
    # every session meets every prefix: (session i%4, prefix pattern
    # that rotates) — see docstring
    pattern = [(i % 4, (i + i // 4) % 2) for i in range(n_req)]

    def mk_replicas():
        reps = []
        for i in range(2):
            pt.seed(0)
            m = G.GPTForCausalLM(G.GPTConfig.tiny()).eval()
            d = BatchedDecoder(m, slots=2, capacity=192, pages=24,
                               page_size=64, prefix_cache=True)
            reps.append(LocalReplica(d, name=f"p{i}").start())
        for rep in reps:
            rep.warmup()
        return reps

    out = {}
    for arm, pht in (("hash", 64), ("session", None)):
        reps = mk_replicas()
        router = Router(reps, poll_interval_s=0.02,
                        prefix_hash_tokens=pht,
                        disagg_min_tokens=None)
        try:
            if arm == "session":
                seeds = [router.submit(seeds_p[j], 2, session=f"s{j}")
                         for j in range(4)]
                router.wait(seeds, timeout=300)
            base_h = sum(r.decoder.prefix_hits for r in reps)
            base_l = sum(r.decoder.prefix_lookups for r in reps)
            for i, (sess_i, pfx_i) in enumerate(pattern):
                p = np.concatenate([sys_prompts[pfx_i], suffixes[i]])
                sess = (f"s{sess_i}" if arm == "session"
                        else f"fresh{i}")
                # sequential on purpose: the measured quantity is the
                # hit RATE, and concurrent same-prefix admissions
                # can't hit a registry that fills at completion
                router.submit(p, 4, session=sess).wait(300)
            hits = sum(r.decoder.prefix_hits for r in reps) - base_h
            lookups = (sum(r.decoder.prefix_lookups for r in reps)
                       - base_l)
            out[f"prefix_hits_{arm}"] = int(hits)
            out[f"prefix_lookups_{arm}"] = int(lookups)
            out[f"prefix_hit_rate_{arm}"] = round(
                hits / max(1, lookups), 4)
        finally:
            router.close()
            for rep in reps:
                rep.close()
    return out


def _kv_serve_density(model, cap: int, smoke: bool):
    """The serving-density A/B behind ``--kv-dtype int8``: at ONE
    page-pool HBM budget (what ``base_pages`` fp32 pages cost), how
    many concurrent sessions does each KV storage form admit before
    the pool backpressures? Sessions are real admissions (one page
    each), counted after a single admission pass with slots sized off
    the critical path — pages are the binding resource, exactly the
    production regime (KV HBM sets the per-chip session ceiling). Both
    arms then serve the SAME prompts to completion greedily; the
    agreement of rid-matched outputs is the parity evidence (near-tie
    argmax flips compound on an untrained model, so first-half
    agreement is the gate — the same contract the spec-decode bench
    uses)."""
    from paddle_tpu.serving import BatchedDecoder, PagedKVPool

    attn0 = model.blocks[0].self_attn
    nblk = len(model.blocks)
    ps = 64

    def per_page(kvd):
        return PagedKVPool(1, ps, attn0.num_kv_heads, attn0.head_dim,
                           arrays=False, kv_dtype=kvd).pool_nbytes

    base_pages = 8 if smoke else 24
    budget = base_pages * 2 * nblk * per_page(None)
    pages = {kvd: int(budget // (2 * nblk * per_page(kvd)))
             for kvd in (None, "int8")}
    # enough submissions that BOTH arms hit pool backpressure
    n_req = pages["int8"] + 2
    rng = np.random.default_rng(7)
    vocab = model.cfg.vocab_size
    plen, mnew = 24, 8
    prompts = [rng.integers(1, vocab, (plen,)).astype(np.int32)
               for _ in range(n_req)]
    out = {"kv_page_bytes_fp32": per_page(None),
           "kv_page_bytes_int8": per_page("int8"),
           "kv_pool_budget_bytes": int(budget)}
    outs_by_arm = {}
    for kvd in (None, "int8"):
        dec = BatchedDecoder(model, slots=n_req, capacity=cap,
                             pages=pages[kvd], page_size=ps,
                             kv_dtype=kvd)
        rids = [dec.submit(p, mnew) for p in prompts]
        dec._admit()  # ONE admission wave: pages bind, slots don't
        admitted = sum(o is not None for o in dec.owner)
        out[f"max_sessions_{kvd or 'fp32'}"] = int(admitted)
        served = dec.run()
        outs_by_arm[kvd] = [served[r] for r in rids]
    if out["max_sessions_fp32"]:
        out["session_ratio"] = round(
            out["max_sessions_int8"] / out["max_sessions_fp32"], 3)
    agree = [float((a == b).mean()) for a, b in
             zip(outs_by_arm[None], outs_by_arm["int8"])]
    half = [float((a[:len(a) // 2] == b[:len(b) // 2]).mean())
            for a, b in zip(outs_by_arm[None], outs_by_arm["int8"])]
    out["kv_parity_agree"] = round(sum(agree) / len(agree), 3)
    out["kv_parity_gate"] = bool(sum(half) / len(half) >= 0.9)
    return out


def _kv_decode_step_time(model, cap: int, smoke: bool):
    """The decode-step-time A/B behind ``--kv-dtype int8`` (ISSUE 15
    column): one jitted paged-attend step at the SAME batch over
    identical live caches, fp32 storage vs int8 storage. On a real
    chip the int8 arm rides the Pallas dequant-epilogue kernel (int8
    HBM blocks, in-VMEM dequant) and the gate is parity-or-better; on
    the CPU backend both arms take the gather path, so the columns are
    recorded but the gate stays unjudged (``None`` — degraded-bench
    honesty, same contract as the rest of the r06 rows)."""
    import time as _t

    import jax
    import jax.numpy as jnp
    from paddle_tpu.serving import PagedKVPool

    attn0 = model.blocks[0].self_attn
    kvh, hd = attn0.num_kv_heads, attn0.head_dim
    nh = attn0.num_heads
    ps = 64
    bsz = 2 if smoke else 8
    nlog = cap // ps
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.normal(size=(bsz, 1, nh, hd)).astype(np.float32))
    t_rows = jnp.asarray([cap // 2 + (i % ps) for i in range(bsz)],
                         jnp.int32)
    out = {}
    for kvd in (None, "int8"):
        pool = PagedKVPool(pages=bsz * nlog, page_size=ps, kv_heads=kvh,
                           head_dim=hd, kv_dtype=kvd)
        table = jnp.asarray(np.stack([pool.alloc(nlog)
                                      for _ in range(bsz)]))
        kp, vp = pool.kpool, pool.vpool
        for i in range(bsz):
            n = int(t_rows[i]) + 1
            kc = jnp.asarray(rng.normal(size=(1, n, kvh, hd))
                             .astype(np.float32))
            vc = jnp.asarray(rng.normal(size=(1, n, kvh, hd))
                             .astype(np.float32))
            kp, vp = PagedKVPool.write_chunk(kp, vp, table[i], 0, kc,
                                             vc, ps)
        fn = jax.jit(lambda q, kp, vp, t: PagedKVPool.attend(
            q, kp, vp, table, t))
        jax.block_until_ready(fn(q, kp, vp, t_rows))   # compile
        iters = 3 if smoke else 10
        t0 = _t.perf_counter()
        for _ in range(iters):
            o = fn(q, kp, vp, t_rows)
        jax.block_until_ready(o)
        out[f"kv_decode_step_ms_{kvd or 'fp32'}"] = round(
            (_t.perf_counter() - t0) / iters * 1e3, 3)
    ratio = (out["kv_decode_step_ms_int8"]
             / max(out["kv_decode_step_ms_fp32"], 1e-9))
    out["kv_decode_step_ratio"] = round(ratio, 3)
    out["kv_decode_gate"] = (bool(ratio <= 1.05)
                             if jax.default_backend() in ("tpu", "axon")
                             else None)
    return out


def _parse_plan_arg(plan: str) -> dict:
    """'ep=8' / 'dp=2,ep=4' -> {'dp': int, 'ep': int} (argument misuse
    raises ValueError; main() turns it into the value-0.0 error line)."""
    axes = {"dp": 1, "ep": 1}
    for part in str(plan).split(","):
        k, sep, v = part.partition("=")
        k, v = k.strip(), v.strip()
        if k not in axes or not sep or not v.isdigit() or int(v) < 1:
            raise ValueError(
                f"--plan expects 'ep=N' or 'dp=M,ep=N' with N>=1, "
                f"got {plan!r}")
        axes[k] = int(v)
    return axes


def _bench_deepfm_sparse_ep(steps, batch_size, amp, vocab, plan_arg):
    """The ep-sharded arm of deepfm_sparse: the full sharded-embedding
    vertical slice under ``Plan(dp=M, ep=N, tables=[...])`` —

    - tables row-sharded over the ``ep`` mesh axis, trained through
      ``embedding.sparse_ep_minimize_fn`` (local MergeAdd + int8
      (ids, rows) exchange; the dense (V, D) gradient never exists) and
      compiled once through ``parallel.compile_step``;
    - the byte-budget gate (the PR-6 evidence shape): the REPLICATED
      table footprint must exceed the per-device budget while the
      ep-sharded footprint fits — the table provably cannot fit one
      device, only the plan can hold it;
    - wire accounting: per-step sparse payload bytes (counter-verified
      via ``record_exchange_bytes``) next to the dense-allreduce
      counterfactual over the same device count;
    - the host-backed feeding plane: a ``HostBackedTable`` mirror of
      the big table rides ``DevicePrefetcher(prefetch_rows=...)`` so
      each batch's rows stage host->chip overlapped with compute;
      extras report its cache hit rate on the (skewed) id stream.
    """
    import contextlib

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.core.dtypes import policy_scope
    from paddle_tpu.data import DevicePrefetcher
    from paddle_tpu.embedding import (HostBackedTable, dense_grad_bytes,
                                      exchange_payload_bytes,
                                      record_exchange_bytes,
                                      should_compress,
                                      sparse_ep_minimize_fn)
    from paddle_tpu.models import deepfm as DF
    from paddle_tpu.parallel.plan import Plan, compile_step

    axes = _parse_plan_arg(plan_arg)
    dp, ep = axes["dp"], axes["ep"]
    need = dp * ep
    n_dev = len(jax.devices())
    if n_dev < need:  # main() pre-checks; defensive for direct callers
        raise RuntimeError(f"--plan {plan_arg} needs {need} devices, "
                           f"have {n_dev}")

    pt.seed(0)
    vocab = max(ep, vocab - vocab % ep)   # ep must divide the rows
    batch_size = max(dp, batch_size - batch_size % dp)
    cfg = DF.DeepFMConfig(total_vocab=vocab, num_fields=26, dense_dim=13,
                          embed_dim=16, embedding_axis=None,
                          sparse_grads=True)
    model = DF.DeepFM(cfg)
    params = model.named_parameters()
    plan = Plan(dp=dp, ep=ep,
                tables=[r"(embedding|linear_embed)\.weight$"],
                devices=jax.devices()[:need])
    table_names = sorted(n for n in params if plan.is_table(n))
    assert table_names, "no table matched the ep registration"

    # --- byte-budget gate (PR-6 evidence shape): replicated tables
    # exceed the per-device budget, the ep-sharded form fits ----------
    replicated = sum(int(np.prod(params[n].shape)) * 4
                     for n in table_names)
    planned = sum(-(-int(params[n].shape[0]) // ep)
                  * int(np.prod(params[n].shape[1:])) * 4
                  for n in table_names)
    budget = replicated // 2
    assert planned <= budget < replicated, (
        f"byte-budget gate: planned {planned} must fit budget {budget} "
        f"< replicated {replicated} (raise --vocab or ep)")

    placed = plan.place(params)

    def forward_loss(p, ids, dense):
        with (policy_scope(amp) if amp else contextlib.nullcontext()):
            logits, _ = model.functional_call(p, ids, dense)
            labels = (ids[:, 0] % 2).astype(jnp.float32)
            return DF.loss_fn(logits, labels)

    opt = optimizer.Adam(1e-3)
    init_fn, step_fn = sparse_ep_minimize_fn(model, forward_loss, opt,
                                             plan=plan)
    state = init_fn(placed)
    rep = NamedSharding(plan.mesh, P())
    s_sh = jax.tree_util.tree_map(
        lambda x: (NamedSharding(plan.mesh, P("ep", None))
                   if getattr(x, "ndim", 0) >= 1 and x.shape[0] == vocab
                   else rep), state)
    state = jax.tree_util.tree_map(jax.device_put, state, s_sh)
    p_sh = jax.tree_util.tree_map(lambda x: x.sharding, placed)
    bs = plan.batch_sharding()
    step = compile_step(plan, step_fn, in_shardings=(p_sh, s_sh, bs, bs),
                        out_shardings=(rep, p_sh, s_sh))

    # --- host-backed feeding plane: the big table's HostBackedTable
    # mirror stages each batch's rows host->chip from the prefetcher's
    # background thread (parameter_prefetch overlap, no PS fleet) ------
    cap = max(64, vocab // 16)
    host_tbl = HostBackedTable.from_array(placed[table_names[0]],
                                          capacity=cap,
                                          name="deepfm.embedding")
    rng = np.random.default_rng(0)
    total = steps + 3  # timed steps + warmup

    def batches():
        for _ in range(total):
            # power-law id skew (CTR traffic shape): the hot head makes
            # the working set meaningful — a uniform stream at V >> cap
            # would measure only cold misses
            ids = np.minimum(
                vocab * rng.random((batch_size, cfg.num_fields)) ** 8,
                vocab - 1).astype(np.int32)
            dense = rng.normal(
                size=(batch_size, cfg.dense_dim)).astype(np.float32)
            yield {"ids": ids, "dense": dense}

    pref = DevicePrefetcher(
        batches, size=2, sharding=bs,
        prefetch_rows=lambda b: host_tbl.prefetch(b["ids"]))

    # --- wire accounting (static shapes -> computed once per step) ----
    n_ids = batch_size * cfg.num_fields  # global ids per step
    payload = 0
    for n in table_names:
        dim = int(params[n].shape[1])
        comp = should_compress(n_ids, dp, dim)
        payload += exchange_payload_bytes(n_ids // dp, dim, dp,
                                          compressed=comp)
    # the counterfactual: dense (V, D) fp32 table-grad allreduce over
    # the SAME device count (what a replicated-table dp=need run moves)
    dense_cf = sum(dense_grad_bytes(vocab, int(params[n].shape[1]), need)
                   for n in table_names)

    it = iter(pref)
    for _ in range(3):
        b = next(it)
        loss, placed, state = step(placed, state, b["ids"], b["dense"])
    float(loss)
    t0 = time.perf_counter()
    done = 0
    for b in it:
        loss, placed, state = step(placed, state, b["ids"], b["dense"])
        for n in table_names:
            dim = int(params[n].shape[1])
            record_exchange_bytes(
                n_ids // dp, dim, dp,
                compressed=should_compress(n_ids, dp, dim))
        done += 1
        if done % 4 == 3:
            float(loss)
    float(loss)
    dt = time.perf_counter() - t0
    assert done == steps, f"prefetcher delivered {done}/{steps} batches"

    extras = {
        "step_time_ms": round(dt / steps * 1e3, 3),
        "emb_rows_per_sec": round(steps * n_ids / dt, 1),
        "emb_payload_bytes_per_step": int(payload),
        "emb_dense_grad_bytes_per_step": int(dense_cf),
        "emb_bytes_ratio": (round(dense_cf / payload, 1)
                            if payload else None),
        "emb_cache_hit_rate": round(host_tbl.hit_rate, 4),
        "emb_cache_capacity_rows": int(cap),
        "emb_table_rows": int(vocab),
        "peak_mem_bytes_replicated": int(replicated),
        "peak_mem_bytes_planned": int(planned),
        "byte_budget": int(budget),
        "fits_budget_only_planned": True,  # asserted above
        "shard_ratio": round(replicated / planned, 3),
        "dp": dp,
        "emb_ep": ep,
    }
    return steps * batch_size / dt, "examples/sec", extras


def bench_deepfm_sparse(steps: int, batch_size: int, amp=None,
                        vocab: int = 100_000, plan=None):
    """DeepFM with ROW-SPARSE embedding updates (the SelectedRows
    capability, reference: operators/optimizers/adam_op.h sparse branch):
    the optimizer touches O(batch x fields) table rows per step instead
    of O(vocab). Run next to --model deepfm (dense updates) — the gap IS
    the sparse-update win, and it widens with total_vocab (``--vocab``
    sweeps the crossover; on-chip at V=100k dense wins, BASELINE.md).

    ``--plan ep=8`` (or ``dp=2,ep=4``) switches to the ep-sharded arm:
    tables row-sharded over the plan mesh, sparse (ids, rows) gradient
    exchange, host-backed row prefetch, and the byte-budget gate — see
    :func:`_bench_deepfm_sparse_ep`."""
    if plan:
        return _bench_deepfm_sparse_ep(steps, batch_size, amp, vocab,
                                       plan)
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models import deepfm as DF
    from paddle_tpu.optimizer.sparse import sparse_minimize_fn

    pt.seed(0)
    cfg = DF.DeepFMConfig(total_vocab=vocab, num_fields=26, dense_dim=13,
                          embed_dim=16, embedding_axis=None,
                          sparse_grads=True)
    model = DF.DeepFM(cfg)
    params = model.named_parameters()
    rng = np.random.default_rng(0)

    import contextlib

    from paddle_tpu.core.dtypes import policy_scope

    def forward_loss(p, ids, dense):
        # honor --amp exactly like _train_bench, so the dense-vs-sparse
        # comparison isolates the update path, not the dtype policy
        with (policy_scope(amp) if amp else contextlib.nullcontext()):
            logits, _ = model.functional_call(p, ids, dense)
            labels = (ids[:, 0] % 2).astype(jnp.float32)
            return DF.loss_fn(logits, labels)

    opt = optimizer.Adam(1e-3)
    init_fn, step_fn = sparse_minimize_fn(model, forward_loss, opt)
    state = init_fn(params)
    ids = jnp.asarray(rng.integers(0, cfg.total_vocab,
                                   (batch_size, cfg.num_fields)))
    dense = jnp.asarray(rng.normal(size=(batch_size, cfg.dense_dim))
                        .astype(np.float32))
    k = max(1, _STEPS_PER_CALL or 1)  # honor --steps-per-call

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, state, ids, dense):
        if k == 1:
            return step_fn(params, state, ids, dense)

        def body(carry, _):
            p, s = carry
            l, p, s = step_fn(p, s, ids, dense)
            return (p, s), l

        (params, state), ls = jax.lax.scan(body, (params, state), None,
                                           length=k)
        return ls[-1], params, state

    from paddle_tpu.core.profiler import RecordEvent

    dispatch_flops = _ledger_flops("bench.deepfm_sparse.step", step,
                                   params, state, ids, dense)
    for _ in range(3):
        loss, params, state = step(params, state, ids, dense)
    float(loss)
    outer = max(1, steps // k)
    t0 = time.perf_counter()
    for i in range(outer):
        with RecordEvent(f"train_step[{k}]"):
            loss, params, state = step(params, state, ids, dense)
        if i % 4 == 3:
            float(loss)
    float(loss)
    dt = time.perf_counter() - t0
    extras = {"step_time_ms": round(dt / (outer * k) * 1e3, 3)}
    if dispatch_flops:
        extras["flops_per_sec"] = dispatch_flops * outer / dt
        extras.update(ledger_program="bench.deepfm_sparse.step",
                      ledger_dispatches=outer, ledger_window_s=dt)
    return outer * k * batch_size / dt, "examples/sec", extras


def bench_deepfm(steps: int, batch_size: int, amp=None,
                 vocab: int = 100_000):
    """BASELINE config 5: DeepFM sparse CTR step (dense-gradient
    updates; ``--vocab`` scales the table for the sparse crossover)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import deepfm as DF

    pt.seed(0)
    cfg = DF.DeepFMConfig(total_vocab=vocab, num_fields=26, dense_dim=13,
                          embed_dim=16, embedding_axis=None)
    model = DF.DeepFM(cfg)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        ids = jnp.asarray(rng.integers(0, cfg.total_vocab,
                                       (bs, cfg.num_fields)))
        dense = jnp.asarray(rng.normal(size=(bs, cfg.dense_dim))
                            .astype(np.float32))
        return (ids, dense)

    def loss_fn(logits, batch):
        labels = (batch[0][:, 0] % 2).astype(jnp.float32)
        return DF.loss_fn(logits, labels)

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_stacked_lstm(steps: int, batch_size: int, amp=None,
                       scan_unroll: int = 1):
    """Bench model 6: stacked dynamic LSTM sentiment (reference:
    benchmark/fluid/models/stacked_dynamic_lstm.py), seq 100.
    ``--scan-unroll K`` unrolls the time recurrence K steps per compiled
    loop body (identical math) — the r3 3.1%-MFU diagnosis was
    batch-starved AND scan-overhead-bound; sweep with --batch-size."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import stacked_lstm as S

    pt.seed(0)
    batch_size = _cap(batch_size, 64)
    model = S.StackedLSTM(vocab_size=5149, embed_dim=512, hidden_dim=512,
                          num_layers=3, scan_unroll=scan_unroll)
    rng = np.random.default_rng(0)
    T = 100

    def make_batch(bs):
        ids = jnp.asarray(rng.integers(0, 5149, (bs, T)))
        lengths = jnp.asarray(rng.integers(T // 2, T + 1, (bs,)))
        return (ids, lengths)

    def loss_fn(logits, batch):
        labels = (batch[0][:, 0] % 2).astype(jnp.int32)
        return S.loss_fn(logits, labels)

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_vgg16(steps: int, batch_size: int, smoke: bool = False, amp=None):
    """Bench model: vgg (reference benchmark/fluid/models/vgg.py)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import vgg as V

    pt.seed(0)
    size = 224  # vgg's classifier is fixed to 7x7 feature maps
    batch_size = _cap(batch_size, 2 if smoke else 64)
    model = V.vgg16(num_classes=1000) if hasattr(V, "vgg16") else V.VGG16()
    rng = np.random.default_rng(0)

    def make_batch(bs):
        return (jnp.asarray(rng.normal(size=(bs, 3, size, size))
                            .astype(np.float32)),)

    def loss_fn(logits, batch):
        from paddle_tpu.ops import loss as L

        labels = jnp.zeros((logits.shape[0],), jnp.int32)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_se_resnext50(steps: int, batch_size: int, smoke: bool = False,
                       amp=None, layout: str = "NHWC"):
    """Bench model: se_resnext (reference benchmark list). NHWC is the
    TPU-native layout default (r3 measured 9.5% MFU in NCHW — the
    grouped-conv stack is layout-sensitive); pass --layout NCHW to
    compare."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import se_resnext as S

    pt.seed(0)
    size = 64 if smoke else 224
    batch_size = _cap(batch_size, 8 if smoke else 64)
    model = S.se_resnext50(num_classes=1000, data_format=layout)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        return (jnp.asarray(rng.normal(size=(bs, 3, size, size))
                            .astype(np.float32)),)

    def loss_fn(logits, batch):
        from paddle_tpu.ops import loss as L

        labels = jnp.zeros((logits.shape[0],), jnp.int32)
        return jnp.mean(L.softmax_with_cross_entropy(logits, labels))

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_alexnet(steps: int, batch_size: int, smoke: bool = False,
                  amp=None):
    """Legacy comparison family (reference benchmark/figs AlexNet charts)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import alexnet as A

    pt.seed(0)
    batch_size = _cap(batch_size, 8 if smoke else 256)
    model = A.alexnet(num_classes=1000)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        return (jnp.asarray(rng.normal(size=(bs, 3, 224, 224))
                            .astype(np.float32)),)

    def loss_fn(logits, batch):
        labels = jnp.zeros((logits.shape[0],), jnp.int32)
        return A.loss_fn(logits, labels)

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_googlenet(steps: int, batch_size: int, smoke: bool = False,
                    amp=None):
    """Legacy comparison family (reference benchmark/figs GoogleNet)."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import googlenet as G

    pt.seed(0)
    batch_size = _cap(batch_size, 8 if smoke else 128)
    model = G.googlenet(num_classes=1000)
    rng = np.random.default_rng(0)

    def make_batch(bs):
        return (jnp.asarray(rng.normal(size=(bs, 3, 224, 224))
                            .astype(np.float32)),)

    def loss_fn(outputs, batch):
        bs = (outputs[0] if isinstance(outputs, tuple) else outputs).shape[0]
        labels = jnp.zeros((bs,), jnp.int32)
        return G.loss_fn(outputs, labels)

    return _train_bench(model, loss_fn, make_batch, steps, batch_size,
                        amp=amp)


def bench_input_pipeline(steps: int, batch_size: int, warmup: int = 3,
                         amp=None):
    """Built-in A/B of the overlapped device input pipeline
    (data/device_loader.py): the SAME jitted train step driven from a
    host-side numpy stream (per-batch rng generation + per-row
    normalization — real input-pipeline host work), once staged
    synchronously in the consumer thread (prefetch OFF) and once through
    a depth-2 DevicePrefetcher background thread (prefetch ON). Every
    step is loss-fenced in BOTH arms, so each arm measures honest
    host+compute wall time per step and the ON/OFF delta is exactly the
    host-work overlap the prefetcher buys. Each arm runs twice and keeps
    its best time (same discipline for both, cancels machine drift).
    ``value`` is the prefetch-ON throughput; extras carry both arms and
    the speedup ratio."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import optimizer, parallel
    from paddle_tpu.data.device_loader import DevicePrefetcher
    from paddle_tpu.models import mnist as M

    pt.seed(0)
    batch_size = _cap(batch_size, 256)
    mesh = pt.build_mesh(dp=1, devices=jax.devices()[:1])
    trainer = parallel.Trainer.supervised(
        M.MnistMLP(hidden1=512, hidden2=256), optimizer.Adam(1e-3),
        M.loss_fn, mesh=mesh, amp=amp)

    def host_batches(n, seed=0):
        rng = np.random.default_rng(seed)
        for _ in range(n):
            x = rng.normal(size=(batch_size, 784)).astype(np.float32)
            x = (x - x.mean(axis=1, keepdims=True)) / (
                x.std(axis=1, keepdims=True) + 1e-6)
            yield {"x": x, "label": rng.integers(0, 10, batch_size)}

    # FLOPs before the first call donates the trainer state
    probe = next(host_batches(1))
    step_flops = _ledger_flops("bench.input_pipeline.step",
                               trainer._jit_step, trainer.params,
                               trainer.buffers, trainer.opt_state,
                               trainer._rng, probe)
    loss = None
    for b in DevicePrefetcher(lambda: host_batches(max(warmup, 1)),
                              size=0):
        loss, _ = trainer.train_step(b)
    float(loss)

    def run_arm(depth, seed):
        t0 = time.perf_counter()
        for b in DevicePrefetcher(lambda: host_batches(steps, seed),
                                  size=depth):
            loss, _ = trainer.train_step(b)
            float(loss)  # per-step fence — see docstring
        return time.perf_counter() - t0

    # off, on, on, off: mirrored order so slow machine drift hits both
    # arms symmetrically
    dt_off = run_arm(0, seed=1)
    dt_on = min(run_arm(2, seed=2), run_arm(2, seed=3))
    dt_off = min(dt_off, run_arm(0, seed=4))
    value = steps * batch_size / dt_on
    extras = {
        "prefetch_off": round(steps * batch_size / dt_off, 2),
        "prefetch_on": round(value, 2),
        "overlap_speedup": round(dt_off / dt_on, 4),
        "step_time_ms": round(dt_on / steps * 1e3, 3),
    }
    if step_flops:
        extras["flops_per_sec"] = step_flops * steps / dt_on
        extras.update(ledger_program="bench.input_pipeline.step",
                      ledger_dispatches=steps, ledger_window_s=dt_on)
    return value, "examples/sec", extras


def bench_checkpoint(steps: int, batch_size: int, amp=None):
    """Checkpoint save + verified-restore round trips (checkpoint.py +
    the resilience integrity plane): a ~16 MB multi-leaf state is saved
    synchronously (checksummed, COMMITTED-marked, atomic rename) and
    restored through ``CheckpointManager.restore`` — the same
    newest-committed-checksum-valid scan a crash-resumed run takes, so
    ``resume_restore_ms`` IS the recovery latency and lands in the perf
    trajectory. ``value`` is payload throughput over the full round
    trip."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.checkpoint import CheckpointManager

    del batch_size  # payload size is the workload, not the batch
    key = jax.random.key(0)
    state = {
        "params": {f"w{i}": jax.random.normal(
            jax.random.fold_in(key, i), (512, 2048), jnp.float32)
            for i in range(3)},
        "opt": {f"m{i}": jnp.zeros((512, 2048), jnp.float32)
                for i in range(1)},
        "step": jnp.asarray(0, jnp.int32),
    }
    payload_bytes = sum(a.nbytes for a in jax.tree_util.tree_leaves(state))
    root = tempfile.mkdtemp(prefix="pt_bench_ckpt_")
    try:
        mgr = CheckpointManager(root, max_to_keep=2, async_save=False)
        mgr.save(0, state)  # warmup (dir creation, allocator, caches)
        mgr.restore()
        save_s, restore_s = [], []
        for i in range(1, steps + 1):
            t0 = time.perf_counter()
            mgr.save(i, state)
            t1 = time.perf_counter()
            mgr.restore()
            t2 = time.perf_counter()
            save_s.append(t1 - t0)
            restore_s.append(t2 - t1)
        dt = sum(save_s) + sum(restore_s)
        value = payload_bytes * steps * 2 / dt / 1e6  # MB through disk
        extras = {
            "payload_mb": round(payload_bytes / 1e6, 2),
            "save_ms": round(sum(save_s) / steps * 1e3, 3),
            # recovery latency: verified manager restore (checksum scan
            # + newest-committed selection + reassembly)
            "resume_restore_ms": round(sum(restore_s) / steps * 1e3, 3),
            "step_time_ms": round(dt / steps * 1e3, 3),
        }
        # step-agreed save transaction overhead: a 2-rank in-process
        # fleet (file transport) runs the two-phase global commit and
        # commit_barrier_ms is the time from this rank's last shard
        # staged to the fleet-wide COMMITTED marker landing — the
        # transaction's cost on the trend line, separate from raw IO
        import os
        import threading

        from paddle_tpu.resilience import FleetController
        from paddle_tpu.resilience.controller import FileTransport

        froot = os.path.join(root, "fleet")

        def ctl(rank):
            return FleetController(
                rank=rank, world=2, hold_poll_s=0.002,
                ckpt_timeout_s=120.0,
                transport=FileTransport(froot, "bench"))

        m0 = CheckpointManager(os.path.join(root, "ga"),
                               max_to_keep=2, async_save=False,
                               coordinator=ctl(0))
        m1 = CheckpointManager(os.path.join(root, "gb"),
                               max_to_keep=2, async_save=False,
                               coordinator=ctl(1))
        barriers = []
        for i in range(1, min(steps, 4) + 1):
            t = threading.Thread(target=lambda s=i: m1.save(s, state),
                                 name="pt-bench-ckpt-rank1")
            t.start()
            m0.save(i, state)
            t.join()
            barriers.append(m0.last_commit_barrier_s)
        extras["commit_barrier_ms"] = round(
            sum(barriers) / len(barriers) * 1e3, 3)
        return value, "MB/sec", extras
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_sharding_plan(steps: int, batch_size: int, amp=None):
    """OOM-gate bench for the sharding-plan plane (parallel/plan.py): a
    model whose REPLICATED param+opt state exceeds the per-device byte
    budget under dp=1, trained under an fsdp Plan instead. On a real
    chip the budget is HBM and the replicated form simply OOMs; on CPU
    backends (no hard HBM wall) the budget is MEASURED: replicated
    per-device bytes = the full state (every device holds every byte),
    budget = half of that, and the planned per-device footprint must
    come in under it — it lands at ~replicated/fsdp, the evidence the
    acceptance gate asks for. The timed loop is the steady-state planned
    step; one lap runs under the transfer guard (zero resharding
    copies) and the jit cache is pinned to one entry (zero retraces
    after step 1). extras carry both footprints, the budget, and the
    shard ratio."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import optimizer, parallel
    from paddle_tpu.models import mnist as M
    from paddle_tpu.parallel.plan import (Plan, guard_no_resharding,
                                          max_device_bytes)

    pt.seed(0)
    batch_size = _cap(batch_size, 256)
    n_dev = len(jax.devices())
    fsdp = next((k for k in (8, 4, 2, 1) if k <= n_dev), 1)
    plan = Plan(dp=1, fsdp=fsdp)
    model = M.MnistMLP(hidden1=2048, hidden2=2048)
    trainer = parallel.Trainer.supervised(
        model, optimizer.Adam(1e-3), M.loss_fn, plan=plan, amp=amp)
    state = {"params": trainer.params, "opt": trainer.opt_state}
    # replicated per-device footprint: every device holds every byte
    replicated = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                     for l in jax.tree_util.tree_leaves(state))
    planned = max_device_bytes(state)
    budget = replicated // 2
    fits = planned <= budget < replicated

    rng = np.random.default_rng(0)
    assert batch_size >= fsdp > 0, \
        f"batch {batch_size} must be >= fsdp {fsdp}"
    batch_size -= batch_size % fsdp
    sh = trainer.data_sharding()
    batch = {"x": jax.device_put(jnp.asarray(
                 rng.normal(size=(batch_size, 784)).astype(np.float32)),
                 sh),
             "label": jax.device_put(
                 jnp.asarray(rng.integers(0, 10, batch_size)), sh)}
    step_flops = _ledger_flops("bench.sharding_plan.step",
                               trainer._jit_step, trainer.params,
                               trainer.buffers, trainer.opt_state,
                               trainer._rng, batch,
                               n_partitions=plan.num_devices)
    for _ in range(3):
        loss, _ = trainer.train_step(batch)
    float(loss)
    with guard_no_resharding():  # steady state pays no resharding copy
        loss, _ = trainer.train_step(batch)
    float(loss)
    t0 = time.perf_counter()
    for i in range(steps):
        loss, _ = trainer.train_step(batch)
        if i % 4 == 3:
            float(loss)
    float(loss)
    dt = time.perf_counter() - t0
    assert trainer._jit_step._cache_size() == 1, \
        "planned step retraced after step 1"
    extras = {
        "step_time_ms": round(dt / steps * 1e3, 3),
        "fsdp": fsdp,
        "peak_mem_bytes_replicated": int(replicated),
        "peak_mem_bytes_planned": int(planned),
        "byte_budget": int(budget),
        "fits_budget_only_planned": bool(fits),
        "shard_ratio": round(replicated / planned, 3) if planned else None,
    }
    if step_flops:
        extras["flops_per_sec"] = step_flops * steps / dt
        extras.update(ledger_program="bench.sharding_plan.step",
                      ledger_dispatches=steps, ledger_window_s=dt)
    return steps * batch_size / dt, "examples/sec", extras


def bench_quant_comm(steps: int, batch_size: int, amp=None):
    """Compressed-gradient-allreduce A/B (quant.collectives): the SAME
    pure-DP plan trained with the fp32 ``lax.pmean`` vs the hand-written
    int8 ring psum (``Plan(grad_compression="int8")``), on however many
    devices are up (8-device sim on CPU; real chips on-TPU). Evidence
    the acceptance gate asks for: per-step collective payload bytes
    int8 vs fp32 (counter-verified against
    ``pt_collective_bytes_total{compressed=}``), step time both ways,
    and the TRAJECTORY PARITY GATE — K lockstep steps from one seed
    must keep the loss gap inside tolerance, or the extras say so
    loudly. On ICI-rich single-host sims the ring moves host-memory
    bytes, so step-time parity (not speedup) is the CPU expectation;
    the byte counters are the transferable number."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import optimizer, parallel, telemetry
    from paddle_tpu.models import mnist as M
    from paddle_tpu.parallel.plan import Plan
    from paddle_tpu.quant.collectives import _comm_metrics

    n_dev = len(jax.devices())
    dp = next((k for k in (8, 4, 2) if k <= n_dev), 0)
    if dp < 2:
        raise RuntimeError(
            f"quant_comm needs >= 2 devices for the allreduce ring, "
            f"got {n_dev} (is the 8-device sim guard stripped?)")
    batch_size = _cap(batch_size, 256)
    # round to the dp grid, never below one row per shard (an explicit
    # --batch-size 4 on the 8-device sim must not become an empty batch)
    batch_size = max(dp, batch_size - batch_size % dp)
    rng = np.random.default_rng(0)
    batch = {"x": jnp.asarray(rng.normal(size=(batch_size, 784))
                              .astype(np.float32)),
             "label": jnp.asarray(rng.integers(0, 10, batch_size))}

    def make(comp):
        pt.seed(0)
        model = M.MnistMLP(hidden1=1024, hidden2=1024)
        return parallel.Trainer.supervised(
            model, optimizer.Adam(1e-3), M.loss_fn, amp=amp,
            plan=Plan(dp=dp, grad_compression=comp))

    was_enabled = telemetry.enabled()
    telemetry.enable()  # the byte counters ARE the evidence
    try:
        tr_fp, tr_q = make(None), make("int8")
        # trajectory parity gate: K lockstep steps, one seed, one batch
        parity_steps = 8
        for _ in range(parity_steps):
            l_fp, _ = tr_fp.train_step(batch)
            l_q, _ = tr_q.train_step(batch)
        l_fp, l_q = float(l_fp), float(l_q)
        parity_gap = abs(l_fp - l_q)
        parity_ok = parity_gap <= max(5e-3, 5e-3 * abs(l_fp))
        # counter-verified bytes: the per-step payload each trainer
        # recorded must match what the counters actually advanced by
        m = _comm_metrics()
        c_i8, c_fp = m["bytes_int8"].value, m["bytes_fp32"].value
        warm = parity_steps
        i8_step = sum(tr_q._comm_bytes)
        fp_step = sum(tr_fp._comm_bytes)
        counters_match = (
            abs(c_i8 - tr_q._comm_bytes[0] * warm) < 1
            and abs(c_fp - (tr_fp._comm_bytes[1]
                            + tr_q._comm_bytes[1]) * warm) < 1)

        def timed(tr):
            loss, _ = tr.train_step(batch)
            float(loss)
            t0 = time.perf_counter()
            for i in range(steps):
                loss, _ = tr.train_step(batch)
                if i % 4 == 3:
                    float(loss)
            float(loss)
            return time.perf_counter() - t0

        dt_fp, dt_q = timed(tr_fp), timed(tr_q)
    finally:
        if not was_enabled:
            telemetry.disable()
    ratio = fp_step / i8_step if i8_step else None
    extras = {
        "dp": dp,
        "step_time_ms": round(dt_q / steps * 1e3, 3),
        "step_time_ms_fp32": round(dt_fp / steps * 1e3, 3),
        "comm_bytes_per_step_fp32": int(fp_step),
        "comm_bytes_per_step_int8": int(i8_step),
        "comm_byte_ratio": round(ratio, 3) if ratio else None,
        "comm_counter_verified": bool(counters_match),
        "parity_loss_fp32": round(l_fp, 6),
        "parity_loss_int8": round(l_q, 6),
        "parity_gate": bool(parity_ok),
    }
    return steps * batch_size / dt_q, "examples/sec", extras


MODELS = {
    "mnist_mlp": bench_mnist_mlp,
    "quant_comm": bench_quant_comm,
    "input_pipeline": bench_input_pipeline,
    "checkpoint": bench_checkpoint,
    "sharding_plan": bench_sharding_plan,
    "alexnet": bench_alexnet,
    "googlenet": bench_googlenet,
    "stacked_lstm": bench_stacked_lstm,
    "vgg16": bench_vgg16,
    "se_resnext50": bench_se_resnext50,
    "resnet50": bench_resnet50,
    "bert_base": bench_bert_base,
    "bert_packed": bench_bert_packed,
    "bert_moe": bench_bert_moe,
    "gpt": bench_gpt,
    "vit": bench_vit,
    "bert_long": bench_bert_long,
    "transformer_nmt": bench_transformer_nmt,
    "nmt_decode": bench_nmt_decode,
    "gpt_decode": bench_gpt_decode,
    "gpt_serve": bench_gpt_serve,
    "deepfm": bench_deepfm,
    "deepfm_sparse": bench_deepfm_sparse,
}


def hist_value(entry) -> float:
    """Numeric view of a history entry — dict form ({"value": ...} with
    metadata) or the legacy bare float."""
    return entry["value"] if isinstance(entry, dict) else entry


def run_config_fingerprint(metric: str, args, steps: int):
    """Like-for-like identity + provenance for a history entry.

    Returns ``(config_hash, config)``. The hash covers the WORKLOAD
    identity: the metric key (which already encodes model + every
    workload suffix: _vN/_wN/_nocache/_uN/_layout/_kN/_bN/_dpN/_infer)
    plus the measurement length (``steps`` — a 24-step fast-sweep number
    is noisier than a 100-step one and must never set or mask the
    headline record; it lives under its own ``metric@hash`` variant
    key). Two runs that share a metric key and steps hash identically —
    knob sweeps (remat / amp / fused-ce variants that deliberately
    compete for the headline record under one key) stay comparable. The
    ``config`` dict records the full knob set as provenance so the
    history is never silent about what produced a record (VERDICT r4
    weak #4).
    """
    import hashlib

    workload = {"metric": metric, "dp": args.dp, "steps": steps}
    config_hash = hashlib.sha1(
        json.dumps(workload, sort_keys=True).encode()).hexdigest()[:12]
    config = {
        "model": args.model, "steps": steps,
        # an explicit --batch-size is honored as given; the harness-wide
        # default is clamped per model inside the bench fn (_cap), so
        # the requested value would be provenance fiction — record the
        # truth we have
        "batch": args.batch_size if args.batch_size else "model-default",
        "amp": args.amp, "fused_ce": args.fused_ce, "remat": args.remat,
        "scan_layers": args.scan_layers, "scan_unroll": args.scan_unroll,
        "steps_per_call": args.steps_per_call, "vocab": args.vocab,
        "window": args.window, "kv_cache": args.kv_cache,
        "gamma": args.gamma, "weight_only": args.weight_only,
        "paged": args.paged,
        "router": (args.replicas if getattr(args, "router", False)
                   else None),
        "router_prefill_workers": (
            args.prefill_workers if getattr(args, "router", False)
            else None),
        "router_from_artifact": (
            True if getattr(args, "router", False)
            and getattr(args, "from_artifact", False) else None),
        "router_autoscale": (
            getattr(args, "autoscale", None)
            if getattr(args, "router", False) else None),
        "router_gray_failure": (
            True if getattr(args, "router", False)
            and getattr(args, "gray_failure", False) else None),
        "layout": args.layout, "dp": args.dp, "infer": args.infer,
    }
    # None = knob not set; False values (e.g. --no-fused-ce) are REAL
    # provenance and must stay visible
    config = {k: v for k, v in config.items() if v is not None}
    return config_hash, config


def evaluate_against_history(metric: str, value: float, history: dict, *,
                             on_accelerator: bool, record: bool,
                             device_kind=None, config_hash=None,
                             config=None, now=None):
    """Perf-regression contract: ``vs_baseline`` compares this run to the
    BEST recorded accelerator number for the SAME workload (history keeps
    the max; CPU runs never recorded). Returns (vs_baseline, regression);
    regression = accelerator run >10% below the record — the API.spec
    freeze philosophy applied to throughput. Mutates ``history`` in
    place when ``record`` and ``on_accelerator``.

    Entries are dicts ``{value, ts, device, config_hash, config}``
    (legacy bare floats still read, and are upgraded in place on the
    next record). Like-for-like gate: a run only ever compares against
    and updates an entry whose ``device`` and ``config_hash`` match its
    own. A mismatched run is NOT silently compared (vs_baseline 1.0, no
    regression flag) and records NON-destructively under the variant key
    ``metric@config_hash`` — the headline record keeps its key, so an
    alternating pair of configs can neither demote the true record nor
    mask a later real regression against it. Legacy floats carry no
    metadata; they were by construction 100-step headline chip runs
    (CPU was never recorded), so they baseline only runs whose measured
    length is the headline default."""
    def _matches(entry):
        if not isinstance(entry, dict) or entry.get("legacy"):
            # legacy bare float (or its dict upgrade) — a full-length
            # headline chip number with unknown knob provenance: it
            # baselines only headline-length runs
            return (config or {}).get("steps") in (None, HEADLINE_STEPS)
        pd, ph = entry.get("device"), entry.get("config_hash")
        if pd is not None and device_kind is not None and pd != device_kind:
            return False
        if ph is not None and config_hash is not None and ph != config_hash:
            return False
        return True

    variant_key = f"{metric}@{config_hash}" if config_hash else None
    # third tier: device-qualified variant, so runs from two chip
    # generations each keep (and regress against) their OWN record
    # instead of thrashing one key through _superseded
    device_key = (f"{variant_key}@{device_kind}"
                  if variant_key and device_kind else None)
    baseline_key, prev_entry = None, None
    for key in filter(None, (metric, variant_key, device_key)):
        entry = history.get(key)
        if entry is not None and _matches(entry):
            baseline_key, prev_entry = key, entry
            break
    prev = hist_value(prev_entry) if prev_entry is not None else None
    vs_baseline = (value / prev) if prev else 1.0
    regression = bool(on_accelerator and prev and value < 0.9 * prev)
    if record and on_accelerator:
        if prev is not None and prev >= value:
            # the record stands, keeping the metadata of the run that
            # set it; bare legacy floats get a minimal dict upgrade
            if not isinstance(prev_entry, dict):
                history[baseline_key] = {"value": prev, "legacy": True}
        else:
            entry = {"value": value}
            if now:
                entry["ts"] = now
            if device_kind:
                entry["device"] = device_kind
            if config_hash:
                entry["config_hash"] = config_hash
            if config:
                entry["config"] = config
            if baseline_key is not None:
                target = baseline_key  # beat a matching record in place
            else:
                # headline-config runs own the bare metric key when it
                # is free; anything else takes the first vacant variant
                # tier (config, then config@device). All tiers occupied
                # by mismatched entries can only mean scheme drift —
                # archive the most specific one, never drop it.
                headline = (config or {}).get("steps") in (None, HEADLINE_STEPS)
                candidates = ([metric] if headline else []) + list(
                    filter(None, (variant_key, device_key)))
                vacant = [k for k in candidates if k not in history]
                target = vacant[0] if vacant else (
                    candidates[-1] if candidates else metric)
                old = history.get(target)
                if old is not None:
                    history.setdefault("_superseded", []).append(
                        {"metric": target, "entry": old})
            history[target] = entry
    return vs_baseline, regression


def _emit_error(metric: str, msg: str) -> None:
    """One-JSON-line driver contract, argument-MISUSE form: a
    deterministic caller error keeps the value-0.0 shape (it could never
    have produced a number and never enters history)."""
    print(json.dumps({"metric": metric, "value": 0.0,
                      "unit": "examples/sec", "vs_baseline": 0.0,
                      "backend": None, "mfu": None, "step_time_ms": None,
                      "peak_mem_bytes": None, "error": msg}))


class _SkipBench(Exception):
    """Raised by a bench fn when the ENVIRONMENT (not the workload)
    makes the measurement impossible mid-run — e.g. the aot artifact
    failed to export/load. main() converts it into the ``skipped``
    JSON line via :func:`_emit_skip`; a fabricated 0.0 (or a fake TTFR)
    would read as a real measurement and poison the trend history."""

    def __init__(self, msg: str, cause: str = None):
        super().__init__(msg)
        self.cause = cause


def _emit_skip(metric: str, msg: str, cause: str = None) -> None:
    """One-JSON-line driver contract, INFRA-error form: the workload is
    fine but the environment failed (device init timeout, profiler
    unsupported). Emits ``"skipped": true`` with the error and NO value
    key — a 0.0 row here would read as a real measurement and drag
    BENCH_HISTORY trend plots to zero. ``cause`` stamps a stable
    machine-readable reason (e.g. ``device_init_timeout``) so trend
    tooling can bucket degraded rounds without parsing prose."""
    line = {"metric": metric, "skipped": True,
            # infra-degraded row: trend tooling must not
            # fold it into deltas (the BENCH_r05 hazard)
            "backend_degraded": True,
            "peak_mem_bytes": None, "error": msg}
    if cause:
        line["cause"] = cause
    print(json.dumps(line))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="mnist_mlp", choices=sorted(MODELS))
    ap.add_argument("--smoke", action="store_true", help="quick run")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--layout", default=None,
                    help="conv data format for models that support it "
                    "(NHWC default on resnet)")
    ap.add_argument("--fused-ce", dest="fused_ce", default=True,
                    action="store_true",
                    help="bert/nmt: chunked linear-CE head (the default "
                    "measured configuration; pass --no-fused-ce for the "
                    "legacy full-logits path)")
    ap.add_argument("--no-fused-ce", dest="fused_ce", action="store_false")
    ap.add_argument("--remat", nargs="?", const="full", default=None,
                    choices=["full", "dots"],
                    help="bert: jax.checkpoint per transformer block; "
                    "'dots' saves matmul outputs and recomputes only the "
                    "elementwise tail (less recompute, more HBM)")
    ap.add_argument("--scan-layers", dest="scan_layers",
                    action="store_true",
                    help="bert: lax.scan over the layer stack (dropout "
                    "forced to 0)")
    ap.add_argument("--scan-unroll", dest="scan_unroll", type=int,
                    default=None,
                    help="stacked_lstm: unroll the time-recurrence scan "
                    "K steps per compiled loop body (identical math)")
    ap.add_argument("--amp", default="mixed_bf16",
                    help="dtype policy for the step (mixed_bf16 is the TPU "
                    "training default; pass float32 to disable)")
    ap.add_argument("--steps-per-call", dest="steps_per_call", type=int,
                    default=None,
                    help="fuse K update steps per dispatch (lax.scan; "
                    "identical math). Default: model-specific (mnist 8, "
                    "others 1)")
    ap.add_argument("--profile", default=None, metavar="TRACE_JSON",
                    help="wrap the timed run in the profiler and write a "
                    "chrome-trace JSON here (fluid_benchmark --profile "
                    "analog)")
    ap.add_argument("--device-trace", dest="device_trace", default=None,
                    metavar="DIR",
                    help="wrap the timed run in jax.profiler.trace(DIR): "
                    "captures DEVICE-side op timelines (xplane.pb, "
                    "TensorBoard-consumable) — the device_tracer.h half "
                    "of the profiler capability; fails loudly if the "
                    "PJRT plugin exposes no profiler")
    ap.add_argument("--vocab", type=int, default=None,
                    help="deepfm/deepfm_sparse: embedding table size "
                    "(sweeps the sparse-vs-dense update crossover)")
    ap.add_argument("--window", type=int, default=None,
                    help="bert_long: sliding-window attention width "
                    "(O(T*W) local attention vs the O(T^2) default)")
    ap.add_argument("--paged", action="store_true",
                    help="gpt_serve: paged-KV arena (page pool sized "
                    "to ~half the dense slots x capacity)")
    ap.add_argument("--kv-dtype", dest="kv_dtype", default=None,
                    choices=("int8",),
                    help="gpt_serve: quantized paged KV pool (implies "
                    "--paged; int8 values + per-vector scales — "
                    "~3.7x pages per HBM byte) plus the max-sessions "
                    "density A/B and greedy parity extras")
    ap.add_argument("--router", action="store_true",
                    help="gpt_serve: the production-serving A/B — "
                    "multi-replica router + prefill/decode "
                    "disaggregation + SLO shed under a seeded Poisson "
                    "open-loop load (p50/p99 TTFT, p99 ITL, aggregate "
                    "tok/s, shed rate; _routerN history key)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="--router: decode replica count")
    ap.add_argument("--prefill-workers", dest="prefill_workers",
                    type=int, default=1,
                    help="--router: dedicated prefill workers (0 = "
                    "no disaggregation)")
    ap.add_argument("--overload", type=float, default=2.0,
                    help="--router: overload factor for the shed arm")
    ap.add_argument("--router-procs", dest="router_procs",
                    action="store_true",
                    help="--router: replicas as real worker processes "
                    "over HTTP instead of in-process threads")
    ap.add_argument("--stream", action="store_true",
                    help="--router: add the per-token STREAMING arm "
                    "(router-side first-token TTFT + client-side "
                    "inter-token-latency columns) and the "
                    "prefix-hash vs session-only routing hit-rate "
                    "A/B to the same JSON line")
    ap.add_argument("--autoscale", default=None, metavar="MIN,MAX",
                    help="--router: replace the disagg arms with the "
                    "autoscaling spike A/B — static MAX fleet vs a "
                    "Scaler-driven fleet growing from MIN on a "
                    "seeded 3x spike and draining back on sustained "
                    "headroom, gated on SLO at strictly fewer "
                    "replica-seconds")
    ap.add_argument("--gray-failure", dest="gray_failure",
                    action="store_true",
                    help="--router: replace the disagg arms with the "
                    "gray-failure reliability A/B — one replica "
                    "wedged ~10x slow (seeded replica.wedge delay), "
                    "clean vs reliability-off vs reliability-on arms; "
                    "gates quarantine + bounded p99 TTFT with the "
                    "plane on (_gray history key)")
    ap.add_argument("--from-artifact", dest="from_artifact",
                    action="store_true",
                    help="--router: add the AOT cold-start A/B — "
                    "export the replica's compiled programs "
                    "(paddle_tpu.aot) and boot a second replica "
                    "trace-free from the artifact; reports "
                    "ttfr_traced_ms vs ttfr_aot_ms and GATES "
                    "ttfr_aot < ttfr_traced (_aot history key)")
    ap.add_argument("--prefill-chunk", dest="prefill_chunk", type=int,
                    default=None,
                    help="gpt_serve: chunked prefill — C prompt tokens "
                    "per serving tick instead of whole-prompt "
                    "admission stalls (_pcN history key)")
    ap.add_argument("--decode-steps", dest="decode_steps", type=int,
                    default=None,
                    help="gpt_serve: k tokens per serving dispatch "
                    "(in-device picks; token-identical to k=1) — "
                    "amortizes the per-dispatch round trip (_dsN key)")
    ap.add_argument("--weight-only", dest="weight_only",
                    action="store_true",
                    help="gpt_decode/gpt_serve: weight-only int8 "
                    "(W8A16) on the model's matmuls (_w8 history key)")
    ap.add_argument("--gamma", type=int, default=None,
                    help="gpt_decode: speculative-decoding draft length "
                    "(0/unset = plain greedy decode)")
    ap.add_argument("--no-kv-cache", dest="kv_cache", action="store_false",
                    help="nmt_decode: full-prefix re-run decode instead "
                    "of the K/V-cached step (same tokens; the honest "
                    "baseline for the cache win)")
    ap.add_argument("--dp", type=int, default=1,
                    help="data-parallel device count (--gpus analog; on "
                    "--platform cpu this creates virtual host devices)")
    ap.add_argument("--plan", default=None, metavar="AXES",
                    help="deepfm_sparse: sharding plan for the embedding "
                    "tables, e.g. 'ep=8' or 'dp=2,ep=4' — tables "
                    "row-shard over the ep mesh axis with sparse "
                    "(ids, rows) gradient exchange and the byte-budget "
                    "gate (on cpu the dp*ep virtual devices are created "
                    "automatically)")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) — needed because "
                    "this environment's sitecustomize overrides JAX_PLATFORMS")
    ap.add_argument("--infer", action="store_true",
                    help="inference mode: jitted forward only, reports "
                    "examples/sec + p50/p99 latency (the reference's "
                    "inference/tests/api latency-harness role)")
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        if args.dp > 1 and args.platform == "cpu":
            try:
                jax.config.update("jax_num_cpu_devices", args.dp)
            except AttributeError:
                # older JAX only honors the XLA_FLAGS env var, and only
                # before backend init (the conftest guard, applied here)
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "") +
                    f" --xla_force_host_platform_device_count={args.dp}"
                ).strip()

    steps = args.steps or (10 if args.smoke else HEADLINE_STEPS)
    batch = args.batch_size or (256 if args.smoke else 8192)
    global _EXPLICIT_BATCH
    _EXPLICIT_BATCH = bool(args.batch_size)  # assignment: a second
    # in-process main() without --batch-size gets the caps back

    # Resolve the workload-suffixed metric key ONCE, before any code
    # that can fail: error lines must carry the same key as the success
    # line for the same command, or retry/history tooling mis-files the
    # failure under a different workload. inspect on the local bench fn
    # is safe pre-watchdog (nothing touches the device).
    import inspect

    global _MODE
    _MODE = "infer" if args.infer else "train"
    fn = MODELS[args.model]
    if args.stream and not args.router:
        _emit_error(f"{args.model}_throughput",
                    "--stream only applies with --router "
                    "(gpt_serve streaming arm)")
        return
    if args.from_artifact and not args.router:
        _emit_error(f"{args.model}_throughput",
                    "--from-artifact only applies with --router "
                    "(the aot cold-start A/B)")
        return
    autoscale = None
    if args.autoscale:
        if not args.router:
            _emit_error(f"{args.model}_throughput",
                        "--autoscale only applies with --router "
                        "(the elastic-fleet spike A/B)")
            return
        if args.stream or args.from_artifact or args.router_procs:
            _emit_error(f"{args.model}_throughput",
                        "--autoscale is its own workload: drop "
                        "--stream/--from-artifact/--router-procs")
            return
        try:
            amin, amax = (int(x) for x in args.autoscale.split(","))
        except ValueError:
            _emit_error(f"{args.model}_throughput",
                        f"--autoscale wants MIN,MAX integers, got "
                        f"{args.autoscale!r}")
            return
        if not 1 <= amin < amax:
            _emit_error(f"{args.model}_throughput",
                        f"--autoscale needs 1 <= MIN < MAX, got "
                        f"{amin},{amax}")
            return
        autoscale = (amin, amax)
    if args.gray_failure:
        if not args.router:
            _emit_error(f"{args.model}_throughput",
                        "--gray-failure only applies with --router "
                        "(the reliability A/B)")
            return
        if (args.stream or args.from_artifact or args.router_procs
                or autoscale):
            _emit_error(f"{args.model}_throughput",
                        "--gray-failure is its own workload: drop "
                        "--stream/--from-artifact/--router-procs/"
                        "--autoscale")
            return
    if args.router:
        if args.model != "gpt_serve":
            _emit_error(f"{args.model}_throughput",
                        "--router only applies to --model gpt_serve")
            return
        fn = bench_gpt_router
    sig = inspect.signature(fn).parameters
    metric = (f"{args.model}_infer_throughput" if args.infer
              else f"{args.model}_throughput")
    if args.router:
        # the router A/B is its own WORKLOAD (open-loop Poisson load,
        # multi-replica topology): one history key per replica count
        metric += f"_router{args.replicas}"
        if args.router_procs:
            metric += "_procs"
        if args.stream:
            # the streaming arm changes the measured columns (stream
            # TTFT/ITL + the prefix-routing A/B): its own history key
            metric += "_stream"
        if args.from_artifact:
            # the AOT A/B adds the TTFR columns + its gate: own key
            metric += "_aot"
        if autoscale:
            # the elastic-fleet spike A/B is its own workload
            # (piecewise rate, fleet size varies): own key per band
            metric += f"_as{autoscale[0]}x{autoscale[1]}"
        if args.gray_failure:
            # the gray-failure A/B is its own workload (wedged
            # replica, three arms): own key
            metric += "_gray"
    if (args.vocab and "vocab" in sig
            and args.vocab != sig["vocab"].default):
        metric += f"_v{args.vocab}"
    if args.window and "window" in sig:
        # a window changes the WORKLOAD (different attention math):
        # its history key must not collide with the full-attention one
        metric += f"_w{args.window}"
    if args.gamma is not None and args.gamma < 0:
        # a negative value would fall back to greedy inside the bench fn
        # while recording under a speculative _gN key — refuse instead
        _emit_error(metric, f"--gamma must be >= 1, got {args.gamma}")
        return
    if args.gamma and "gamma" in sig:
        # speculative decode is a different WORKLOAD (draft model in the
        # loop): its own history key per gamma
        metric += f"_g{args.gamma}"
    if args.weight_only and "weight_only" in sig:
        # same workload, different weight storage — own history key so
        # the W8A16-vs-bf16 comparison stays visible
        metric += "_w8"
    if args.paged and "paged" in sig:
        # different cache layout (page pool vs dense arena): own key
        metric += "_paged"
    if args.kv_dtype and "kv_dtype" in sig:
        # different KV storage form (quantized page pool): own key so
        # the density-vs-precision trade stays visible next to fp32
        metric += f"_kv{args.kv_dtype}"
    if args.prefill_chunk and "prefill_chunk" in sig:
        # different admission schedule (prefill interleaved with
        # decode): own key per chunk size
        metric += f"_pc{args.prefill_chunk}"
    if (args.decode_steps and args.decode_steps > 1
            and "decode_steps" in sig):
        # same workload, fused dispatch — own key so the RTT
        # amortization stays visible next to the k=1 row (--decode-steps
        # 1 IS the baseline: no key fork, mirrors --gamma 0)
        metric += f"_ds{args.decode_steps}"
    if "cached" in sig and not args.kv_cache:
        # same workload, different implementation — its own history key
        # so the cache-vs-recompute comparison stays visible
        metric += "_nocache"
    if (args.scan_unroll and "scan_unroll" in sig
            and args.scan_unroll != sig["scan_unroll"].default):
        # same math, different compiled loop body — own key for the sweep
        metric += f"_u{args.scan_unroll}"
    if args.layout and "layout" in sig and args.layout != sig["layout"].default:
        metric += f"_{args.layout.lower()}"
    if args.steps_per_call:
        # a dispatch-fusion factor that DIFFERS from the model's headline
        # default is a sweep point: its own history key. Passing the
        # model's own default explicitly (e.g. mnist --steps-per-call 8)
        # must not fork the history of an identical configuration —
        # mirror the scan-unroll pattern and compare against the bench
        # signature's default (1 for models routed via _train_bench).
        _k_default = (sig["steps_per_call"].default
                      if "steps_per_call" in sig else 1)
        if not isinstance(_k_default, int):
            _k_default = 1
        if args.steps_per_call != _k_default:
            metric += f"_k{args.steps_per_call}"
    if _EXPLICIT_BATCH:
        metric += f"_b{batch}"
    if args.dp > 1:
        # data-parallel width changes the WORKLOAD (global batch shards
        # over dp devices): its own history key, never silently compared
        # against the single-device record
        metric += f"_dp{args.dp}"
    plan_axes = None
    if args.plan:
        if args.model != "deepfm_sparse" or "plan" not in sig:
            _emit_error(metric, "--plan only applies to --model "
                        "deepfm_sparse (the ep-sharded embedding arm)")
            return
        if args.infer:
            _emit_error(metric, "--infer does not support --plan "
                        "(the ep arm measures the sparse train step)")
            return
        if args.dp > 1:
            _emit_error(metric, "--plan carries its own dp axis "
                        "(use --plan dp=M,ep=N, not --dp)")
            return
        try:
            plan_axes = _parse_plan_arg(args.plan)
        except ValueError as e:
            _emit_error(metric, str(e))
            return
        # the plan shape is the WORKLOAD (mesh axes + exchange
        # topology): its own history key, e.g. _ep8 or _dp2_ep4
        metric += "_" + args.plan.replace("=", "").replace(",", "_")
    if args.infer and args.model == "deepfm_sparse":
        # sparse_grads only changes the UPDATE path; the forward is
        # identical to deepfm's — bench that instead of duplicating it
        _emit_error(metric, "--infer: use --model deepfm (the sparse "
                    "variant differs only in the optimizer update)")
        return
    if args.infer and args.model == "input_pipeline":
        # the A/B measures the TRAIN step under both staging modes; an
        # --infer run would silently measure training under an infer key
        _emit_error(metric, "--infer: input_pipeline A/Bs the train "
                    "step; run it without --infer")
        return
    if args.infer and args.model == "gpt_serve":
        _emit_error(metric, "--infer: --model gpt_serve already measures "
                    "inference serving; run it without --infer")
        return
    if args.infer and args.model == "gpt_decode":
        _emit_error(metric, "--infer: --model gpt_decode already measures "
                    "inference decode; run it without --infer")
        return
    if args.infer and args.model == "nmt_decode":
        # the decode bench IS an inference workload; an --infer run would
        # duplicate it under a second metric key and fork its history
        _emit_error(metric, "--infer: --model nmt_decode already measures "
                    "inference decode; run it without --infer")
        return
    if args.infer and args.model == "bert_packed":
        # packing is a training-batch layout; the pretraining head's
        # plain forward takes no segment_ids, so an infer run would
        # silently measure the UNPACKED attention path under a packed
        # label
        _emit_error(metric, "--infer: use --model bert_base (packing is "
                    "a training-batch layout)")
        return

    if args.model == "quant_comm" or plan_axes:
        # the allreduce ring / the plan mesh needs devices: give a
        # cpu-only run the device sim BEFORE backend init (accelerator
        # backends ignore the cpu device count — on-chip runs use the
        # real devices)
        import jax

        n_sim = (plan_axes["dp"] * plan_axes["ep"]) if plan_axes else 8
        try:
            jax.config.update("jax_num_cpu_devices", n_sim)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n_sim}"
            ).strip()

    # device-init watchdog: if the accelerator tunnel is wedged (device
    # claim hangs), still emit the one JSON line the driver expects
    # instead of hanging the whole round
    import threading

    init_ok = threading.Event()

    def _probe():
        import jax

        jax.devices()
        init_ok.set()

    # ONE hard window — the old double-join gave a wedged tunnel
    # 2x420 s per bench row, and the cpu-fallback re-exec then paid the
    # same again: a full bench round could hang for the better part of
    # an hour doing nothing (the ROADMAP/BENCH_r05-r06 operational
    # note). CPU init is near-instant, so the fallback attempt gets a
    # short bounded window instead of the accelerator's.
    timeout_s = float(os.environ.get("PT_BENCH_DEVICE_TIMEOUT_S", "420"))
    if os.environ.get("PT_BENCH_CPU_FALLBACK"):
        timeout_s = min(timeout_s, 60.0)
    probe = threading.Thread(target=_probe, daemon=True,
                             name="pt-bench-device-probe")
    probe.start()
    probe.join(timeout=timeout_s)
    if not init_ok.is_set():
        if os.environ.get("PT_BENCH_CPU_FALLBACK"):
            # already fell back once and CPU init ALSO hung — nothing
            # left to fall back to; keep the one-JSON-line contract
            # (skipped, not value 0.0: infra error, not a measurement)
            _emit_skip(metric,
                       "device init timeout (accelerator unreachable; "
                       "cpu fallback also failed)",
                       cause="device_init_timeout")
            return
        # fall back to CPU so the round still produces a real number
        # (tagged "backend": "cpu_fallback" in the JSON) instead of the
        # driver-breaking value-0.0 error line. The wedged backend init
        # may hold jax's init lock in this process, so re-exec with the
        # platform forced — a clean process is the only reliable way to
        # re-enter backend selection.
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PT_BENCH_CPU_FALLBACK="1")
        print(f"WARNING: device init timed out ({timeout_s:.0f}s); "
              "re-running on cpu (backend=cpu_fallback, "
              "cause=device_init_timeout)", file=sys.stderr)
        sys.stderr.flush()
        sys.stdout.flush()
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)]
                  + sys.argv[1:], env)
    # Persistent compilation cache: amortizes the slow first compile
    # across bench processes (the knob sweep re-lowers near-identical
    # modules) and lets the AOT compile inside lowered_flops' fallback be
    # reused by the timed dispatch of the same module. After the watchdog
    # on purpose: importing paddle_tpu before the probe could hang on a
    # wedged tunnel with no error line emitted.
    from paddle_tpu.utils.flops import enable_compile_cache

    enable_compile_cache()
    kwargs = {}
    if plan_axes:
        import jax

        need = plan_axes["dp"] * plan_axes["ep"]
        if len(jax.devices()) < need:
            # infra shape, not argument misuse: the workload is fine but
            # this host/backend cannot field the mesh (e.g. a 4-chip
            # slice asked for ep=8) — skipped row, never a 0.0 value
            _emit_skip(metric,
                       f"--plan {args.plan} needs {need} devices, "
                       f"have {len(jax.devices())}",
                       cause="insufficient_devices")
            return
        kwargs["plan"] = args.plan
    if "smoke" in sig:
        kwargs["smoke"] = args.smoke
    if "amp" in sig and args.amp and args.amp != "float32":
        kwargs["amp"] = args.amp
    if "layout" in sig and args.layout:
        kwargs["layout"] = args.layout
    if "fused_ce" in sig:
        kwargs["fused_ce"] = args.fused_ce
    if "remat" in sig and args.remat:
        kwargs["remat"] = args.remat
    if "scan_layers" in sig and args.scan_layers:
        kwargs["scan_layers"] = True
    if "scan_unroll" in sig and args.scan_unroll:
        kwargs["scan_unroll"] = args.scan_unroll
    if "vocab" in sig and args.vocab:
        kwargs["vocab"] = args.vocab
    if "window" in sig and args.window:
        kwargs["window"] = args.window
    if "cached" in sig:
        kwargs["cached"] = args.kv_cache
    if args.gamma and "gamma" in sig:
        kwargs["gamma"] = args.gamma
    if args.weight_only and "weight_only" in sig:
        kwargs["weight_only"] = True
    if args.paged and "paged" in sig:
        kwargs["paged"] = True
    if args.kv_dtype and "kv_dtype" in sig:
        kwargs["kv_dtype"] = args.kv_dtype
    if args.router:
        kwargs["replicas"] = args.replicas
        kwargs["prefill_workers"] = args.prefill_workers
        kwargs["overload"] = args.overload
        kwargs["router_procs"] = args.router_procs
        kwargs["stream"] = args.stream
        kwargs["from_artifact"] = args.from_artifact
        kwargs["autoscale"] = autoscale
        kwargs["gray_failure"] = args.gray_failure
    if args.prefill_chunk and "prefill_chunk" in sig:
        kwargs["prefill_chunk"] = args.prefill_chunk
    if (args.decode_steps and args.decode_steps > 1
            and "decode_steps" in sig):
        kwargs["decode_steps"] = args.decode_steps
    if args.steps_per_call:
        if "steps_per_call" in sig:
            kwargs["steps_per_call"] = args.steps_per_call
        else:
            global _STEPS_PER_CALL
            _STEPS_PER_CALL = args.steps_per_call
    if args.dp > 1:
        if args.infer:
            # bench_mnist_mlp would otherwise build the dp mesh and then
            # silently measure a single-device forward under a metric
            # name that carries no dp marker
            _emit_error(metric, "--infer does not support --dp "
                        "(inference bench is single-device)")
            return
        if "dp" not in sig:
            _emit_error(metric,
                        f"--dp is not supported by model {args.model} "
                        "(single-device bench)")
            return
        kwargs["dp"] = args.dp
    import contextlib

    if args.profile:
        # fail on an unwritable path BEFORE the (possibly long) run,
        # keeping the one-JSON-line contract
        try:
            with open(args.profile, "w"):
                pass
        except OSError as e:
            _emit_error(metric,
                        f"unwritable --profile path: {e}")
            return
        from paddle_tpu.core.profiler import profiler as _prof

        ctx = _prof(timeline_path=args.profile)
    else:
        ctx = contextlib.nullcontext()
    if args.device_trace:
        import jax

        dctx = jax.profiler.trace(args.device_trace)
    else:
        dctx = contextlib.nullcontext()
    with ctx, dctx:
        try:
            value, unit, *rest = fn(steps, batch, **kwargs)
        except _SkipBench as e:
            _emit_skip(metric, str(e), cause=e.cause)
            return
    extras = rest[0] if rest else {}
    if args.device_trace:
        # the artifact contract: at least one non-trivial xplane proto
        # must exist, or the run errors (an empty dir would let the
        # fill item mark "device trace captured" on a no-op)
        import glob as _glob

        planes = [p for p in _glob.glob(os.path.join(
            args.device_trace, "**", "*.xplane.pb"), recursive=True)
            if os.path.getsize(p) > 1024]
        if not planes:
            _emit_skip(metric, "device trace produced no xplane.pb "
                       "(PJRT profiler unsupported on this platform?)")
            return
        extras["device_trace_planes"] = [
            {"file": os.path.relpath(p, args.device_trace),
             "bytes": os.path.getsize(p)} for p in planes]

    # `metric` was resolved before the watchdog (same suffixed key on
    # error and success lines for the same command)
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_HISTORY.json")
    config_hash, run_config = run_config_fingerprint(metric, args, steps)
    line = report_line(metric, value, unit, extras,
                       history_path=hist_path, smoke=args.smoke,
                       dp=args.dp, config_hash=config_hash,
                       run_config=run_config)
    if os.environ.get("PT_BENCH_CPU_FALLBACK"):
        # this run is a device-init-timeout fallback: the number is a
        # CPU number and must never read as an accelerator record —
        # and trend tooling must refuse to diff it against on-chip
        # rows (BENCH_r05 polluted deltas exactly this way)
        line["backend"] = "cpu_fallback"
        line["backend_degraded"] = True
        line["cause"] = "device_init_timeout"
    print(json.dumps(line))


def report_line(metric, value, unit, extras, *, history_path, smoke,
                dp=1, device=None, config_hash=None, run_config=None):
    """Post-run reporting: history recording + regression contract + MFU.

    Separated from main() so the ACCELERATOR code path (history writes,
    regression warnings, MFU vs the peak table) is exercised by tests
    with a stand-in device BEFORE the first real chip session — the
    machinery must not meet hardware for the first time in production
    (VERDICT r2 'first on-chip session will shake out bugs' risk).
    ``device`` defaults to jax.devices()[0].
    """
    history = {}
    if os.path.exists(history_path):
        try:
            with open(history_path) as f:
                history = json.load(f)
        except Exception:
            history = {}
    if device is None:
        import jax

        device = jax.devices()[0]

    on_accelerator = device.platform != "cpu"
    import datetime

    vs_baseline, regression = evaluate_against_history(
        metric, value, history, on_accelerator=on_accelerator,
        record=not smoke,
        device_kind=getattr(device, "device_kind", None) or device.platform,
        config_hash=config_hash, config=run_config,
        now=datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"))
    if regression:
        # the baseline may live under a variant key; recover its value
        # from the ratio rather than assuming history[metric] holds it
        # (guarded: a 0.0 value yields vs_baseline 0.0)
        prev_str = (f"{value / vs_baseline:.2f}" if vs_baseline > 0
                    else "recorded baseline")
        print(f"WARNING: {metric} regressed >10% vs best recorded "
              f"({value:.2f} vs {prev_str} {unit})", file=sys.stderr)
    # regression-sentinel tie-in: arm from the LAST session's recorded
    # timings (the reserved "_sentinel" history section — underscore
    # keys never collide with metric names, the _superseded precedent),
    # feed this run's measured step time, and persist the updated
    # baselines back. A fresh bench session alarms on step-time drift
    # against the previous session instead of needing min_samples
    # warmup runs of its own.
    from paddle_tpu.telemetry import profiling as _profiling

    _profiling.seed_sentinel_from_history(history_path)
    perf_diag = None
    st_ms = extras.get("step_time_ms")
    if st_ms:
        perf_diag = _profiling.sentinel().observe(
            metric, device.platform, float(st_ms) / 1e3,
            degraded=bool(os.environ.get("PT_BENCH_CPU_FALLBACK")))
    if not smoke and on_accelerator:
        history[_profiling.SENTINEL_HISTORY_KEY] = (
            _profiling.sentinel_history_entry())
        # CPU debug runs never pollute the recorded trajectory
        with open(history_path, "w") as f:
            json.dump(history, f, indent=1)

    line = {"metric": metric, "value": round(value, 2), "unit": unit,
            "vs_baseline": round(vs_baseline, 4),
            # backend on EVERY line (main() overrides to "cpu_fallback"
            # after a device-init-timeout re-exec) so a reader never has
            # to infer which hardware a number came from
            "backend": device.platform,
            # fenced wall time per step/dispatch — the denominator the
            # mfu field divides FLOPs by; None when a bench predates it
            "step_time_ms": extras.get("step_time_ms")}
    # device-memory high-water mark of the run (telemetry.diag monitor):
    # null where the backend has no memory_stats() (CPU) — the
    # live-array fallback is an allocation view, never a peak, and must
    # not masquerade as one in recorded numbers
    try:
        from paddle_tpu.telemetry.diag import peak_memory_bytes

        line["peak_mem_bytes"] = peak_memory_bytes()
    except Exception:
        line["peak_mem_bytes"] = None
    # MFU: model FLOP/s (XLA cost model over the lowered step) / chip peak.
    # Reported only when both sides are known (never on CPU).
    from paddle_tpu.utils.flops import mfu as _mfu

    # latency percentiles from the inference harness, the
    # speculative-decode acceptance stats, the input-pipeline A/B
    # numbers, and the sharding-plan byte-budget evidence ride along
    # verbatim
    line.update({k: v for k, v in extras.items()
                 if k.startswith(("latency_ms_", "comm_", "parity_",
                                  "kv_", "max_sessions_",
                                  # router serving A/B: TTFT/ITL
                                  # percentiles, shed rates, and the
                                  # mono/overload comparison arms (+
                                  # the streaming arm, the prefix-hash
                                  # routing A/B, and the aot TTFR
                                  # cold-start A/B columns)
                                  "ttft_", "itl_", "mono_",
                                  "stream_", "prefix_", "ttfr_",
                                  # autoscale plane: replica-seconds
                                  # accounting + fleet timelines on
                                  # every router row; the spike A/B's
                                  # static-arm comparison columns and
                                  # scale-event/TTFR evidence
                                  "replica_", "autoscale_",
                                  "static_", "spike_",
                                  # reliability plane: the
                                  # gray-failure A/B's three-arm
                                  # comparison + breaker evidence
                                  "gray_",
                                  # sharded-embedding plane: wire
                                  # payload vs dense counterfactual,
                                  # host-cache hit rate, table rows
                                  "overload_", "emb_"))
                 or k in ("aot_artifact_id",
                          "accept_per_round", "rounds", "prefetch_off",
                          "prefetch_on", "overlap_speedup", "fsdp",
                          # checkpoint bench: save/recovery latency and
                          # the step-agreed transaction's barrier cost
                          "save_ms", "resume_restore_ms",
                          "commit_barrier_ms", "payload_mb",
                          "peak_mem_bytes_replicated",
                          "peak_mem_bytes_planned", "byte_budget",
                          "fits_budget_only_planned", "shard_ratio",
                          "session_ratio", "step_time_ms_fp32", "dp",
                          "shed_rate", "replicas", "prefill_workers",
                          "rate_rps",
                          # performance-attribution plane: fraction of
                          # serving capacity that emitted tokens
                          "goodput_ratio")})
    flops_per_sec = extras.get("flops_per_sec")
    line["mfu"] = None
    if flops_per_sec:
        line["tflops_per_sec"] = round(flops_per_sec / 1e12, 3)
        m = _mfu(flops_per_sec, device, n_devices=max(1, dp))
        if m is not None:
            line["mfu"] = round(m, 4)
    # Ledger-derived columns (performance-attribution plane): the
    # roofline verdict rides straight from the cost-registry record,
    # and the mfu above is AUDITED against it — the numerator must
    # equal ledger FLOPs x scale x dispatches / window or the row
    # refuses to print an mfu at all (``mfu_audit`` says why). A bench
    # whose flops source drifts from the registry can't quietly ship a
    # hand-rolled utilization number.
    prog = extras.get("ledger_program")
    if prog:
        rec = None
        try:
            from paddle_tpu.telemetry import costs as _tcosts

            rec = _tcosts.get(prog)
        except Exception:
            pass
        rl = (rec or {}).get("roofline") or {}
        if rl.get("verdict"):
            line["roofline"] = rl["verdict"]
            if rl.get("nominal"):
                line["roofline_nominal"] = True
        n_disp = extras.get("ledger_dispatches")
        window = extras.get("ledger_window_s")
        if flops_per_sec and n_disp and window:
            rec_flops = (rec or {}).get("flops")
            if not rec_flops:
                line["mfu"] = None
                line["mfu_audit"] = "no_ledger_record"
            else:
                expected = (rec_flops
                            * float(extras.get("ledger_scale") or 1.0)
                            * n_disp / window)
                if abs(expected - flops_per_sec) <= 0.02 * expected:
                    line["mfu_audit"] = "ledger"
                else:
                    line["mfu"] = None
                    line["mfu_audit"] = "ledger_mismatch"
    if regression:
        line["regression"] = True
    if perf_diag is not None:
        # the sentinel's step-TIME alarm rides the JSON line next to
        # the throughput regression flag (different denominators — a
        # batch-size change can move one without the other)
        line["perf_regression"] = str(perf_diag)
    return line


if __name__ == "__main__":
    main()
