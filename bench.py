#!/usr/bin/env python
"""Benchmark harness — fluid_benchmark.py analog (reference:
benchmark/fluid/fluid_benchmark.py:296-300 examples/sec metric).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` compares against the last recorded value in BENCH_HISTORY.json
(the reference publishes no numbers — BASELINE.md — so the baseline is our own
trajectory; >1.0 means faster than the previous record).

Usage: python bench.py [--smoke] [--model mnist_mlp]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def bench_mnist_mlp(steps: int, batch_size: int, warmup: int = 5):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import optimizer, parallel
    from paddle_tpu.models import mnist as M

    pt.seed(0)
    mesh = pt.build_mesh(dp=1, devices=jax.devices()[:1])
    model = M.MnistMLP(hidden1=512, hidden2=256)
    trainer = parallel.Trainer.supervised(
        model, optimizer.Adam(1e-3), M.loss_fn, mesh=mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch_size, 784)).astype(np.float32))
    label = jnp.asarray(rng.integers(0, 10, batch_size))
    batch = {"x": x, "label": label}
    for _ in range(warmup):
        loss, _ = trainer.train_step(batch)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = trainer.train_step(batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return steps * batch_size / dt, "examples/sec"


MODELS = {
    "mnist_mlp": bench_mnist_mlp,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="mnist_mlp", choices=sorted(MODELS))
    ap.add_argument("--smoke", action="store_true", help="quick run")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    args = ap.parse_args()

    steps = args.steps or (10 if args.smoke else 100)
    batch = args.batch_size or (256 if args.smoke else 8192)
    value, unit = MODELS[args.model](steps, batch)

    metric = f"{args.model}_throughput"
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_HISTORY.json")
    history = {}
    if os.path.exists(hist_path):
        try:
            with open(hist_path) as f:
                history = json.load(f)
        except Exception:
            history = {}
    prev = history.get(metric)
    vs_baseline = (value / prev) if prev else 1.0
    if not args.smoke:
        history[metric] = max(value, prev or 0.0)
        with open(hist_path, "w") as f:
            json.dump(history, f, indent=1)

    print(json.dumps({"metric": metric, "value": round(value, 2), "unit": unit,
                      "vs_baseline": round(vs_baseline, 4)}))


if __name__ == "__main__":
    main()
