"""Automatic mixed precision — capability parity with the reference's
mixed-precision decorator (reference:
python/paddle/fluid/contrib/mixed_precision/decorator.py:26
OptimizerWithMixedPrecision, :190 decorate; fp16_lists.py
AutoMixedPrecisionLists; fp16_utils.py cast helpers).

TPU-first stance: the default policy is ``mixed_bf16`` — fp32 master params,
bf16 compute on the MXU, fp32 loss — which needs NO loss scaling (bf16 has
fp32's exponent range). ``mixed_fp16`` exists for porting fp16 recipes and
engages static/dynamic loss scaling with non-finite-step skipping, exactly
the reference's decorator semantics. Master weights are inherent to the
functional design: the optimizer state and params stay fp32; casting happens
at layer boundaries via the dtype policy (core/dtypes.py Policy).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp

from .core.dtypes import POLICIES, Policy, get_policy, policy_scope, set_policy
from .core.enforce import enforce
from .optimizer.loss_scaler import DynamicLossScaler
from .optimizer.optimizers import Optimizer

# Reference fp16_lists.py: ops safe in half precision (matmul/conv heavy —
# MXU targets), ops that must stay fp32 (reductions prone to overflow), and
# gray ops that follow their inputs. Here the lists document + drive layer
# policy decisions (op_should_run_fp32) rather than a graph rewrite.
WHITE_LIST: Set[str] = {
    "conv2d", "conv3d", "matmul", "mul", "fc", "depthwise_conv2d",
    "conv2d_transpose", "attention",
}
BLACK_LIST: Set[str] = {
    "exp", "log", "square", "softmax", "log_softmax", "mean", "sum",
    "cross_entropy", "softmax_with_cross_entropy", "cos_sim", "layer_norm",
    "batch_norm", "group_norm", "l2_normalize", "reduce_sum", "reduce_mean",
}


class AutoMixedPrecisionLists:
    """White/black op-name lists with custom overrides (reference:
    contrib/mixed_precision/fp16_lists.py)."""

    def __init__(self, custom_white_list: Optional[Set[str]] = None,
                 custom_black_list: Optional[Set[str]] = None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list:
            for op in custom_white_list:
                enforce(op not in (custom_black_list or ()),
                        "op %s in both custom white and black lists", op)
                self.black_list.discard(op)
                self.white_list.add(op)
        if custom_black_list:
            for op in custom_black_list:
                self.white_list.discard(op)
                self.black_list.add(op)

    def should_run_fp32(self, op_name: str) -> bool:
        return op_name in self.black_list


def amp_guard(policy="mixed_bf16"):
    """Context manager enabling a mixed-precision policy for the scope
    (trace-time; the jitted function bakes the policy in)."""
    return policy_scope(policy)


class MixedPrecisionOptimizer(Optimizer):
    """Wraps an optimizer with loss scaling + nonfinite-step skipping
    (reference: decorator.py OptimizerWithMixedPrecision.minimize —
    scaled loss, check_finite_and_unscale, update_loss_scaling).

    Usage in a manual loop:
        state = opt.init(params)
        loss = opt.scale_loss(raw_loss, state)     # inside grad closure
        params, state = opt.apply(params, scaled_grads, state)
    ``apply`` unscales the grads, applies the inner update only when all
    grads are finite, and updates the loss-scale state.
    """

    def __init__(self, inner: Optimizer, init_loss_scaling: float = 2.0 ** 15,
                 use_dynamic_loss_scaling: bool = True,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5):
        self.inner = inner
        self.use_dynamic = use_dynamic_loss_scaling
        self.scaler = DynamicLossScaler(
            init_scale=init_loss_scaling,
            incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio)

    # -- Optimizer interface -------------------------------------------------

    def init(self, params):
        return {"inner": self.inner.init(params),
                "scaler": self.scaler.init()}

    def scale_loss(self, loss, state):
        return loss * state["scaler"]["scale"].astype(loss.dtype)

    def current_scale(self, state):
        return state["scaler"]["scale"]

    def current_lr(self, state):
        return self.inner.current_lr(state["inner"])

    def apply(self, params, grads, state):
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)  # master-grad precision
        unscaled, scaler_state, is_finite = self.scaler.unscale_and_update(
            grads, state["scaler"])
        if not self.use_dynamic:
            # static scaling: keep the scale constant, only the skip logic
            scaler_state = dict(scaler_state,
                                scale=state["scaler"]["scale"])
        cand_params, cand_inner = self.inner.apply(params, unscaled,
                                                   state["inner"])
        pick = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(is_finite, n, o), new, old)
        return (pick(cand_params, params),
                {"inner": pick(cand_inner, state["inner"]),
                 "scaler": scaler_state})


def decorate(optimizer: Optimizer,
             amp_lists: Optional[AutoMixedPrecisionLists] = None,
             init_loss_scaling: float = 2.0 ** 15,
             use_dynamic_loss_scaling: bool = True,
             policy: str = "mixed_fp16",
             **scaler_kw) -> MixedPrecisionOptimizer:
    """reference: contrib/mixed_precision/decorator.py:190 ``decorate`` —
    returns an optimizer with mixed-precision training enabled. Also sets the
    global compute policy (bf16 policies never need the scaler but get the
    same wrapper so train loops are policy-agnostic)."""
    set_policy(policy)
    if amp_lists is not None:
        # lists are advisory on TPU (XLA decides fusions); retained for
        # API parity and for layers that consult them
        pass
    return MixedPrecisionOptimizer(
        optimizer, init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling, **scaler_kw)


def cast_params(params, dtype=jnp.bfloat16):
    """fp16_utils cast helper analog: cast floating leaves (for export or
    pure-half inference)."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
