"""Gradient clipping — capability parity with the reference clip module
(reference: python/paddle/fluid/clip.py — GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm, ErrorClipByValue).

Each clip is a callable ``grads_pytree -> grads_pytree``, pluggable into
``Optimizer(grad_clip=...)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class GradientClipByValue:
    def __init__(self, max: float, min: float = None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class GradientClipByNorm:
    """Per-tensor L2 clip (reference: clip.py GradientClipByNorm)."""

    def __init__(self, clip_norm: float):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            return jnp.where(norm > self.clip_norm,
                             g * (self.clip_norm / norm), g)

        return jax.tree_util.tree_map(clip_one, grads)


class GradientClipByGlobalNorm:
    """Global-norm clip (reference: clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm: float):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        global_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves)
        gnorm = jnp.sqrt(global_sq)
        factor = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return jax.tree_util.tree_map(
            lambda g: g * factor.astype(g.dtype), grads)


def global_norm(grads) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


class ErrorClipByValue:
    """reference: clip.py ErrorClipByValue — clip a single tensor."""

    def __init__(self, max: float, min: float = None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, x):
        return jnp.clip(x, self.min, self.max)
