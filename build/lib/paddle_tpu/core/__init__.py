"""Core runtime: config, devices, mesh, dtypes, randomness, errors, tracing."""

from .config import FLAGS, BuildStrategy, DistributeConfig, ExecutionStrategy
from .dtypes import Policy, get_policy, policy_scope, set_policy, to_dtype
from .enforce import (EnforceError, InvalidArgumentError, NotFoundError,
                      UnimplementedError, enforce, enforce_eq, enforce_in)
from .mesh import (AXIS_NAMES, auto_mesh, axis_size, build_hybrid_mesh,
                   build_mesh, build_multihost_mesh, get_mesh,
                   mesh_scope, replicated, set_mesh, sharding)
from .places import (CPUPlace, Place, TPUPlace, default_place, device_count,
                     device_pool, is_compiled_with_tpu, set_device)
from .profiler import RecordEvent, profiler, start_profiler, stop_profiler
from .random import get_seed, next_key, seed

__all__ = [
    "FLAGS", "BuildStrategy", "DistributeConfig", "ExecutionStrategy",
    "Policy", "get_policy", "policy_scope", "set_policy", "to_dtype",
    "EnforceError", "InvalidArgumentError", "NotFoundError",
    "UnimplementedError", "enforce", "enforce_eq", "enforce_in",
    "AXIS_NAMES", "auto_mesh", "axis_size", "build_hybrid_mesh",
    "build_mesh", "build_multihost_mesh", "get_mesh",
    "mesh_scope", "replicated", "set_mesh", "sharding",
    "CPUPlace", "Place", "TPUPlace", "default_place", "device_count",
    "device_pool", "is_compiled_with_tpu", "set_device",
    "RecordEvent", "profiler", "start_profiler", "stop_profiler",
    "get_seed", "next_key", "seed",
]
