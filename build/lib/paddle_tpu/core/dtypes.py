"""Dtype registry and mixed-precision policy.

Parity targets: the reference's dtype enum (framework.proto VarType.Type),
``platform::float16`` (reference: paddle/fluid/platform/float16.h) and the
mixed-precision decorator (reference:
python/paddle/fluid/contrib/mixed_precision/decorator.py:26,190).

TPU-first stance: bfloat16 is the native half type (no loss scaling needed);
a Policy captures (param_dtype, compute_dtype, output_dtype). An fp16-compat
mode with dynamic loss scaling exists for capability parity in
``paddle_tpu.optimizer.loss_scaler``.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Union

import jax.numpy as jnp
import numpy as np

from .enforce import enforce

# Canonical name -> jnp dtype. Mirrors VarType.Type coverage.
_DTYPES = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
}

DTypeLike = Union[str, np.dtype, type]


def to_dtype(d: DTypeLike):
    if isinstance(d, str):
        enforce(d in _DTYPES, "unknown dtype name %s", d)
        return jnp.dtype(_DTYPES[d])
    return jnp.dtype(d)


def is_floating(d: DTypeLike) -> bool:
    return jnp.issubdtype(to_dtype(d), jnp.floating)


def is_integer(d: DTypeLike) -> bool:
    return jnp.issubdtype(to_dtype(d), jnp.integer)


@dataclasses.dataclass(frozen=True)
class Policy:
    """Mixed-precision policy: where each dtype applies."""

    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    output_dtype: str = "float32"

    def cast_to_compute(self, x):
        return _cast_floating(x, to_dtype(self.compute_dtype))

    def cast_to_output(self, x):
        return _cast_floating(x, to_dtype(self.output_dtype))


# Named policies. "mixed_bf16" is the TPU default for training at scale:
# fp32 master params, bf16 compute (MXU-native), fp32 outputs/loss.
POLICIES = {
    "float32": Policy(),
    "bfloat16": Policy("bfloat16", "bfloat16", "bfloat16"),
    "mixed_bf16": Policy("float32", "bfloat16", "float32"),
    "mixed_fp16": Policy("float32", "float16", "float32"),
}

_current_policy = POLICIES["float32"]


def get_policy() -> Policy:
    return _current_policy


def set_policy(p: Union[str, Policy]) -> Policy:
    global _current_policy
    if isinstance(p, str):
        enforce(p in POLICIES, "unknown policy %s", p)
        p = POLICIES[p]
    _current_policy = p
    return p


@contextlib.contextmanager
def policy_scope(p: Union[str, Policy]):
    prev = get_policy()
    set_policy(p)
    try:
        yield get_policy()
    finally:
        set_policy(prev)


def _cast_floating(x, dtype):
    import jax

    def cast_leaf(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(cast_leaf, x)


def default_dtype():
    from .config import FLAGS

    return to_dtype(FLAGS.get("default_dtype"))
