"""Error-checking helpers.

Capability parity with the reference's ``PADDLE_ENFORCE`` macro family
(reference: paddle/fluid/platform/enforce.h:245) — but implemented as plain
Python raising typed exceptions; stack traces come for free.
"""

from __future__ import annotations

from typing import Any, NoReturn


class EnforceError(RuntimeError):
    """Raised when an ``enforce`` condition fails (PADDLE_ENFORCE analog)."""


class NotFoundError(EnforceError):
    pass


class InvalidArgumentError(EnforceError, ValueError):
    pass


class UnimplementedError(EnforceError, NotImplementedError):
    pass


def enforce(cond: Any, msg: str = "", *args: Any) -> None:
    """Raise :class:`EnforceError` unless ``cond`` is truthy.

    ``msg`` may be a format string applied to ``*args`` (lazily, so hot paths
    pay nothing when the condition holds).
    """
    if not cond:
        raise EnforceError(msg % args if args else (msg or "enforce failed"))


def enforce_eq(a: Any, b: Any, msg: str = "") -> None:
    if a != b:
        raise EnforceError(f"enforce_eq failed: {a!r} != {b!r}. {msg}")


def enforce_in(item: Any, container: Any, msg: str = "") -> None:
    if item not in container:
        raise EnforceError(f"enforce_in failed: {item!r} not in {container!r}. {msg}")


def not_found(msg: str) -> NoReturn:
    raise NotFoundError(msg)


def invalid_argument(msg: str) -> NoReturn:
    raise InvalidArgumentError(msg)


def unimplemented(msg: str) -> NoReturn:
    raise UnimplementedError(msg)
