"""Global device-mesh management — the backbone of all parallelism.

This replaces the reference's entire multi-device plumbing:
``NCCLContextMap`` (reference: paddle/fluid/platform/nccl_helper.h:90),
``ParallelExecutor`` device lists (reference: framework/parallel_executor.cc:195)
and ``gen_nccl_id`` bootstrap (reference:
operators/distributed_ops/gen_nccl_id_op.cc:43-59). On TPU, collectives are
compiler-inserted over a named :class:`jax.sharding.Mesh`; this module owns the
canonical axis names and a process-global current mesh.

Canonical axis names (fixed vocabulary so sharding rules compose):
  - "dp": data parallel            - "tp": tensor (model) parallel
  - "pp": pipeline parallel        - "sp": sequence/context parallel
  - "ep": expert parallel
"""

from __future__ import annotations

import contextlib
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .config import DistributeConfig
from .enforce import enforce

AXIS_NAMES = ("dp", "pp", "tp", "sp", "ep")

_current_mesh: Optional[Mesh] = None


def build_mesh(
    dp: int = 1,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    ep: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over ``devices`` (default: all).

    Axis order is (dp, pp, tp, sp, ep): the innermost axes (tp/sp) get
    ICI-adjacent devices so tensor/sequence collectives ride the fastest links;
    dp/pp span the outer (possibly DCN) dimension — the standard scaling-book
    layout.

    Degenerate (size-1) axes are kept in the mesh so sharding rules can always
    name every axis regardless of the active parallelism.
    """
    sizes = {"dp": dp, "pp": pp, "tp": tp, "sp": sp, "ep": ep}
    for name, s in sizes.items():
        enforce(s >= 1, "axis %s must be >= 1, got %s", name, s)
    if devices is None:
        devices = jax.devices()
    total = dp * tp * pp * sp * ep
    enforce(
        total == len(devices),
        "mesh size %s != device count %s", total, len(devices),
    )
    dev_array = np.asarray(devices).reshape(dp, pp, tp, sp, ep)
    return Mesh(dev_array, axis_names=("dp", "pp", "tp", "sp", "ep"))


def build_multihost_mesh(
    world_size: int,
    *,
    dcn_axis: str = "dp",
    dp: int = 1,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh whose ``dcn_axis`` spans the host (process) dimension.

    ``jax.devices()`` orders devices process-major, so the plain
    :func:`build_mesh` reshape always puts the OUTERMOST axis (dp) across
    hosts. The reference's NCCL2 mode proved its collectives across real
    processes (reference: transpiler _transpile_nccl2,
    tests/unittests/test_dist_base.py:545); here ANY axis can be the one
    that rides DCN: the chosen axis is split (world, size/world) with the
    process dimension outermost, so its collectives decompose into
    intra-host ICI plus one inter-host DCN exchange, and all other axes
    stay host-local.

    ``dcn_axis='dp'`` reproduces :func:`build_mesh`'s layout exactly.
    """
    sizes = {"dp": dp, "pp": pp, "tp": tp, "sp": sp, "ep": ep}
    order = ("dp", "pp", "tp", "sp", "ep")
    enforce(dcn_axis in sizes, "unknown mesh axis %r", dcn_axis)
    enforce(world_size >= 1 and sizes[dcn_axis] % world_size == 0,
            "%s axis size %s must divide by world size %s to span hosts",
            dcn_axis, sizes[dcn_axis], world_size)
    if devices is None:
        devices = jax.devices()
    total = dp * pp * tp * sp * ep
    enforce(total == len(devices),
            "mesh size %s != device count %s", total, len(devices))
    k = order.index(dcn_axis)
    local_shape = [sizes[a] for a in order]
    local_shape[k] //= world_size
    # (world, per-host mesh) → move the host dim next to its axis's local
    # part → merge: axis index = host * local + j (host outermost)
    arr = np.asarray(devices).reshape([world_size] + local_shape)
    arr = np.moveaxis(arr, 0, k)
    arr = arr.reshape([sizes[a] for a in order])
    return Mesh(arr, axis_names=order)


def from_config(cfg: DistributeConfig, devices=None) -> Mesh:
    return build_mesh(dp=cfg.dp, tp=cfg.tp, pp=cfg.pp, sp=cfg.sp, ep=cfg.ep,
                      devices=devices)


def auto_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Pure-DP mesh over all devices — the ParallelExecutor default
    (reference: compiler.py:117 with_data_parallel)."""
    if devices is None:
        devices = jax.devices()
    return build_mesh(dp=len(devices), devices=devices)


def get_mesh() -> Mesh:
    """Current global mesh; lazily a 1-chip (or all-device DP) mesh."""
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    return _current_mesh


def set_mesh(mesh: Mesh) -> Mesh:
    global _current_mesh
    _current_mesh = mesh
    return mesh


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    global _current_mesh
    prev = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = prev


def axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return int(mesh.shape.get(name, 1))


def sharding(spec: PartitionSpec, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    return sharding(PartitionSpec(), mesh)


def data_sharding(mesh: Optional[Mesh] = None, batch_axes=("dp",)) -> NamedSharding:
    """Sharding for a host batch: leading dim split over dp (and sp if used)."""
    return sharding(PartitionSpec(batch_axes), mesh)


def build_hybrid_mesh(
    dcn_dp: int = 1,
    *,
    dp: int = 1,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: an outer data-parallel axis over DCN (slices /
    hosts) and inner ICI axes within each slice (SURVEY §5.8: same
    collectives over the DCN mesh axis; compiler-partitioned — the
    scaling-book hybrid layout, jax mesh_utils.create_hybrid_device_mesh
    role).

    The total dp axis becomes ``dcn_dp * dp`` with DCN-adjacent devices
    outermost, so gradient all-reduces decompose into intra-slice ICI
    reductions + a small inter-slice DCN exchange. Device order: JAX sorts
    ``jax.devices()`` by (process, local id), which already groups
    slice-local devices contiguously — the reshape below relies on that.
    """
    if devices is None:
        devices = jax.devices()
    inner = dp * tp * pp * sp * ep
    enforce(dcn_dp * inner == len(devices),
            "hybrid mesh %s x %s != %s devices", dcn_dp, inner,
            len(devices))
    dev_array = np.asarray(devices).reshape(dcn_dp, dp, pp, tp, sp, ep)
    dev_array = dev_array.reshape(dcn_dp * dp, pp, tp, sp, ep)
    return Mesh(dev_array, axis_names=("dp", "pp", "tp", "sp", "ep"))
