"""Device abstraction: Place over JAX devices.

Capability parity with the reference's ``platform::Place`` variant
(reference: paddle/fluid/platform/place.h:26,37,52,81) and
``DeviceContextPool`` (reference: platform/device_context.h:408).

On TPU there are no user-managed streams or handles — PJRT owns them — so a
Place is a thin, hashable handle resolving to a ``jax.Device``. The pool
analog is :func:`device_pool`, a cached view of all local devices.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional

import jax

from .enforce import enforce, not_found


@dataclasses.dataclass(frozen=True)
class Place:
    """A logical device handle: ``kind`` in {"cpu", "tpu"} plus ordinal."""

    kind: str
    ordinal: int = 0

    def device(self) -> jax.Device:
        devs = _devices_of_kind(self.kind)
        if self.ordinal >= len(devs):
            not_found(f"no {self.kind} device with ordinal {self.ordinal} "
                      f"(found {len(devs)})")
        return devs[self.ordinal]

    def __repr__(self) -> str:  # mirrors Place printing, e.g. TPUPlace(0)
        return f"{self.kind.upper()}Place({self.ordinal})"


def CPUPlace(ordinal: int = 0) -> Place:
    return Place("cpu", ordinal)


def TPUPlace(ordinal: int = 0) -> Place:
    return Place("tpu", ordinal)


@functools.lru_cache(maxsize=None)
def _devices_of_kind(kind: str) -> tuple:
    if kind == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple()
    # "tpu": any accelerator backend (tpu or the axon tunnel platform).
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        devs = list(jax.devices())  # CPU-only simulation: every device plays TPU
    return tuple(devs)


def device_pool(kind: Optional[str] = None) -> List[Place]:
    """All local places of ``kind`` (default: accelerator if present else cpu).

    DeviceContextPool analog (reference: platform/device_context.h:408).
    """
    if kind is None:
        kind = "tpu" if is_compiled_with_tpu() else "cpu"
    return [Place(kind, i) for i in range(len(_devices_of_kind(kind)))]


def is_compiled_with_tpu() -> bool:
    """True when a non-CPU accelerator backend is live (CUDA-availability analog,
    reference: pybind.cc is_compiled_with_cuda)."""
    return any(d.platform != "cpu" for d in jax.devices())


def default_place() -> Place:
    return TPUPlace(0) if is_compiled_with_tpu() else CPUPlace(0)


def device_count(kind: Optional[str] = None) -> int:
    return len(device_pool(kind))


def set_device(place: Place):
    """Make ``place`` the default for uncommitted arrays (InitDevices-adjacent,
    reference: platform/init.h:29)."""
    enforce(place.device() is not None, "invalid place %s", place)
    jax.config.update("jax_default_device", place.device())
    return place
