"""Tracing / profiling: RecordEvent spans + chrome-trace export + jax.profiler.

Parity targets (SURVEY §5.1):
  - RAII ``RecordEvent`` (reference: paddle/fluid/platform/profiler.h:81)
  - python ``fluid.profiler.profiler`` context (reference:
    python/paddle/fluid/profiler.py:222)
  - ``tools/timeline.py`` chrome://tracing export (reference: tools/timeline.py:131)

Host-side spans are collected in-process and exported directly as chrome-trace
JSON (no intermediate proto — the proto existed to cross the C++/Python
boundary, which we don't have). Device-side tracing delegates to
``jax.profiler`` (XPlane/ TensorBoard), the TPU analog of CUPTI.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_enabled = False


class RecordEvent:
    """Context-manager span recorder; also annotates device traces via
    ``jax.profiler.TraceAnnotation`` so spans appear in XPlane timelines."""

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        if _enabled:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if _enabled:
            with _lock:
                _events.append({
                    "name": self.name,
                    "ph": "X",
                    "ts": self._t0 / 1e3,  # chrome trace wants microseconds
                    "dur": (t1 - self._t0) / 1e3,
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 100000,
                })
        return False


def record_event(name: str) -> RecordEvent:
    return RecordEvent(name)


def start_profiler(device_trace_dir: Optional[str] = None) -> None:
    """Begin collecting host spans; optionally also start a jax device trace."""
    global _enabled
    with _lock:
        _events.clear()
    _enabled = True
    if device_trace_dir:
        jax.profiler.start_trace(device_trace_dir)


def stop_profiler(timeline_path: Optional[str] = None,
                  device_trace: bool = False) -> List[Dict[str, Any]]:
    """Stop collection; optionally write chrome-trace JSON (timeline.py analog)."""
    global _enabled
    _enabled = False
    if device_trace:
        jax.profiler.stop_trace()
    with _lock:
        events = list(_events)
    if timeline_path:
        export_chrome_trace(events, timeline_path)
    return events


def export_chrome_trace(events: List[Dict[str, Any]], path: str) -> None:
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


@contextlib.contextmanager
def profiler(timeline_path: Optional[str] = None,
             device_trace_dir: Optional[str] = None):
    """``with profiler("/tmp/timeline.json"):`` — fluid.profiler.profiler analog."""
    start_profiler(device_trace_dir)
    try:
        yield
    finally:
        stop_profiler(timeline_path, device_trace=device_trace_dir is not None)
