"""Global seed / PRNG-key management.

The reference threads integer seeds through programs (``Program.random_seed``,
reference: python/paddle/fluid/framework.py Program.random_seed; per-op seed
attrs on dropout/uniform_random). JAX is functional: randomness is an explicit
key. This module bridges the two — a global seed (settable like the reference)
from which fresh subkeys are split for eager use, while traced code takes keys
explicitly.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

_lock = threading.Lock()
_seed: int = 0
_key: Optional[jax.Array] = None
_counter: int = 0


def seed(s: int) -> None:
    """Set the global seed (analog of fluid's Program.random_seed)."""
    global _seed, _key, _counter
    with _lock:
        _seed = int(s)
        _key = jax.random.key(_seed)
        _counter = 0


def get_seed() -> int:
    return _seed


def next_key(n: int = 1):
    """Split fresh subkey(s) off the global stream (eager-mode use only)."""
    global _key, _counter
    with _lock:
        if _key is None:
            _key = jax.random.key(_seed)
        _key, *subs = jax.random.split(_key, n + 1)
        _counter += n
    return subs[0] if n == 1 else subs


def key_for(name: str, base_key: Optional[jax.Array] = None) -> jax.Array:
    """Derive a named key deterministically (trace-safe: fold a stable hash of
    the name into the key). Uses crc32, not Python hash(), so every process /
    host derives the same key for the same name — required for SPMD."""
    import zlib

    k = base_key if base_key is not None else jax.random.key(_seed)
    return jax.random.fold_in(k, zlib.crc32(name.encode()) & 0x7FFFFFFF)
