"""Length bucketing — the recompilation-management half of the LoD
replacement (SURVEY §7 hard parts: "the reference re-interprets any shape;
XLA recompiles. Need shape bucketing + compile cache").

Variable-length samples are grouped into a FIXED set of length buckets;
each bucket pads to its boundary, so a whole training run compiles at most
``len(boundaries)`` step shapes regardless of the data distribution. The
reference's LoD machinery avoided padding entirely at the cost of dynamic
shapes (framework/lod_tensor.h:229); this is the static-shape dual.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from ..core.enforce import enforce


def quantile_boundaries(lengths: Sequence[int], num_buckets: int,
                        round_to: int = 8) -> List[int]:
    """Pick bucket boundaries at length quantiles (rounded up to a
    lane-friendly multiple) — balances samples per bucket."""
    enforce(num_buckets >= 1, "num_buckets must be >= 1")
    ls = np.asarray(sorted(lengths))
    qs = [ls[min(int(len(ls) * (i + 1) / num_buckets), len(ls) - 1)]
          for i in range(num_buckets)]
    out: List[int] = []
    for q in qs:
        b = int(-(-int(q) // round_to) * round_to)
        if not out or b > out[-1]:
            out.append(b)
    return out


def round_to_bucket(n: int, buckets) -> int:
    """Round a length UP to its bucket boundary — the single source of
    boundary semantics shared by bucket_by_length and DataFeeder's
    padded-sequence path. ``buckets``: "pow2" rounds to the next power
    of two; an ascending list picks the first boundary >= n; a length
    beyond the last boundary returns n unchanged (exact padding — the
    caller decides whether that's a drop, like bucket_by_length, or an
    accepted recompile, like the feeder)."""
    if buckets is None:
        return n
    if buckets == "pow2":
        b = 1
        while b < n:
            b *= 2
        return b
    for bound in buckets:
        if n <= bound:
            return int(bound)
    return n


def pad_to(sample: np.ndarray, length: int, pad_value=0) -> np.ndarray:
    """Pad axis 0 of one sample to ``length``."""
    sample = np.asarray(sample)
    enforce(sample.shape[0] <= length,
            "sample length %s exceeds bucket %s", sample.shape[0], length)
    pad = [(0, length - sample.shape[0])] + [(0, 0)] * (sample.ndim - 1)
    return np.pad(sample, pad, constant_values=pad_value)


def bucket_by_length(reader: Callable[[], Iterator],
                     boundaries: Sequence[int],
                     batch_size: int,
                     length_of: Optional[Callable] = None,
                     pad_value=0,
                     drop_long: bool = False) -> Callable[[], Iterator]:
    """Reader decorator (composes with paddle_tpu.data.reader decorators):
    group samples by length bucket and yield dict batches
    ``{"data": (B, bucket_len, ...), "lengths": (B,)}`` — one static shape
    per bucket.

    ``length_of(sample)`` defaults to ``len(sample)`` (or of its first
    field when the sample is a tuple — remaining fields are carried
    per-sample in "extras"). Samples longer than the last boundary raise
    (or are dropped with ``drop_long``).
    """
    bounds = list(boundaries)
    enforce(bounds == sorted(bounds) and len(set(bounds)) == len(bounds),
            "boundaries must be strictly increasing, got %s", bounds)

    def get_len(sample):
        if length_of is not None:
            return length_of(sample)
        if isinstance(sample, tuple):
            return len(sample[0])
        return len(sample)

    def bucket_of(n: int) -> int:
        for i, b in enumerate(bounds):
            if n <= b:
                return i
        return -1

    def gen():
        pending: List[List] = [[] for _ in bounds]
        for sample in reader():
            n = get_len(sample)
            i = bucket_of(n)
            if i < 0:
                if drop_long:
                    continue
                enforce(False, "sample length %s exceeds largest bucket %s "
                        "(use drop_long=True to skip)", n, bounds[-1])
            pending[i].append(sample)
            if len(pending[i]) == batch_size:
                yield _emit(pending[i], bounds[i])
                pending[i] = []
        for i, bucket in enumerate(pending):  # flush remainders
            if bucket:
                yield _emit(bucket, bounds[i])

    def _emit(samples: List, bound: int):
        first_tuple = isinstance(samples[0], tuple)
        seqs = [s[0] if first_tuple else s for s in samples]
        lengths = np.asarray([len(s) for s in seqs], np.int32)
        data = np.stack([pad_to(np.asarray(s), bound, pad_value)
                         for s in seqs])
        out = {"data": data, "lengths": lengths}
        if first_tuple and len(samples[0]) > 1:
            out["extras"] = [s[1:] for s in samples]
        return out

    return gen


def compile_shape_count(batches: Iterable[dict]) -> int:
    """Distinct (B, T) shapes a stream produces — the number of XLA
    recompiles a jitted step would pay. Diagnostic used in tests."""
    return len({b["data"].shape for b in batches})


def pack_sequences(reader: Callable[[], Iterator], capacity: int,
                   batch_size: int, pad_value=0,
                   min_fill: float = 0.0) -> Callable[[], Iterator]:
    """Greedy sequence PACKING — the padding-free dual of bucketing.

    Multiple variable-length sequences share one fixed-length row of
    ``capacity`` tokens; attention stays correct via the emitted
    per-token segment ids (ops.attention segment_ids → the Pallas flash
    kernel's packed-batch path). Bucketing bounds recompilation by
    padding each sample up; packing removes the padding waste entirely —
    the layout pretraining pipelines use. Capability lineage: the
    reference's LoD layout also stored sequences back-to-back without
    padding (framework/lod_tensor.h:229); this is that idea made
    static-shape.

    ``reader`` yields 1-D int/float sequences (len <= capacity; longer
    ones raise). Yields dicts with fixed shapes (batch_size, capacity):
      tokens       the packed rows (padded tail with ``pad_value``)
      segment_ids  1-based segment id per token, 0 = padding tail
      positions    position WITHIN each segment (for position embeddings)
    A row closes when the next sequence does not fit; a batch closes when
    ``batch_size`` rows are full. ``min_fill`` (0..1) applies to the
    FINAL flushed batch only: it is dropped when its used-token fraction
    falls below the floor (0 keeps everything). Mid-stream batches are
    always kept — their density is governed by packing, not stream end.
    """
    enforce(capacity >= 1 and batch_size >= 1,
            "capacity and batch_size must be >= 1")
    enforce(0.0 <= min_fill <= 1.0,
            "min_fill must be in [0, 1], got %s", min_fill)

    def gen():
        rows: List[List[np.ndarray]] = []
        cur: List[np.ndarray] = []
        used = 0

        def close_row():
            nonlocal cur, used
            if cur:
                rows.append(cur)
                cur, used = [], 0

        def emit(batch_rows, final=False):
            # buffer dtype follows the data (float sequences stay float),
            # widened as needed to also hold pad_value exactly
            dt = np.result_type(np.min_scalar_type(pad_value),
                                *(s.dtype for seqs in batch_rows
                                  for s in seqs))
            tokens = np.full((batch_size, capacity), pad_value, dtype=dt)
            segs = np.zeros((batch_size, capacity), np.int32)
            poss = np.zeros((batch_size, capacity), np.int32)
            n_used = 0
            for r, seqs in enumerate(batch_rows):
                off = 0
                for si, s in enumerate(seqs):
                    L = len(s)
                    tokens[r, off:off + L] = s
                    segs[r, off:off + L] = si + 1  # 0 marks padding
                    poss[r, off:off + L] = np.arange(L)
                    off += L
                n_used += off
            if final and n_used < min_fill * batch_size * capacity:
                return None  # final partial batch below the fill floor
            return {"tokens": tokens, "segment_ids": segs,
                    "positions": poss}

        for seq in reader():
            s = np.asarray(seq)
            enforce(s.ndim == 1, "pack_sequences packs 1-D sequences, "
                    "got shape %s", s.shape)
            enforce(len(s) <= capacity,
                    "sequence length %s exceeds capacity %s (truncate or "
                    "raise capacity)", len(s), capacity)
            if used + len(s) > capacity:
                close_row()
            cur.append(s)
            used += len(s)
            if len(rows) == batch_size:
                # mid-stream batches always yield (emit only returns
                # None on the min_fill-checked final flush)
                yield emit(rows)
                rows.clear()
        close_row()
        if rows:
            out = emit(rows, final=True)
            if out is not None:
                yield out

    return gen
