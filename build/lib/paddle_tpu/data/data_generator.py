"""MultiSlot data generator — capability parity with the reference's
dataset-file producer (reference: python/paddle/fluid/incubate/
data_generator/__init__.py MultiSlotDataGenerator — user subclasses yield
(slot_name, values) samples; the generator serializes them into the text
format the C++ DataFeed parses, reference: framework/data_feed.cc
MultiSlotDataFeed::ParseOneInstance).

The emitted format is exactly what ``paddle_tpu.native.MultiSlotFeed``
(native/src/datafeed.cc) consumes:
  one sample per line; for each declared slot: ``<n> v_1 ... v_n``.
"""

from __future__ import annotations

import sys
from typing import Callable, Iterable, Iterator, List, Sequence, Tuple

from ..core.enforce import enforce

Sample = Sequence[Tuple[str, Sequence]]


class MultiSlotDataGenerator:
    """Subclass and implement ``generate_sample(line)`` returning an
    iterator of samples, each a list of (slot_name, values) in slot order
    (reference API: data_generator.__init__ run_from_stdin/run_from_files).
    """

    def __init__(self):
        self._slots: List[str] = []

    def set_slots(self, slots: Sequence[str]) -> None:
        self._slots = list(slots)

    # -- user hook -----------------------------------------------------------

    def generate_sample(self, line: str) -> Iterator[Sample]:
        raise NotImplementedError

    # -- serialization -------------------------------------------------------

    def _format_sample(self, sample: Sample) -> str:
        if self._slots:
            names = [name for name, _ in sample]
            enforce(names == self._slots,
                    "sample slots %s != declared %s", names, self._slots)
        parts = []
        for _, values in sample:
            vals = list(values)
            enforce(len(vals) > 0, "empty slot in sample")
            parts.append(str(len(vals)))
            parts.extend(str(v) for v in vals)
        return " ".join(parts)

    # -- drivers (reference: run_from_stdin / batch file production) ---------

    def run_from_stdin(self) -> None:
        for line in sys.stdin:
            for sample in self.generate_sample(line):
                sys.stdout.write(self._format_sample(sample) + "\n")

    def run_from_files(self, input_files: Sequence[str],
                       output_file: str) -> int:
        n = 0
        with open(output_file, "w") as out:
            for path in input_files:
                with open(path) as f:
                    for line in f:
                        for sample in self.generate_sample(line):
                            out.write(self._format_sample(sample) + "\n")
                            n += 1
        return n

    def run_from_iterable(self, samples: Iterable[Sample],
                          output_file: str) -> int:
        """Write already-built samples (no parse hook needed)."""
        n = 0
        with open(output_file, "w") as out:
            for sample in samples:
                out.write(self._format_sample(sample) + "\n")
                n += 1
        return n
