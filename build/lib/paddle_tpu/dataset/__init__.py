"""``paddle_tpu.dataset`` — the ``paddle.dataset.*`` loader suite
(reference: python/paddle/dataset/, 14 modules — SURVEY §2 layer 12).

Same module names, same reader-creator contracts (``train()``/``test()``
return sample generators; vocab helpers return dicts). Loading order per
module: a cached copy under ``common.DATA_HOME`` if present → otherwise a
DETERMINISTIC synthetic dataset with the real shapes/dtypes/vocab sizes
(this environment has no network egress; the download helper explains
that). Synthetic corpora are class-conditional/learnable so convergence
smoke tests remain meaningful (tests/book pattern, SURVEY §4).
"""

from . import (cifar, common, conll05, flowers, image, imdb, imikolov,
               mnist, movielens, mq2007, sentiment, uci_housing, voc2012,
               wmt14, wmt16)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "movielens", "sentiment",
           "uci_housing", "wmt14", "wmt16", "mq2007", "flowers", "voc2012",
           "conll05", "image", "common"]
