"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py — readers yield
(3072-float image in [0, 1], int label))."""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from . import common


def _tar_reader(path: str, sub_name: str):
    def reader():
        with tarfile.open(path, mode="r") as tf:
            names = [n for n in tf.getnames() if sub_name in n]
            for name in names:
                batch = pickle.load(tf.extractfile(name), encoding="bytes")
                data = batch[b"data"].astype(np.float32) / 255.0
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                for x, y in zip(data, labels):
                    yield x, int(y)

    return reader


def _synthetic(tag: str, mode: str, num_classes: int, n: int):
    rng = common.synthetic_rng(f"cifar{tag}", "proto")
    protos = rng.normal(0.5, 0.25, (num_classes, 3072)).astype(np.float32)
    rng = common.synthetic_rng(f"cifar{tag}", mode)
    labels = rng.integers(0, num_classes, n)
    imgs = protos[labels] + rng.normal(0, 0.1, (n, 3072)).astype(np.float32)
    imgs = np.clip(imgs, 0, 1).astype(np.float32)

    def reader():
        for x, y in zip(imgs, labels):
            yield x, int(y)

    return reader


def _make(tag: str, num_classes: int, mode: str, sub: str, n: int):
    cache = common.cached("cifar", f"cifar-{tag}-python.tar.gz")
    if cache:
        return _tar_reader(cache, sub)
    return _synthetic(tag, mode, num_classes, n)


def train10(synthetic_size: int = 4096):
    return _make("10", 10, "train", "data_batch", synthetic_size)


def test10(synthetic_size: int = 1024):
    return _make("10", 10, "test", "test_batch", synthetic_size)


def train100(synthetic_size: int = 4096):
    return _make("100", 100, "train", "train", synthetic_size)


def test100(synthetic_size: int = 1024):
    return _make("100", 100, "test", "test", synthetic_size)
