"""Shared dataset plumbing (reference: python/paddle/dataset/common.py —
DATA_HOME, download, md5file, split, cluster_files_reader)."""

from __future__ import annotations

import glob
import hashlib
import os
import pickle

import numpy as np

DATA_HOME = os.path.expanduser(
    os.environ.get("PT_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def md5file(fname: str) -> str:
    m = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            m.update(chunk)
    return m.hexdigest()


def download(url: str, module_name: str, md5sum: str | None = None,
             save_name: str | None = None) -> str:
    """Return the cached path if the file exists under DATA_HOME; this
    environment has no network egress, so a missing file is a typed error
    telling the user where to place it (the synthetic fallback in each
    dataset module means training flows never need this)."""
    from ..core.enforce import EnforceError

    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(dirname,
                            save_name or url.split("/")[-1])
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise EnforceError("cached %s fails md5 check" % filename)
        return filename
    raise EnforceError(
        "no network egress: place %s at %s, or use the module's synthetic "
        "reader (the default when no cache exists)" % (url, filename))


def cached(module_name: str, filename: str) -> str | None:
    """Path of a cached data file, or None (the synthetic trigger)."""
    p = os.path.join(DATA_HOME, module_name, filename)
    return p if os.path.exists(p) else None


def split(reader, line_count: int, suffix: str = "%05d.pickle",
          dumper=None):
    """reference: common.py split — shard a reader into pickle files."""
    dumper = dumper or pickle.dump
    lines, idx, files = [], 0, []
    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            path = suffix % idx
            with open(path, "wb") as f:
                dumper(lines, f)
            files.append(path)
            lines, idx = [], idx + 1
    if lines:
        path = suffix % idx
        with open(path, "wb") as f:
            dumper(lines, f)
        files.append(path)
    return files


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=None):
    """reference: common.py cluster_files_reader — each trainer reads its
    round-robin shard of the file list."""
    loader = loader or pickle.load

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, path in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(path, "rb") as f:
                    for sample in loader(f):
                        yield sample

    return reader


def synthetic_rng(module: str, mode: str) -> np.random.Generator:
    """One deterministic stream per (module, mode): synthetic datasets are
    stable across runs and machines."""
    seed = int.from_bytes(hashlib.md5(
        f"{module}:{mode}".encode()).digest()[:4], "little")
    return np.random.default_rng(seed)


def make_vocab(module: str, size: int, special=("<unk>", "<s>", "<e>")):
    """Deterministic synthetic vocab word->id with the usual specials."""
    vocab = {w: i for i, w in enumerate(special)}
    for i in range(size - len(special)):
        vocab[f"{module}_w{i}"] = len(vocab)
    return vocab
