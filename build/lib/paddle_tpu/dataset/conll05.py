"""CoNLL-2005 semantic role labeling (reference:
python/paddle/dataset/conll05.py — get_dict(), get_embedding(), test()
yields (word ids, ctx ids x5, predicate ids, mark, label ids))."""

from __future__ import annotations

import numpy as np

from . import common

_WORD_V = 44068
_LABEL_V = 59  # B/I/O tags over the role set
_PRED_V = 3162
_EMB_DIM = 32


def get_dict(word_size: int = _WORD_V, label_size: int = _LABEL_V,
             pred_size: int = _PRED_V):
    word_dict = common.make_vocab("conll_w", word_size, special=("<unk>",))
    verb_dict = common.make_vocab("conll_v", pred_size, special=("<unk>",))
    label_dict = {f"tag_{i}": i for i in range(label_size)}
    return word_dict, verb_dict, label_dict


def get_embedding(emb_dim: int = _EMB_DIM):
    rng = common.synthetic_rng("conll05", "emb")
    return rng.normal(0, 0.1, (_WORD_V, emb_dim)).astype(np.float32)


def _synthetic(mode: str, n: int):
    def reader():
        rng = common.synthetic_rng("conll05", mode)
        for _ in range(n):
            T = int(rng.integers(5, 40))
            words = rng.integers(1, _WORD_V, T)
            pred = int(rng.integers(1, _PRED_V))
            mark_pos = int(rng.integers(0, T))
            mark = [1 if t == mark_pos else 0 for t in range(T)]
            # tags correlate with word id parity + predicate distance: a
            # BiLSTM-CRF can actually fit this
            labels = [(int(w) + abs(t - mark_pos)) % _LABEL_V
                      for t, w in enumerate(words)]
            wl = list(map(int, words))
            yield (wl, wl, wl, wl, wl, wl,  # word + 5 ctx windows
                   [pred] * T, mark, labels)

    return reader


def test(synthetic_size: int = 512):
    return _synthetic("test", synthetic_size)
