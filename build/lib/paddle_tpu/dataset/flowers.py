"""Oxford-102 flowers (reference: python/paddle/dataset/flowers.py —
train()/test()/valid() yield (3x224x224 float image, int label))."""

from __future__ import annotations

import numpy as np

from . import common

_CLASSES = 102


def _synthetic(mode: str, n: int, hw: int):
    protos = common.synthetic_rng("flowers", "proto").normal(
        0.5, 0.2, (_CLASSES, 3, 8, 8)).astype(np.float32)

    def reader():
        rng = common.synthetic_rng("flowers", mode)
        for _ in range(n):
            y = int(rng.integers(0, _CLASSES))
            # upsample the class prototype + noise to (3, hw, hw)
            img = protos[y].repeat(hw // 8, axis=1).repeat(hw // 8, axis=2)
            img = img + rng.normal(0, 0.08, img.shape).astype(np.float32)
            yield np.clip(img, 0, 1).astype(np.float32), y

    return reader


def train(mapper=None, buffered_size: int = 1024, use_xmap: bool = True,
          synthetic_size: int = 512, image_hw: int = 224):
    return _synthetic("train", synthetic_size, image_hw)


def test(mapper=None, buffered_size: int = 1024, use_xmap: bool = True,
         synthetic_size: int = 128, image_hw: int = 224):
    return _synthetic("test", synthetic_size, image_hw)


def valid(mapper=None, buffered_size: int = 1024, use_xmap: bool = True,
          synthetic_size: int = 128, image_hw: int = 224):
    return _synthetic("valid", synthetic_size, image_hw)
