"""Image preprocessing utilities (reference:
python/paddle/dataset/image.py — resize_short, center_crop, random_crop,
left_right_flip, to_chw, simple_transform, load_and_transform). Pure
numpy; nearest/bilinear resize without cv2."""

from __future__ import annotations

import numpy as np


def _ensure_hwc(im: np.ndarray) -> np.ndarray:
    if im.ndim == 2:
        return im[:, :, None]
    return im


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the short edge is ``size`` (bilinear, HWC)."""
    im = _ensure_hwc(im)
    h, w = im.shape[:2]
    short = min(h, w)
    scale = size / float(short)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    ys = np.clip(np.linspace(0, h - 1, nh), 0, h - 1)
    xs = np.clip(np.linspace(0, w - 1, nw), 0, w - 1)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    out = (im[y0][:, x0] * (1 - wy) * (1 - wx) +
           im[y1][:, x0] * wy * (1 - wx) +
           im[y0][:, x1] * (1 - wy) * wx +
           im[y1][:, x1] * wy * wx)
    return out.astype(im.dtype if np.issubdtype(im.dtype, np.floating)
                      else np.float32)


def center_crop(im: np.ndarray, size: int, is_color: bool = True):
    im = _ensure_hwc(im)
    h, w = im.shape[:2]
    y = max((h - size) // 2, 0)
    x = max((w - size) // 2, 0)
    return im[y:y + size, x:x + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True, rng=None):
    im = _ensure_hwc(im)
    rng = rng or np.random.default_rng()
    h, w = im.shape[:2]
    y = int(rng.integers(0, max(h - size, 0) + 1))
    x = int(rng.integers(0, max(w - size, 0) + 1))
    return im[y:y + size, x:x + size]


def left_right_flip(im: np.ndarray, is_color: bool = True):
    return im[:, ::-1]


def to_chw(im: np.ndarray, order=(2, 0, 1)):
    return np.transpose(_ensure_hwc(im), order)


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True, mean=None,
                     rng=None):
    """resize short edge → (random|center) crop → (train: random flip) →
    CHW → mean subtract (reference: image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        rng = rng or np.random.default_rng()
        im = random_crop(im, crop_size, rng=rng)
        if rng.random() > 0.5:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean.reshape(-1, 1, 1) if mean.ndim == 1 else mean
    return im


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    """Minimal image loader: .npy arrays natively; PNG/JPEG via PIL if it
    exists in the environment (it is optional by design)."""
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image  # noqa: WPS433 (optional dependency)

        return np.asarray(Image.open(path).convert(
            "RGB" if is_color else "L"))
    except ImportError as e:
        from ..core.enforce import EnforceError

        raise EnforceError(
            "no image codec available: save arrays as .npy, or provide "
            "PIL") from e


def load_and_transform(path: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True, mean=None):
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
