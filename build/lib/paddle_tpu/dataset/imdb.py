"""IMDB sentiment (reference: python/paddle/dataset/imdb.py — word_dict(),
train(word_idx)/test(word_idx) yield (token-id list, 0/1 label))."""

from __future__ import annotations

import numpy as np

from . import common

_VOCAB = 5149  # reference vocab size after min-freq cutoff


def word_dict(vocab_size: int = _VOCAB):
    return common.make_vocab("imdb", vocab_size)


def _synthetic(mode: str, word_idx, n: int):
    # sentiment signal: positive reviews oversample the first vocab half
    V = len(word_idx)

    def reader():
        # fresh stream per invocation: every epoch/iteration replays the
        # SAME samples (paddle reader-creator contract)
        rng = common.synthetic_rng("imdb", mode)
        for _ in range(n):
            label = int(rng.integers(0, 2))
            T = int(rng.integers(16, 120))
            if label:
                ids = rng.integers(3, 3 + (V - 3) // 2, T)
            else:
                ids = rng.integers(3 + (V - 3) // 2, V, T)
            yield list(map(int, ids)), label

    return reader


def train(word_idx=None, synthetic_size: int = 2048):
    word_idx = word_idx or word_dict()
    return _synthetic("train", word_idx, synthetic_size)


def test(word_idx=None, synthetic_size: int = 512):
    word_idx = word_idx or word_dict()
    return _synthetic("test", word_idx, synthetic_size)
