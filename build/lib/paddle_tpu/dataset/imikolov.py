"""PTB language-model n-grams (reference: python/paddle/dataset/imikolov.py
— build_dict(), train(word_idx, n)/test(word_idx, n) yield n-gram id
tuples; data_type NGRAM or SEQ)."""

from __future__ import annotations

from . import common


class DataType:
    NGRAM = 1
    SEQ = 2


_VOCAB = 2074  # reference dict size at min_word_freq=50


def build_dict(min_word_freq: int = 50, vocab_size: int = _VOCAB):
    return common.make_vocab("imikolov", vocab_size)


def _synthetic(mode: str, word_idx, n, data_type, size: int):
    V = len(word_idx)

    def reader():
        rng = common.synthetic_rng("imikolov", mode)
        for _ in range(size):
            if data_type == DataType.NGRAM:
                # learnable n-gram: last word = sum of context mod V
                ctx = rng.integers(3, V, n - 1)
                tgt = int(ctx.sum() % (V - 3)) + 3
                yield tuple(map(int, ctx)) + (tgt,)
            else:
                T = int(rng.integers(5, 30))
                seq = rng.integers(3, V, T)
                yield list(map(int, seq))

    return reader


def train(word_idx=None, n: int = 5, data_type=DataType.NGRAM,
          synthetic_size: int = 4096):
    word_idx = word_idx or build_dict()
    return _synthetic("train", word_idx, n, data_type, synthetic_size)


def test(word_idx=None, n: int = 5, data_type=DataType.NGRAM,
         synthetic_size: int = 512):
    word_idx = word_idx or build_dict()
    return _synthetic("test", word_idx, n, data_type, synthetic_size)
