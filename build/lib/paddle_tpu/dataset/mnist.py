"""MNIST (reference: python/paddle/dataset/mnist.py — train()/test()
yield (784-float image in [-1, 1], int label))."""

from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common


def _idx_reader(img_path: str, lbl_path: str):
    with gzip.open(img_path, "rb") as f:
        _, n, h, w = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), np.uint8).reshape(n, h * w)
    with gzip.open(lbl_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    return images.astype(np.float32) / 127.5 - 1.0, labels.astype(np.int64)


def _synthetic(mode: str, n: int):
    # class-conditional: each digit k is a fixed prototype + noise, so a
    # classifier genuinely learns (book-test convergence contract)
    rng = common.synthetic_rng("mnist", "proto")
    protos = rng.normal(0, 1, (10, 784)).astype(np.float32)
    rng = common.synthetic_rng("mnist", mode)
    labels = rng.integers(0, 10, n)
    images = protos[labels] + rng.normal(0, 0.3, (n, 784)).astype(np.float32)
    return np.clip(images, -1, 1).astype(np.float32), labels.astype(np.int64)


def _reader(mode: str, synthetic_size: int):
    files = {"train": ("train-images-idx3-ubyte.gz",
                       "train-labels-idx1-ubyte.gz"),
             "test": ("t10k-images-idx3-ubyte.gz",
                      "t10k-labels-idx1-ubyte.gz")}[mode]
    img = common.cached("mnist", files[0])
    lbl = common.cached("mnist", files[1])

    def reader():
        if img and lbl:
            images, labels = _idx_reader(img, lbl)
        else:
            images, labels = _synthetic(mode, synthetic_size)
        for x, y in zip(images, labels):
            yield x, int(y)

    return reader


def train(synthetic_size: int = 8192):
    return _reader("train", synthetic_size)


def test(synthetic_size: int = 1024):
    return _reader("test", synthetic_size)
