"""MovieLens-1M (reference: python/paddle/dataset/movielens.py — train()/
test() yield (user_id, gender, age, job, movie_id, category ids, title
ids, rating); plus the id-space helpers the recommender model sizes its
embeddings with)."""

from __future__ import annotations

import numpy as np

from . import common

age_table = [1, 18, 25, 35, 45, 50, 56]

_MAX_USER = 6040
_MAX_MOVIE = 3952
_MAX_JOB = 20
_N_CATS = 18
_TITLE_VOCAB = 5174


def max_user_id() -> int:
    return _MAX_USER


def max_movie_id() -> int:
    return _MAX_MOVIE


def max_job_id() -> int:
    return _MAX_JOB


def movie_categories():
    return {f"cat_{i}": i for i in range(_N_CATS)}


def get_movie_title_dict():
    return common.make_vocab("ml_title", _TITLE_VOCAB, special=("<unk>",))


def user_info():
    rng = common.synthetic_rng("movielens", "user")
    return {u: {"gender": int(rng.integers(0, 2)),
                "age": int(rng.integers(0, len(age_table))),
                "job": int(rng.integers(0, _MAX_JOB))}
            for u in range(1, _MAX_USER + 1)}


def movie_info():
    rng = common.synthetic_rng("movielens", "movie")
    return {m: {"categories": list(map(int, rng.integers(0, _N_CATS, 2))),
                "title": list(map(int, rng.integers(1, _TITLE_VOCAB, 4)))}
            for m in range(1, _MAX_MOVIE + 1)}


def _synthetic(mode: str, n: int):
    wu = common.synthetic_rng("movielens", "wu").normal(0, 1, _MAX_USER + 1)
    wm = common.synthetic_rng("movielens", "wm").normal(0, 1, _MAX_MOVIE + 1)
    users = user_info()
    movies = movie_info()

    def reader():
        # fresh stream per invocation (reader-creator contract); user and
        # movie side features come from the SAME tables user_info()/
        # movie_info() expose, so joins on those helpers are consistent
        rng = common.synthetic_rng("movielens", mode)
        for _ in range(n):
            u = int(rng.integers(1, _MAX_USER + 1))
            m = int(rng.integers(1, _MAX_MOVIE + 1))
            # learnable bilinear preference signal, quantized to 1..5
            score = wu[u] * wm[m] + 0.1 * rng.normal()
            rating = float(np.clip(np.round(3 + 1.5 * np.tanh(score)), 1, 5))
            ui, mi = users[u], movies[m]
            yield (u, ui["gender"], ui["age"], ui["job"], m,
                   mi["categories"], mi["title"], rating)

    return reader


def train(synthetic_size: int = 4096):
    return _synthetic("train", synthetic_size)


def test(synthetic_size: int = 512):
    return _synthetic("test", synthetic_size)
