"""MQ2007 learning-to-rank (reference: python/paddle/dataset/mq2007.py —
readers yield per-query groups in pointwise/pairwise/listwise form over
46-dim feature vectors with 0-2 relevance)."""

from __future__ import annotations

import numpy as np

from . import common

_N_FEAT = 46


def _synthetic(mode: str, n_queries: int):
    w = common.synthetic_rng("mq2007", "w").normal(0, 1, _N_FEAT)

    def gen_query(qid):
        # per-query stream keyed by qid: deterministic on re-iteration
        rng = common.synthetic_rng("mq2007", f"{mode}:{qid}")
        docs = int(rng.integers(5, 20))
        X = rng.normal(0, 1, (docs, _N_FEAT)).astype(np.float32)
        score = X @ w
        rel = np.digitize(score, np.quantile(score, [0.5, 0.85]))
        return X, rel.astype(np.int64)

    return gen_query, n_queries


def train(format: str = "pairwise", synthetic_size: int = 256):
    gen, n = _synthetic("train", synthetic_size)
    return _format_reader(gen, n, format)


def test(format: str = "pairwise", synthetic_size: int = 64):
    gen, n = _synthetic("test", synthetic_size)
    return _format_reader(gen, n, format)


def _format_reader(gen, n, format: str):
    def reader():
        for q in range(n):
            X, rel = gen(q)
            if format == "pointwise":
                for x, r in zip(X, rel):
                    yield x, int(r)
            elif format == "pairwise":
                hi = np.flatnonzero(rel == rel.max())
                lo = np.flatnonzero(rel == rel.min())
                for i in hi[:3]:
                    for j in lo[:3]:
                        yield X[i], X[j]
            else:  # listwise
                yield X, rel

    return reader
