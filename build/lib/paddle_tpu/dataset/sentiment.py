"""Movie-review sentiment, NLTK-corpus flavor (reference:
python/paddle/dataset/sentiment.py — get_word_dict(), train()/test()
yield (token-id list, 0/1 label))."""

from __future__ import annotations

from . import common, imdb

_VOCAB = 2048


def get_word_dict(vocab_size: int = _VOCAB):
    return common.make_vocab("sentiment", vocab_size)


def train(synthetic_size: int = 1600):
    return imdb._synthetic("sent_train", get_word_dict(), synthetic_size)


def test(synthetic_size: int = 400):
    return imdb._synthetic("sent_test", get_word_dict(), synthetic_size)
