"""UCI Boston housing (reference: python/paddle/dataset/uci_housing.py —
13 normalized features, float target; 80/20 train/test split)."""

from __future__ import annotations

import numpy as np

from . import common

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _data():
    cache = common.cached("uci_housing", "housing.data")
    if cache:
        raw = np.loadtxt(cache)
    else:
        # synthetic linear task with fixed ground-truth weights: fit_a_line
        # genuinely converges on it (tests/book/test_fit_a_line analog)
        rng = common.synthetic_rng("uci_housing", "all")
        X = rng.normal(0, 1, (506, 13))
        w = common.synthetic_rng("uci_housing", "w").normal(0, 1, 13)
        y = X @ w + 0.1 * rng.normal(0, 1, 506)
        raw = np.concatenate([X, y[:, None]], axis=1)
    feats = raw[:, :-1].astype(np.float32)
    # feature normalization to [-1, 1] by min/max (reference behavior)
    fmin, fmax = feats.min(0), feats.max(0)
    feats = (feats - (fmin + fmax) / 2) / np.maximum(fmax - fmin, 1e-6) * 2
    target = raw[:, -1:].astype(np.float32)
    split = int(len(feats) * 0.8)
    return feats, target, split


def train():
    def reader():
        feats, target, split = _data()
        for x, y in zip(feats[:split], target[:split]):
            yield x, y

    return reader


def test():
    def reader():
        feats, target, split = _data()
        for x, y in zip(feats[split:], target[split:]):
            yield x, y

    return reader
