"""PASCAL VOC2012 segmentation (reference:
python/paddle/dataset/voc2012.py — train()/test()/val() yield
(3xHxW float image, HxW int label mask))."""

from __future__ import annotations

import numpy as np

from . import common

_CLASSES = 21


def _synthetic(mode: str, n: int, hw: int):
    def reader():
        rng = common.synthetic_rng("voc2012", mode)
        for _ in range(n):
            img = rng.normal(0.5, 0.2, (3, hw, hw)).astype(np.float32)
            mask = np.zeros((hw, hw), np.int64)
            # a few class rectangles; image channels carry the class signal
            for _k in range(int(rng.integers(1, 4))):
                c = int(rng.integers(1, _CLASSES))
                x0, y0 = rng.integers(0, hw // 2, 2)
                x1 = int(x0 + rng.integers(hw // 8, hw // 2))
                y1 = int(y0 + rng.integers(hw // 8, hw // 2))
                mask[y0:y1, x0:x1] = c
                img[:, y0:y1, x0:x1] += c / _CLASSES
            yield np.clip(img, 0, 1.5).astype(np.float32), mask

    return reader


def train(synthetic_size: int = 256, image_hw: int = 64):
    return _synthetic("train", synthetic_size, image_hw)


def test(synthetic_size: int = 64, image_hw: int = 64):
    return _synthetic("test", synthetic_size, image_hw)


def val(synthetic_size: int = 64, image_hw: int = 64):
    return _synthetic("val", synthetic_size, image_hw)
