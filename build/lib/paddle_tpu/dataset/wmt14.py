"""WMT-14 fr→en (reference: python/paddle/dataset/wmt14.py — train/test
yield (src ids, trg ids with <s>, trg ids with <e>); get_dict returns
(src_dict, trg_dict) id→word)."""

from __future__ import annotations

from . import common

UNK, START, END = 2, 0, 1  # reference id layout: <s>=0 <e>=1 <unk>=2
_SPECIAL = ("<s>", "<e>", "<unk>")


def get_dict(dict_size: int = 30000, reverse: bool = False):
    src = common.make_vocab("wmt14_src", dict_size, special=_SPECIAL)
    trg = common.make_vocab("wmt14_trg", dict_size, special=_SPECIAL)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def _synthetic(mode: str, dict_size: int, n: int):
    def reader():
        rng = common.synthetic_rng("wmt14", mode)
        for _ in range(n):
            T = int(rng.integers(4, 30))
            src = rng.integers(3, dict_size, T)
            # learnable mapping: trg token = (src token + 7) mod vocab
            trg = (src + 7 - 3) % (dict_size - 3) + 3
            trg = list(map(int, trg))
            yield (list(map(int, src)), [START] + trg, trg + [END])

    return reader


def train(dict_size: int = 30000, synthetic_size: int = 4096):
    return _synthetic("train", dict_size, synthetic_size)


def test(dict_size: int = 30000, synthetic_size: int = 512):
    return _synthetic("test", dict_size, synthetic_size)
