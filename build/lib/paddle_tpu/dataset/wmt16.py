"""WMT-16 de→en, BPE-vocab flavor (reference:
python/paddle/dataset/wmt16.py — train/test/validation readers +
get_dict(lang, dict_size))."""

from __future__ import annotations

from . import common

_SPECIAL = ("<s>", "<e>", "<unk>")


def get_dict(lang: str = "en", dict_size: int = 10000,
             reverse: bool = False):
    d = common.make_vocab(f"wmt16_{lang}", dict_size, special=_SPECIAL)
    return {v: k for k, v in d.items()} if reverse else d


def _synthetic(mode: str, src_dict_size: int, trg_dict_size: int, n: int):
    def reader():
        rng = common.synthetic_rng("wmt16", mode)
        for _ in range(n):
            T = int(rng.integers(4, 30))
            src = rng.integers(3, src_dict_size, T)
            trg = (src * 3 + 1 - 3) % (trg_dict_size - 3) + 3
            trg = list(map(int, trg))
            yield (list(map(int, src)), [0] + trg, trg + [1])

    return reader


def train(src_dict_size: int = 10000, trg_dict_size: int = 10000,
          src_lang: str = "en", synthetic_size: int = 4096):
    return _synthetic("train", src_dict_size, trg_dict_size, synthetic_size)


def test(src_dict_size: int = 10000, trg_dict_size: int = 10000,
         src_lang: str = "en", synthetic_size: int = 512):
    return _synthetic("test", src_dict_size, trg_dict_size, synthetic_size)


def validation(src_dict_size: int = 10000, trg_dict_size: int = 10000,
               src_lang: str = "en", synthetic_size: int = 512):
    return _synthetic("val", src_dict_size, trg_dict_size, synthetic_size)
