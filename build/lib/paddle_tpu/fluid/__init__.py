"""``paddle_tpu.fluid`` — drop-in namespace for reference users.

``import paddle_tpu.fluid as fluid`` gives the `paddle.fluid` surface
(reference: python/paddle/fluid/__init__.py + API.spec) wired to the
TPU-native implementations: Program/Executor from `paddle_tpu.static`,
layers from `paddle_tpu.layers`, places/mesh from `paddle_tpu.core`, the
data pipeline from `paddle_tpu.data`. Names whose mechanism was redesigned
(LoDTensor, PS transpiler, RecordIO) resolve to their documented
replacements — see PARITY.md / OP_COVERAGE.md for the disposition of every
reference component.

Coverage against the reference API.spec's `paddle.fluid.*` names is
asserted by tests/test_fluid_compat.py.
"""

from __future__ import annotations

import contextlib
import sys as _sys

import jax as _jax
import jax.numpy as _jnp

import paddle_tpu as _pt
from .. import clip, initializer, layers, metrics, nets, regularizer
from .. import data as _data
from ..core import CPUPlace, TPUPlace
from ..core import config as _config
from ..core.enforce import EnforceError as _EnforceError
from ..install_check import run_check as _run_check
from ..static import (Executor, Program, Scope, default_main_program,
                      global_scope, program_guard)
from ..static import io as _static_io
from . import (backward, contrib, dygraph, io, optimizer, profiler,
               transpiler, unique_name)

# submodule aliases so `import paddle_tpu.fluid.layers` etc. resolve
for _name, _mod in [("layers", layers), ("nets", nets), ("clip", clip),
                    ("regularizer", regularizer),
                    ("initializer", initializer), ("metrics", metrics)]:
    _sys.modules[__name__ + "." + _name] = _mod

# --- places (reference: platform/place.h; TPU is the accelerator here) -----
CUDAPlace = TPUPlace        # accelerator place: TPU chips, not CUDA devices
CUDAPinnedPlace = CPUPlace  # host staging; PJRT owns pinned buffers


def cpu_places(device_count=None):
    n = device_count or 1
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places — one per visible TPU device."""
    ids = device_ids or range(len(_jax.devices()))
    return [TPUPlace(i) for i in ids]


def cuda_pinned_places(device_count=None):
    return cpu_places(device_count)


# --- programs / execution --------------------------------------------------
def default_startup_program():
    """The reference splits init ops into a startup program; here
    initialization happens when the main Program's parameters are created,
    so the startup program IS the main program's init stage."""
    return default_main_program()


@contextlib.contextmanager
def scope_guard(scope):
    from ..static import executor as _exec

    prev = _exec._global_scope
    _exec._global_scope = scope
    try:
        yield
    finally:
        _exec._global_scope = prev


@contextlib.contextmanager
def name_scope(prefix: str):
    """Name prefix for created vars (debugging/viz aid, as in reference
    framework.py name_scope)."""
    prog = default_main_program()
    old = getattr(prog, "_name_prefix", "")
    prog._name_prefix = old + prefix + "/"
    try:
        yield
    finally:
        prog._name_prefix = old


def in_dygraph_mode() -> bool:
    """Eager is the default execution model (JAX); static Programs are the
    opt-in path — the inverse of the reference's default."""
    return not getattr(dygraph, "_static_forced", False)


# --- strategies / compiled program -----------------------------------------
BuildStrategy = _config.BuildStrategy
ExecutionStrategy = _config.ExecutionStrategy


class CompiledProgram:
    """reference: compiler.py CompiledProgram — with_data_parallel maps to
    mesh-sharded compilation (the compiler inserts collectives; SURVEY §7)."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()
        self.data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self.data_parallel = True
        self.loss_name = loss_name
        if build_strategy is not None:
            self.build_strategy = build_strategy
        return self

    def with_inference_optimize(self, config=None):
        """Inference compilation (reference: compiler.py) — the analysis
        pipeline's role is XLA AOT; the artifact path is jit.save /
        static.InferencePredictor."""
        self.data_parallel = False
        self.for_inference = True
        return self


class ParallelExecutor:
    """reference: parallel_executor.py:28 — redesigned as a thin front on
    parallel.Trainer (pjit over the mesh; compiler-inserted collectives
    replace the SSA graph + NCCL op handles)."""

    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, trainer=None):
        self.trainer = trainer  # a parallel.Trainer drives execution
        self.loss_name = loss_name
        self.program = main_program

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed or feed_dict or {}
        if self.trainer is not None:
            return self.trainer.train_step(feed)
        exe = Executor()
        return exe.run(self.program, feed=feed, fetch_list=fetch_list,
                       return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        """Scope reuse is XLA buffer donation here; nothing to drop."""
        return None


# --- LoD compatibility (redesigned: padded + lengths, SURVEY §5.7) ---------
class LoDTensor:
    """Capability shim: a (dense values, lengths) pair. The TPU-native
    representation of the reference's LoDTensor (lod_tensor.h:110) is a
    padded dense array plus a lengths vector (ops.sequence)."""

    def __init__(self, value=None, lengths=None):
        self._value = None if value is None else _jnp.asarray(value)
        self._lengths = None if lengths is None else list(lengths)

    def set(self, value, place=None):
        self._value = _jnp.asarray(value)

    def set_recursive_sequence_lengths(self, lengths):
        self._lengths = lengths

    def recursive_sequence_lengths(self):
        return self._lengths

    def has_valid_recursive_sequence_lengths(self) -> bool:
        if self._lengths is None or self._value is None:
            return self._lengths is None
        import numpy as np

        return int(np.sum(self._lengths[-1])) == int(self._value.shape[0])

    # offset-form LoD accessors (reference lod_tensor.h:229: lod is the
    # cumulative-offset form of the lengths vector)
    def lod(self):
        import numpy as np

        if self._lengths is None:
            return []
        return [[0] + list(np.cumsum(lv)) for lv in self._lengths]

    def set_lod(self, lod):
        import numpy as np

        self._lengths = [list(np.diff(level)) for level in lod]

    def shape(self):
        return tuple(self._value.shape) if self._value is not None else ()

    def value(self):
        return self._value

    def __array__(self, dtype=None, copy=None):
        import numpy as np

        return np.asarray(self._value, dtype)


LoDTensorArray = list  # host-side list of LoDTensors (pybind.cc:391 analog)


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference: lod_tensor.py create_lod_tensor — here: pad ragged rows
    to dense + keep lengths."""
    import numpy as np

    flat = np.asarray(data)
    t = LoDTensor(flat, recursive_seq_lens)
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    import numpy as np

    total = int(np.sum(recursive_seq_lens[-1]))
    shape = (total,) + tuple(base_shape)
    data = np.random.randint(low, high + 1, shape)
    return create_lod_tensor(data, recursive_seq_lens, place)


# --- param attrs -----------------------------------------------------------
class ParamAttr:
    """reference: param_attr.py ParamAttr — bundles name/initializer/
    regularizer/lr for a parameter; consumed by nn layers' create_parameter
    and static layers."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=False):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip


class WeightNormParamAttr(ParamAttr):
    """reference: param_attr.py WeightNormParamAttr (dim-wise weight
    normalization on the parameterization)."""

    def __init__(self, dim=None, **kw):
        super().__init__(**kw)
        self.dim = dim


# --- data ------------------------------------------------------------------
DataFeeder = _data.DataFeeder


class DataFeedDesc:
    """reference: data_feed_desc.py — config for the native MultiSlot feed
    (paddle_tpu.native datafeed)."""

    def __init__(self, proto_or_slots=None):
        self.slots = proto_or_slots or []
        self.batch_size = 1

    def set_batch_size(self, bs: int):
        self.batch_size = bs

    def set_use_slots(self, names):
        self.use_slots = list(names)

    def set_dense_slots(self, names):
        self.dense_slots = list(names)

    def desc(self):
        return {"slots": self.slots, "batch_size": self.batch_size}


# --- memory passes (XLA owns buffer liveness; kept as no-op API) -----------
def memory_optimize(input_program=None, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """reference: memory_optimization_transpiler.py — XLA buffer
    assignment + donation performs this; call is a no-op kept for source
    compatibility (SURVEY §7 'what XLA obsoletes')."""
    return input_program


def release_memory(input_program=None, skip_opt_set=None):
    return input_program


# --- misc ------------------------------------------------------------------
DistributeTranspiler = transpiler.DistributeTranspiler
DistributeTranspilerConfig = transpiler.DistributeTranspilerConfig


class _RecordIOWriter:
    def __init__(self, *a, **kw):
        raise _EnforceError(
            "RecordIO was dropped by design (SURVEY 'what NOT to rebuild'); "
            "use data.MultiSlotDataset or array checkpoint formats")


recordio_writer = _sys.modules[__name__]  # legacy module name; writer below
convert_reader_to_recordio_file = _RecordIOWriter


def install_check():
    return _run_check()


install_check.run_check = _run_check
