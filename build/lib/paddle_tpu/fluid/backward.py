"""fluid.backward compat (reference: python/paddle/fluid/backward.py:394
append_backward; :619 calc_gradient — both over the static Program; the
eager path is jax.grad by construction)."""

from __future__ import annotations

from ..static.program import append_backward


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: backward.py calc_gradient:619 — gradients of ``targets``
    w.r.t. arbitrary program vars (not just parameters)."""
    names = [v.name if hasattr(v, "name") else v for v in
             (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
    tlist = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    if target_gradients is None:
        glist = [None] * len(tlist)
    else:
        glist = (list(target_gradients)
                 if isinstance(target_gradients, (list, tuple))
                 else [target_gradients])
        from ..core.enforce import enforce

        enforce(len(glist) == len(tlist),
                "target_gradients has %s entries for %s targets",
                len(glist), len(tlist))
    import jax.numpy as jnp

    weighted = []
    for t, g in zip(tlist, glist):
        if g is None:
            weighted.append(t)
        else:
            # d(sum(t*g))/dx == g-weighted vjp of t (reference semantics)
            weighted.append(t.program.apply(
                lambda tv, gv: jnp.sum(tv * gv), [t, g],
                name="weighted_target"))
    total = weighted[0]
    for t in weighted[1:]:
        total = total + t  # summed objective: gradient contributions add
    pairs = append_backward(total, parameter_list=names)
    grads = [g for _, g in pairs]
    return grads if isinstance(inputs, (list, tuple)) else grads[0]
