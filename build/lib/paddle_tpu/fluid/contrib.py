"""fluid.contrib compat (reference: python/paddle/fluid/contrib/) — the
contrib surface mapped to the first-class subsystems it matured into here:
mixed_precision → `paddle_tpu.amp`, slim/quant → `paddle_tpu.quant` +
`paddle_tpu.slim`, decoder → `ops.decode`, memory_usage →
`paddle_tpu.utils.memory`."""

from __future__ import annotations

from .. import amp as mixed_precision
from .. import data as reader          # contrib/reader → data pipeline
from ..core.enforce import EnforceError
from ..ops import decode as _decode
from ..quant import calibrate as _calibrate
from ..quant import quantize_model as _quantize_model
from ..slim import Distiller, Pruner
from ..utils.memory import memory_usage


class Compressor:
    """reference: contrib/slim/core/compressor.py — the contrib-era entry
    point, kept as a thin front over the real driver
    (paddle_tpu.slim.Compressor): ``config()`` takes the strategy config
    (dict or JSON path, slim.build_strategies format), ``run()``
    delegates the epoch loop."""

    _KNOWN = ("params", "optimizer", "loss_fn", "train_reader", "eval_fn",
              "epochs", "checkpoint_dir", "converge_delta")

    def __init__(self, params=None, optimizer=None, loss_fn=None,
                 train_reader=None, eval_fn=None, epochs: int = 1, **kw):
        unknown = sorted(set(kw) - set(self._KNOWN))
        if unknown:
            raise TypeError(
                f"Compressor got unknown arguments {unknown}; the contrib "
                f"front takes {list(self._KNOWN)} (see "
                "paddle_tpu.slim.Compressor)")
        self._args = dict(params=params, optimizer=optimizer,
                          loss_fn=loss_fn, train_reader=train_reader,
                          eval_fn=eval_fn, epochs=epochs, **kw)
        self._strategies = []

    def config(self, config_or_path):
        from ..slim import build_strategies

        self._strategies = build_strategies(config_or_path)
        return self

    def run(self):
        from ..slim import Compressor as _C

        return _C(strategies=self._strategies, **self._args).run()


class Calibrator:
    """reference: contrib/int8_inference Calibrator — post-training
    calibration; thin driver over quant.calibrate/freeze."""

    def __init__(self, model=None, **kw):
        self.model = model
        self.stats = None

    def sample_data(self, fn, batches):
        self.stats = _calibrate(fn, batches)
        return self.stats

    def save_int8_model(self, *a, **kw):
        from ..quant import freeze

        return freeze(self.stats, *a, **kw)


class QuantizeTranspiler:
    """reference: contrib/quantize/quantize_transpiler.py — program
    rewriting for QAT; here QAT rewrites Layers (`quant.qat`)."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        self.cfg = dict(weight_bits=weight_bits,
                        activation_bits=activation_bits,
                        activation_quantize_type=activation_quantize_type,
                        weight_quantize_type=weight_quantize_type)

    def training_transpile(self, layer, startup_program=None):
        from ..quant import QuantConfig

        cfg = QuantConfig(weight_bits=self.cfg["weight_bits"],
                          activation_bits=self.cfg["activation_bits"])
        return _quantize_model(layer, cfg)

    def freeze_program(self, layer, place=None):
        from ..quant import freeze

        return freeze(layer)

    def convert_to_int8(self, layer, place=None):
        """Freeze + materialize int8 weights (reference: contrib/quantize
        quantize_transpiler convert_to_int8)."""
        from ..quant import freeze, quantize_to_int

        frozen = freeze(layer)
        return quantize_to_int(frozen) if not hasattr(frozen, "forward") \
            else frozen


def extend_with_decoupled_weight_decay(base_optimizer):
    """reference: contrib/extend_optimizer — Adam + decoupled decay is
    first-class as optimizer.AdamW."""
    from ..optimizer import AdamW

    return AdamW


# --- contrib/decoder (beam search framework) -------------------------------
class InitState:
    """reference: contrib/decoder/beam_search_decoder.py InitState."""

    def __init__(self, init=None, shape=None, value=0.0, dtype="float32"):
        import jax.numpy as jnp

        self.state = (jnp.asarray(init) if init is not None
                      else jnp.full(tuple(shape or ()), value, dtype))


class StateCell:
    """reference: contrib/decoder StateCell — named decode states advanced
    by a user cell function (functional form: compute_state(inputs,
    states) -> new states)."""

    def __init__(self, inputs=None, states=None, out_state: str = "h"):
        self.inputs = inputs or {}
        self.states = {k: (v.state if isinstance(v, InitState) else v)
                       for k, v in (states or {}).items()}
        self.out_state_name = out_state
        self._fn = None

    def register(self, fn):
        self._fn = fn
        return fn

    compute_state = register
    state_updater = register

    def get_state(self, name):
        return self.states[name]

    def set_state(self, name, value):
        self.states[name] = value

    def get_input(self, name):
        return self.inputs[name]

    def update_states(self, new_states):
        self.states.update(new_states)
        return self.states

    def step(self, inputs, states):
        if self._fn is None:
            raise EnforceError("StateCell: register a compute function")
        return self._fn(inputs, states)

    def out_state(self, states=None):
        return (states or self.states)[self.out_state_name]


class TrainingDecoder:
    """reference: contrib/decoder TrainingDecoder — teacher-forced decode
    over a StateCell (functional scan form)."""

    def __init__(self, state_cell: StateCell, max_len: int = 100):
        self.state_cell = state_cell
        self.max_len = max_len

    def __call__(self, step_inputs):
        import jax
        import jax.numpy as jnp
        from jax import lax

        def body(states, x_t):
            new = self.state_cell.step(x_t, states)
            return new, self.state_cell.out_state(new)

        init = self.state_cell.states
        _, outs = lax.scan(body, init, step_inputs)
        return outs


class BeamSearchDecoder:
    """reference: contrib/decoder BeamSearchDecoder — inference-time beam
    decode over a StateCell, delegating to ops.decode.beam_search."""

    def __init__(self, state_cell: StateCell, *, beam_size: int = 4,
                 max_len: int = 100, bos_id: int = 0, end_id: int = 1,
                 length_penalty: float = 0.0):
        self.state_cell = state_cell
        self.kw = dict(beam_size=beam_size, max_len=max_len, bos_id=bos_id,
                       end_id=end_id, length_penalty=length_penalty)

    def decode(self, init_state, step_fn):
        return _decode.beam_search(init_state, step_fn, **self.kw)

    __call__ = decode


# --- PS-era helpers --------------------------------------------------------
def convert_dist_to_sparse_program(program):
    raise EnforceError(
        "sparse PS programs are replaced by parallel.ShardedEmbedding (EP "
        "all-to-all) — PARITY.md §2.5")


def load_persistables_for_increment(dirname, executor, program=None,
                                    lookup_table_var=None,
                                    lookup_table_var_path=None):
    """reference: contrib/utils/lookup_table_utils.py — resuming training
    from a checkpoint is checkpoint.restore_state / CheckpointManager."""
    from ..checkpoint import restore_state

    return restore_state(dirname)


def load_persistables_for_inference(dirname, executor, program=None,
                                    lookup_table_var_name=None):
    from ..static.io import load_persistables

    return load_persistables(dirname)


def op_freq_statistic(program):
    """reference: contrib/op_frequence.py — per-op-type frequency count of
    a static Program (also: tools/op_frequence.py CLI)."""
    from collections import Counter

    counts = Counter()
    for node in getattr(program, "_ops", []):
        counts[getattr(node, "name", type(node).__name__)] += 1
    return counts


class HDFSClient:
    """Dropped: no HDFS in this environment (PARITY.md §2.7); methods kept
    for source compatibility, all raising with the replacement pointer."""

    def __init__(self, *a, **kw):
        raise EnforceError(
            "HDFS is not available in this environment; checkpoint IO is "
            "path-pluggable (PARITY.md §2.7)")

    def _na(self, *a, **kw):
        raise EnforceError("HDFS dropped — checkpoint IO is path-pluggable")

    upload = download = is_exist = is_dir = delete = rename = _na
    makedirs = ls = lsr = make_local_dirs = _na


def multi_download(*a, **kw):
    raise EnforceError("HDFS transfer utilities dropped — see HDFSClient")


def multi_upload(*a, **kw):
    raise EnforceError("HDFS transfer utilities dropped — see HDFSClient")
