"""fluid.dygraph compat (reference: python/paddle/fluid/dygraph/ —
base.py:29 guard, :47 to_variable; layers.py Layer; nn.py layer classes;
parallel.py:79 DataParallel).

JAX is eager by construction, so ``guard`` is a no-op context and
``to_variable`` is array conversion; the Layer system is `paddle_tpu.nn`.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..checkpoint import restore_state as load_persistables
from ..checkpoint import save_state as save_persistables
from ..nn import (GRU, LSTM, NCE, BatchNorm, BilinearTensorProduct, Conv2D,
                  Conv2DTranspose, Embedding, GroupNorm, GRUCell, HSigmoid,
                  Layer, LayerList, LayerNorm, Linear, LSTMCell, Parameter,
                  Pool2D, PRelu, Sequential, SpectralNorm)
from ..parallel import DataParallel

FC = Linear  # reference dygraph/nn.py FC


@contextlib.contextmanager
def guard(place=None):
    """Eager IS the default execution model here; guard is kept as a
    no-op scope for source compatibility (reference: dygraph/base.py:29)."""
    yield


def to_variable(value, block=None, name=None):
    """reference: dygraph/base.py:47 — numpy → device array."""
    return jnp.asarray(value)


def enabled() -> bool:
    return True
