"""fluid.io compat (reference: python/paddle/fluid/io.py:98-1074 save/load
family + fluid/reader.py PyReader)."""

from __future__ import annotations

from ..layers import _PyReader as PyReader  # async device feed pipeline
from ..static.io import load_inference_model as _load_inference_model
from ..static.io import (load_persistables, save_inference_model,
                         save_persistables)


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None, pserver_endpoints=None):
    """fluid signature (reference io.py:1074). The artifact here is
    self-contained: the executor is accepted and unused; per-file names
    don't apply (single manifest-v2 directory) and raise if customized so
    a port doesn't silently load the wrong thing. Returns the predictor."""
    from ..core.enforce import enforce

    enforce(model_filename is None and params_filename is None,
            "the serving artifact is a single manifest directory; "
            "model_filename/params_filename do not apply (got %s/%s)",
            model_filename, params_filename)
    enforce(pserver_endpoints is None,
            "no pserver serving role exists (PARITY.md §2.5); distributed "
            "serving shards via mesh, got endpoints %s", pserver_endpoints)
    return _load_inference_model(dirname)

# vars/params granularities collapse onto the same artifact writer: the
# persistable set IS the param set plus optimizer state in this design
# (reference io.py:98 save_vars / :228 save_params / :460 save_persistables)
save_vars = save_persistables
save_params = save_persistables
load_vars = load_persistables
load_params = load_persistables
