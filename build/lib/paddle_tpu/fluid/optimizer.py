"""fluid.optimizer compat names (reference: python/paddle/fluid/optimizer.py
:49,508-1874) — the reference exposes ``<X>Optimizer`` classes; the
TPU-native classes live in `paddle_tpu.optimizer` under modern names."""

from __future__ import annotations

from ..optimizer import (SGD, Adadelta, Adagrad, Adam, Adamax, DecayedAdagrad,
                         ExponentialMovingAverage, Ftrl, LarsMomentum,
                         Momentum, RMSProp)
from ..parallel import DGCMomentum

SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LarsMomentumOptimizer = LarsMomentum
DGCMomentumOptimizer = DGCMomentum

class ModelAverage(ExponentialMovingAverage):
    """reference optimizer.py ModelAverage — sliding parameter average
    applied at eval time. The accumulator is the EMA state; ``apply`` is a
    context that swaps averaged params in, ``restore`` swaps back."""

    def __init__(self, average_window_rate: float = 0.15, min_average_window=10000,
                 max_average_window=10000, **kw):
        # window-rate ≈ EMA decay mapping: long window -> decay near 1
        decay = 1.0 - 1.0 / max(float(max_average_window), 2.0)
        super().__init__(decay=decay)
        self._backup = None

    def apply(self, params=None, state=None, need_restore: bool = True):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            avg = self.average(state)
            self._backup = params
            yield avg
            if need_restore:
                self._backup = None

        return _ctx()

    def restore(self, executor=None):
        backup, self._backup = self._backup, None
        return backup

    # graph-mode Optimizer methods don't apply to an averaging accumulator
    def minimize(self, *a, **kw):
        from ..core.enforce import EnforceError

        raise EnforceError("ModelAverage accumulates params, it does not "
                           "optimize; use it around evaluation")

    backward = apply_gradients = apply_optimize = minimize

    def get_opti_var_name_list(self):
        return []
