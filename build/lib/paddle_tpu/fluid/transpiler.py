"""fluid.transpiler compat (reference:
python/paddle/fluid/transpiler/distribute_transpiler.py:130,164).

The reference rewrites a Program into trainer/pserver halves exchanging
tensors over RPC. That data plane is replaced wholesale by compiler
collectives over mesh axes (SURVEY §5.8): what transpile() *decided* —
which ranks hold which optimizer shards, how grads move — is now expressed
as sharding rules (`parallel.zero_dp_rules`, `parallel.ShardedEmbedding`)
and `fleet.init`. This module keeps the entry points so reference training
scripts keep a migration path: NCCL2 mode maps directly; PS program
surgery has no equivalent by design and says so.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.enforce import EnforceError


@dataclass
class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:130 — kept fields that still
    steer the TPU-native path; slice_var_up etc. are PS-sharding knobs
    subsumed by ZeRO sharding rules."""

    mode: str = "nccl2"          # collective mode is the TPU-native path
    slice_var_up: bool = True
    min_block_size: int = 8192
    sync_mode: bool = True


class HashName:
    """reference: ps_dispatcher.py HashName — pserver shard routing; kept
    for config compatibility (routing is mesh-sharding now)."""

    def __init__(self, pserver_endpoints):
        self.eps = list(pserver_endpoints)

    def dispatch(self, varlist):
        return [self.eps[hash(v if isinstance(v, str) else v.name)
                         % len(self.eps)] for v in varlist]

    def reset(self):
        pass


class RoundRobin:
    """reference: ps_dispatcher.py RoundRobin."""

    def __init__(self, pserver_endpoints):
        self.eps = list(pserver_endpoints)
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self.eps[self._i % len(self.eps)])
            self._i += 1
        return out

    def reset(self):
        self._i = 0


class DistributeTranspiler:
    """Entry-point shim. ``transpile`` in nccl2/collective mode configures
    the process group via fleet (the gen_nccl_id successor); pserver mode
    raises with the documented redesign."""

    def __init__(self, config: DistributeTranspilerConfig | None = None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id: int, program=None, pservers: str = "",
                  trainers=1, sync_mode: bool = True, startup_program=None,
                  current_endpoint: str = ""):
        if self.config.mode not in ("nccl2", "collective"):
            raise EnforceError(
                "parameter-server program rewriting is replaced by sharding "
                "rules in this framework (ZeRO: parallel.zero_dp_rules; "
                "sparse tables: parallel.ShardedEmbedding; bring-up: "
                "fleet.init) — see PARITY.md §2.5")
        self.trainer_id = trainer_id
        self.trainers = (trainers if isinstance(trainers, int)
                         else len(str(trainers).split(",")))
        self.program = program
        self._transpiled = True
        return self

    def get_trainer_program(self, wait_port: bool = True):
        if not self._transpiled:
            raise EnforceError("call transpile() first")
        # collective mode: the program is unchanged; gradients sync through
        # compiler-inserted collectives when run under parallel.Trainer
        return self.program

    def get_pserver_program(self, endpoint: str):
        raise EnforceError(
            "no pserver role exists: optimizer state shards via ZeRO rules "
            "(parallel.zero_dp_rules), embeddings via "
            "parallel.ShardedEmbedding (PARITY.md §2.5)")

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint: str, pserver_program=None):
        return self.get_pserver_program(endpoint)


def memory_optimize(*a, **kw):
    from . import memory_optimize as _mo

    return _mo(*a, **kw)


def release_memory(*a, **kw):
    from . import release_memory as _rm

    return _rm(*a, **kw)
