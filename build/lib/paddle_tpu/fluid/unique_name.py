"""fluid.unique_name compat (reference: python/paddle/fluid/unique_name.py):
process-wide unique name generator with guard/switch scoping."""

from __future__ import annotations

import contextlib
from collections import defaultdict

_counters = defaultdict(int)


def generate(key: str) -> str:
    _counters[key] += 1
    return f"{key}_{_counters[key] - 1}"


def switch(new_generator=None):
    global _counters
    old = _counters
    _counters = new_generator if new_generator is not None \
        else defaultdict(int)
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
