"""Parameter initializers.

Capability parity with the reference initializer set (reference:
python/paddle/fluid/initializer.py — Constant/Uniform/Normal/TruncatedNormal/
Xavier/MSRA/Bilinear/NumpyArray). The reference emits init *ops* into a
startup program; here an initializer is a pure function
``(key, shape, dtype) -> array`` — the startup-program role is played by
eager parameter creation at Layer construction.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels OIHW: receptive field * channels
    rf = math.prod(shape[2:])
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class Normal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.normal(key, shape, dtype) * self.scale + self.loc


class TruncatedNormal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0):
        self.loc, self.scale = loc, scale

    def __call__(self, key, shape, dtype=jnp.float32):
        return (jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
                * self.scale + self.loc)


class XavierUniform(Initializer):
    """reference: initializer.py XavierInitializer(uniform=True)."""

    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return jax.random.normal(key, shape, dtype) * std


class MSRA(Initializer):
    """Kaiming/He init (reference: initializer.py MSRAInitializer)."""

    def __init__(self, uniform: bool = True, fan_in=None):
        self.uniform = uniform
        self.fan_in = fan_in

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        fan_in = self.fan_in or fan_in
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            return jax.random.uniform(key, shape, dtype, -limit, limit)
        std = math.sqrt(2.0 / fan_in)
        return jax.random.normal(key, shape, dtype) * std


class Bilinear(Initializer):
    """Bilinear upsampling kernel for conv_transpose
    (reference: initializer.py BilinearInitializer)."""

    def __call__(self, key, shape, dtype=jnp.float32):
        # shape: (C_in, C_out, kh, kw) or (C, 1, kh, kw)
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        # standard bilinear kernel
        og = np.ogrid[:kh, :kw]
        center_h = (kh - 1) / 2.0
        center_w = (kw - 1) / 2.0
        filt = ((1 - np.abs(og[0] - center_h) / f_h)
                * (1 - np.abs(og[1] - center_w) / f_w))
        weight = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            weight[i, min(i, shape[1] - 1)] = filt
        return jnp.asarray(weight, dtype)


class NumpyArray(Initializer):
    """reference: initializer.py NumpyArrayInitializer — fixed values."""

    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, key, shape, dtype=jnp.float32):
        assert tuple(self.value.shape) == tuple(shape), \
            f"NumpyArray initializer shape {self.value.shape} != {shape}"
        return jnp.asarray(self.value, dtype)


# Paddle-style aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
MSRAInitializer = MSRA
BilinearInitializer = Bilinear
NumpyArrayInitializer = NumpyArray


def force_init_on_cpu() -> bool:
    """reference: initializer.py force_init_on_cpu — initializer placement
    is XLA's concern here; reported False always."""
    return False


import contextlib as _contextlib


@_contextlib.contextmanager
def init_on_cpu():
    """reference: initializer.py init_on_cpu context — a no-op scope: param
    init runs where XLA places it (host staging is automatic)."""
    yield
