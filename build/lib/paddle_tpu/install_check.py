"""Install sanity check (reference: python/paddle/fluid/install_check.py —
trains a tiny fc model to validate the install + device stack).

Usage: python -c "import paddle_tpu; paddle_tpu.install_check.run_check()"
"""

from __future__ import annotations

import numpy as np


def run_check(verbose: bool = True) -> bool:
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import optimizer

    def log(msg):
        if verbose:
            print(msg)

    devs = jax.devices()
    log(f"paddle_tpu {pt.__version__} — {len(devs)} device(s): "
        f"{devs[0].platform}")

    pt.seed(0)
    model = pt.nn.Sequential(pt.nn.Linear(4, 8, act="relu"),
                             pt.nn.Linear(8, 1))
    params = model.named_parameters()
    opt = optimizer.SGD(0.1)
    state = opt.init(params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    y = jnp.asarray((x.sum(axis=1, keepdims=True)))

    @jax.jit
    def step(params, state):
        def loss(p):
            out, _ = model.functional_call(p, x)
            return jnp.mean((out - y) ** 2)

        l, g = jax.value_and_grad(loss)(params)
        params, state = opt.apply(params, g, state)
        return params, state, l

    losses = []
    for _ in range(10):
        params, state, l = step(params, state)
        losses.append(float(l))
    ok = losses[-1] < losses[0] and np.isfinite(losses[-1])
    if ok:
        log(f"single-device train check ok (loss {losses[0]:.4f} -> "
            f"{losses[-1]:.4f})")
    else:
        log(f"FAILED: loss did not decrease ({losses})")

    if len(devs) > 1:
        mesh = pt.build_mesh(dp=len(devs))
        from jax.sharding import NamedSharding, PartitionSpec as P

        arr = jax.device_put(np.ones((len(devs) * 2, 4), np.float32),
                             NamedSharding(mesh, P("dp")))
        s = jax.jit(lambda a: a.sum())(arr)
        ok = ok and float(s) == len(devs) * 8
        log(f"multi-device sharding check ok over {len(devs)} devices")
    if ok:
        log("paddle_tpu is installed correctly!")
    return ok
