"""``paddle_tpu.layers`` — the complete ``fluid.layers`` user surface
(reference: python/paddle/fluid/layers/ + API.spec `paddle.fluid.layers.*`,
278 public names) as ONE flat, eager, functional namespace.

A reference user types ``fluid.layers.<name>``; every one of those names
resolves here to the TPU-native equivalent: most re-export the functional
op library (`paddle_tpu.ops.*`), LR-decay names construct scheduler objects
(`paddle_tpu.optimizer`), reader names map to the data pipeline
(`paddle_tpu.data`), and static-graph var helpers target the current
default Program when inside ``static.program_guard``. Coverage against the
reference's frozen API.spec is asserted by tests/test_layers_compat.py.

Dygraph-style layers with managed parameters live in ``paddle_tpu.nn``;
Program-recording static layers in ``paddle_tpu.static.layers``.
"""

from __future__ import annotations

from typing import Optional as _Optional, Sequence as _Sequence

import jax
import jax.numpy as jnp

from . import data as _data
from . import initializer as _I
from . import metrics as _metrics
from . import optimizer as _opt
from .ops import control_flow as _CF
from .ops import decode as _DE
from .ops import detection as _D
from .ops import detection_extra as _DX
from .ops import loss as _L
from .ops import math as _M
from .ops import nn as _N
from .ops import nn_extra as _NE
from .ops import reduction as _R
from .ops import rnn as _RN
from .ops import sampling as _SA
from .ops import sequence as _SQ
from .ops import tensor as _T

# --- activations & elementwise math (ops.math) -----------------------------
abs = _M.abs
acos = _M.acos
asin = _M.asin
atan = _M.atan
brelu = _M.brelu
ceil = _M.ceil
clip = _M.clip
clip_by_norm = _M.clip_by_norm
cos = _M.cos
cos_sim = _M.cos_sim
cumsum = _M.cumsum
elementwise_add = _M.elementwise_add
elementwise_div = _M.elementwise_div
elementwise_floordiv = _M.elementwise_floordiv
elementwise_max = _M.elementwise_max
elementwise_min = _M.elementwise_min
elementwise_mod = _M.elementwise_mod
elementwise_mul = _M.elementwise_mul
elementwise_pow = _M.elementwise_pow
elementwise_sub = _M.elementwise_sub
elu = _M.elu
exp = _M.exp
floor = _M.floor
hard_shrink = _M.hard_shrink
hard_sigmoid = _M.hard_sigmoid
has_inf = _M.has_inf
has_nan = _M.has_nan
increment = _M.increment
isfinite = _M.isfinite
leaky_relu = _M.leaky_relu
log = _M.log
logsigmoid = _M.logsigmoid
matmul = _M.matmul
maxout = _M.maxout
mul = _M.mul
pow = _M.pow
prelu = _M.prelu
reciprocal = _M.reciprocal
relu = _M.relu
relu6 = _M.relu6
round = _M.round
rsqrt = _M.rsqrt
scale = _M.scale
selu = _M.selu
sigmoid = _M.sigmoid
sign = _M.sign
sin = _M.sin
soft_relu = _M.soft_relu
softplus = _M.softplus
softshrink = _M.softshrink
softsign = _M.softsign
sqrt = _M.sqrt
square = _M.square
stanh = _M.stanh
swish = _M.swish
tanh = _M.tanh
tanh_shrink = _M.tanh_shrink
thresholded_relu = _M.thresholded_relu
bilinear_tensor_product = _M.bilinear_tensor_product

# --- reductions ------------------------------------------------------------
mean = _R.mean
reduce_all = _R.reduce_all
reduce_any = _R.reduce_any
reduce_max = _R.reduce_max
reduce_mean = _R.reduce_mean
reduce_min = _R.reduce_min
reduce_prod = _R.reduce_prod
reduce_sum = _R.reduce_sum
sum = _R.sum
sums = _R.sum  # pre-1.0 name for elementwise list sum

# --- NN ops ----------------------------------------------------------------
adaptive_pool2d = _N.adaptive_pool2d
adaptive_pool3d = _NE.adaptive_pool3d
batch_norm = _N.batch_norm
conv2d = _N.conv2d
conv2d_transpose = _NE.conv2d_transpose
conv3d = _N.conv3d
conv3d_transpose = _NE.conv3d_transpose
data_norm = _NE.data_norm
dropout = _N.dropout
embedding = _N.embedding
grid_sampler = _N.grid_sampler
group_norm = _N.group_norm
l2_normalize = _N.l2_normalize
layer_norm = _N.layer_norm
lrn = _N.lrn
one_hot = _N.one_hot
pad2d = _N.pad2d
pixel_shuffle = _N.pixel_shuffle
pool2d = _N.pool2d
pool3d = _NE.pool3d
shuffle_channel = _N.shuffle_channel
softmax = _N.softmax
space_to_depth = _N.space_to_depth
temporal_shift = _N.temporal_shift
affine_channel = _NE.affine_channel
affine_grid = _NE.affine_grid
fsp_matrix = _NE.fsp_matrix
similarity_focus = _NE.similarity_focus
tree_conv = _NE.tree_conv
continuous_value_model = _NE.cvm
resize_bilinear = _NE.bilinear_interp
resize_nearest = _NE.nearest_interp
image_resize_short = _NE.image_resize_short


def image_resize(input, out_shape, resample: str = "BILINEAR"):
    """reference: layers/nn.py image_resize (BILINEAR/NEAREST)."""
    method = {"BILINEAR": "bilinear", "NEAREST": "nearest"}.get(
        resample.upper(), resample.lower())
    return _N.interpolate(input, tuple(out_shape), method=method)


def spectral_norm(weight, dim: int = 0, power_iters: int = 1,
                  eps: float = 1e-12):
    """Functional one-shot form; the u/v power-iteration state lives in
    nn.SpectralNorm for training (reference: layers/nn.py spectral_norm)."""
    h = weight.shape[dim]
    wmat = jnp.moveaxis(weight, dim, 0).reshape(h, -1)
    u = jax.random.normal(jax.random.key(0), (h,), weight.dtype)
    v = jax.random.normal(jax.random.key(1), (wmat.shape[1],), weight.dtype)
    out, _, _ = _NE.spectral_norm(weight, u, v, dim=dim,
                                  power_iters=max(power_iters, 8), eps=eps)
    return out


# --- losses ----------------------------------------------------------------
bpr_loss = _L.bpr_loss
cross_entropy = _L.cross_entropy
dice_loss = _L.dice_loss
huber_loss = _L.huber_loss
kldiv_loss = _L.kldiv_loss
label_smooth = _L.label_smooth
log_loss = _L.log_loss
margin_rank_loss = _L.margin_rank_loss
npair_loss = _L.npair_loss
rank_loss = _L.rank_loss
sampled_softmax_with_cross_entropy = _L.sampled_softmax_with_cross_entropy
sigmoid_cross_entropy_with_logits = _L.sigmoid_cross_entropy_with_logits
smooth_l1 = _L.smooth_l1
softmax_with_cross_entropy = _L.softmax_with_cross_entropy
square_error_cost = _L.square_error_cost
teacher_student_sigmoid_loss = _L.teacher_student_sigmoid_loss
warpctc = _DE.ctc_loss

# --- sampling heads --------------------------------------------------------
hsigmoid = _SA.hsigmoid_loss
nce = _SA.nce_loss
sampling_id = _SA.sampling_id

# --- decode / CRF ----------------------------------------------------------
beam_search = _DE.beam_search
beam_search_decode = _DE.beam_search_decode
beam_search_step = _DE.beam_search_batch_step
beam_search_decode_lod = _DE.beam_search_decode_lod
gather_beams = _DE.gather_beams
crf_decoding = _DE.crf_decoding
ctc_greedy_decoder = _DE.ctc_greedy_decode
edit_distance = _DE.edit_distance
linear_chain_crf = _DE.linear_chain_crf

# --- tensor manipulation ---------------------------------------------------
argmax = _T.arg_max
argmin = _T.arg_min
argsort = _T.argsort
assign = _T.assign
cast = _T.cast
concat = _T.concat
crop = _T.crop
diag = _T.diag
expand = _T.expand
def fill_constant(shape, dtype=None, value=0.0, force_cpu=False, out=None):
    """Static mode (inside program_guard) records a Program var — the
    block-DSL's loop counters/conditions need Var identity; eager mode
    returns the array (reference: layers/tensor.py fill_constant)."""
    from .static.program import is_building

    if out is not None or is_building():
        from .static import layers as _SL

        return _SL.fill_constant(shape, dtype or "float32", value,
                                 force_cpu=force_cpu, out=out)
    return _T.fill_constant(shape, value, dtype or jnp.float32)


fill_constant_batch_size_like = _T.fill_constant_batch_size_like
flatten = _T.flatten
gather = _T.gather
gaussian_random = _T.gaussian_random
is_empty = _T.is_empty
linspace = _T.linspace
multiplex = _T.multiplex
ones = _T.ones
pad = _T.pad
pad_constant_like = _T.pad_constant_like
random_crop = _T.random_crop
range = _T.arange
reshape = _T.reshape
reverse = _T.reverse
scatter = _T.scatter
shape = _T.shape
slice = _T.slice
split = _T.split
squeeze = _T.squeeze
stack = _T.stack
topk = _T.top_k
transpose = _T.transpose
uniform_random = _T.uniform_random
unsqueeze = _T.unsqueeze
unstack = _T.unstack
where = _T.where_index
def zeros(shape, dtype="float32", force_cpu=False):
    from .static.program import is_building

    if is_building():
        from .static import layers as _SL

        return _SL.zeros(shape, dtype, force_cpu)
    return _T.zeros(shape, dtype)


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=dtype)


def rank(x):
    """reference: layers/nn.py rank — ndim as a 0-d int tensor."""
    return jnp.asarray(jnp.ndim(x), jnp.int32)


def gaussian_random_batch_size_like(input, shape, mean: float = 0.0,
                                    std: float = 1.0, seed: int = 0):
    shp = (input.shape[0],) + tuple(shape[1:])
    return _T.gaussian_random(shp, mean=mean, std=std, seed=seed)


def uniform_random_batch_size_like(input, shape, min: float = -1.0,
                                   max: float = 1.0, seed: int = 0):
    shp = (input.shape[0],) + tuple(shape[1:])
    return _T.uniform_random(shp, min=min, max=max, seed=seed)


# --- compare / logical / control flow --------------------------------------
equal = _CF.equal
greater_equal = _CF.greater_equal
greater_than = _CF.greater_than
less_equal = _CF.less_equal
less_than = _CF.less_than
logical_and = _CF.logical_and
logical_not = _CF.logical_not
logical_or = _CF.logical_or
logical_xor = _CF.logical_xor
not_equal = _CF.not_equal

# Block-style control flow: the reference's recording block DSL (static
# Programs — static/control_flow.py lowers the recorded body to
# lax.while_loop/scan), with a __new__ escape to the functional
# lax-backed forms for eager callers (SURVEY §2.2 control flow):
from .static import control_flow as _SCF  # noqa: E402


class Switch(_SCF.Switch):
    """``with Switch() as s: with s.case(cond): ...`` in static mode
    (reference: layers/control_flow.py Switch — first-match case chain);
    ``Switch(branch_index, branch_fns, *ops)`` runs the functional
    lax.switch form."""

    def __new__(cls, *args, **kwargs):
        if args and not isinstance(args[0], str):
            return _CF.switch_case(*args, **kwargs)
        return super().__new__(cls)


class While(_SCF.While):
    """``While(cond_var)`` + ``with w.block():`` in static mode
    (reference: layers/control_flow.py:593); ``While(cond_fn, body_fn,
    loop_vars)`` runs the functional lax.while_loop form."""

    def __new__(cls, cond, *args, **kwargs):
        from .static.program import Var as _Var

        if isinstance(cond, _Var) and not args:
            return super().__new__(cls)
        return _CF.while_loop(cond, *args, **kwargs)


class IfElse(_SCF.IfElse):
    """``IfElse(cond_var)`` + true_block()/false_block() in static mode
    (reference: layers/control_flow.py:1489); ``IfElse(pred, true_fn,
    false_fn, *ops)`` runs the functional lax.cond form."""

    def __new__(cls, cond, *args, **kwargs):
        from .static.program import Var as _Var

        if isinstance(cond, _Var) and not args:
            return super().__new__(cls)
        return _CF.cond(cond, *args, **kwargs)


class StaticRNN(_SCF.StaticRNN):
    """No-arg construction opens the recording block DSL (reference:
    layers/control_flow.py:268); a callable first arg runs the functional
    scan form ``static_rnn(cell_fn, ...)``."""

    def __new__(cls, *args, **kwargs):
        if args and callable(args[0]):
            return _CF.static_rnn(*args, **kwargs)
        return super().__new__(cls)


class DynamicRNN(_SCF.DynamicRNN):
    """No-arg construction opens the recording block DSL (reference:
    layers/control_flow.py:1619); a callable first arg runs the
    functional masked-scan form ``dynamic_rnn(cell_fn, x, init, ...)``."""

    def __new__(cls, *args, **kwargs):
        if args and callable(args[0]):
            return _RN.dynamic_rnn(*args, **kwargs)
        return super().__new__(cls)


def Print(input, message: str = "", summarize: int = 20, **_kw):
    """reference: layers/control_flow.py Print — jit-compatible tensor
    print; returns its input so it composes inside traced code."""
    # jax.debug.print's format parser mishandles escaped braces; a plain
    # callback prints arbitrary user messages safely
    jax.debug.callback(lambda v, _m=message: print(_m + str(v)), input)
    return input


# --- TensorArray interface -------------------------------------------------
class _EagerArray:
    """Growable host-side tensor array for eager loops (reference:
    layers/control_flow.py create_array / tensor_array ops). Inside jit
    use ops.control_flow.TensorArray (static capacity, lax-friendly)."""

    def __init__(self, dtype="float32"):
        self.dtype, self._items = dtype, []

    def write(self, i, x):
        i = int(i)
        self._items.extend([None] * (i + 1 - len(self._items)))
        self._items[i] = jnp.asarray(x)
        return self

    def read(self, i):
        return self._items[int(i)]

    def length(self):
        return jnp.asarray(len(self._items))

    def stack(self, axis: int = 0):
        return jnp.stack(self._items, axis=axis)


def create_array(dtype="float32", capacity: int = 64):
    from .static.program import is_building

    if is_building():
        from .static import layers as _SL

        return _SL.create_array(dtype, capacity)
    return _EagerArray(dtype)


def array_write(x, i, array=None, capacity: int = 64):
    from .static.layers import StaticArray
    from .static.program import Var as _Var, is_building

    if isinstance(array, StaticArray) or isinstance(x, _Var) or \
            is_building():
        from .static import layers as _SL

        return _SL.array_write(x, i, array, capacity)
    if array is None:
        array = create_array(x.dtype)
    return array.write(i, x)


def array_read(array, i):
    from .static.layers import StaticArray

    if isinstance(array, StaticArray):
        from .static import layers as _SL

        return _SL.array_read(array, i)
    return array.read(i)


def array_length(array):
    from .static.layers import StaticArray

    if isinstance(array, StaticArray):
        from .static import layers as _SL

        return _SL.array_length(array)
    return array.length()


def tensor_array_to_tensor(array, axis: int = 0):
    from .static.layers import StaticArray

    if isinstance(array, StaticArray):
        from .static import layers as _SL

        return _SL.tensor_array_to_tensor(array, axis)
    stacked = array.stack()
    return stacked, jnp.asarray(stacked.shape[axis])


# --- sequence ops (padded + lengths; SURVEY §5.7) --------------------------
add_position_encoding = _SQ.add_position_encoding
hash = _SQ.hash_embedding_ids
im2sequence = _SQ.im2sequence
sequence_concat = _SQ.sequence_concat
sequence_enumerate = _SQ.sequence_enumerate
sequence_expand = _SQ.sequence_expand
sequence_expand_as = _SQ.sequence_expand_as
sequence_mask = _SQ.sequence_mask
sequence_pad = _SQ.sequence_pad
sequence_pool = _SQ.sequence_pool
sequence_reshape = _SQ.sequence_reshape
sequence_reverse = _SQ.sequence_reverse
sequence_scatter = _SQ.sequence_scatter
sequence_slice = _SQ.sequence_slice
sequence_softmax = _SQ.sequence_softmax
sequence_unpad = _SQ.sequence_unpad
sequence_conv = _RN.sequence_conv
row_conv = _RN.row_conv


def sequence_first_step(x, lengths=None):
    return _SQ.sequence_pool(x, lengths, pool_type="first")


def sequence_last_step(x, lengths=None):
    return _SQ.sequence_pool(x, lengths, pool_type="last")


def lod_reset(x, lengths):
    """LoD → lengths-vector design: 'resetting the LoD' is just pairing
    the data with a new lengths vector (SURVEY §7 LoD replacement)."""
    return x, jnp.asarray(lengths)


def reorder_lod_tensor_by_rank(x, rank_table):
    """reference: operators/reorder_lod_tensor_by_rank_op.cc — permute the
    batch by a rank table (descending-length order). rank_table: the
    permutation indices (e.g. jnp.argsort(-lengths))."""
    return jnp.take(x, jnp.asarray(rank_table), axis=0)


# SelectedRows existed for sparse gradients; grads are dense here and giant
# tables shard via parallel.ShardedEmbedding (OP_COVERAGE.md):
def get_tensor_from_selected_rows(x):
    return x


def merge_selected_rows(x):
    return x


# --- RNN -------------------------------------------------------------------
dynamic_gru = _RN.gru
dynamic_lstm = _RN.lstm
dynamic_lstmp = _RN.lstmp
gru_unit = _RN.gru_unit
lstm = _RN.lstm
lstm_unit = _RN.lstm_unit

# --- detection -------------------------------------------------------------
anchor_generator = _D.anchor_generator
bipartite_match = _D.bipartite_match
box_clip = _D.box_clip
box_coder = _D.box_coder
box_decoder_and_assign = _DX.box_decoder_and_assign
collect_fpn_proposals = _D.collect_fpn_proposals
density_prior_box = _D.density_prior_box
detection_output = _D.detection_output
distribute_fpn_proposals = _D.distribute_fpn_proposals
generate_mask_labels = _DX.generate_mask_labels
generate_proposal_labels = _DX.generate_proposal_labels
generate_proposals = _D.generate_proposals
iou_similarity = _D.iou_similarity
from .nn.layers import MultiBoxHead as multi_box_head  # noqa: E402
multiclass_nms = _D.multiclass_nms
polygon_box_transform = _D.polygon_box_transform
prior_box = _D.prior_box
psroi_pool = _DX.psroi_pool
roi_align = _D.roi_align
roi_perspective_transform = _DX.roi_perspective_transform
roi_pool = _D.roi_pool
rpn_target_assign = _DX.rpn_target_assign
ssd_loss = _D.ssd_loss
target_assign = _D.target_assign
yolo_box = _D.yolo_box
yolov3_loss = _DX.yolov3_loss

def fc(input, size: _Optional[int] = None, weight=None, bias=None,
       act: _Optional[str] = None, name: str = "fc", **kw):
    """reference: layers/nn.py fc:210. Eager form takes explicit weight
    (nn.Linear owns managed params); inside static.program_guard it
    records onto the current Program like fluid's fc."""
    from .static import program as _prog_mod

    if weight is None:
        from .static import layers as _SL

        return _SL.fc(input, size, act=act, name=name, **kw)
    out = jnp.matmul(input, weight)
    if bias is not None:
        out = out + bias
    if act is not None:
        out = getattr(_M, act)(out)
    return out


# --- metrics ---------------------------------------------------------------
accuracy = _metrics.accuracy
auc = _metrics.auc_terms
chunk_eval = _metrics.chunk_eval
detection_map = _metrics.detection_map
mean_iou = _metrics.mean_iou

# --- LR schedules (reference: layers/learning_rate_scheduler.py) -----------
# fluid's decay layers emit a lr Variable; the TPU-native form returns a
# scheduler object every paddle_tpu optimizer accepts as learning_rate.
def cosine_decay(learning_rate, step_each_epoch, epochs):
    return _opt.CosineDecay(learning_rate, step_each_epoch, epochs)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase: bool = False):
    return _opt.ExponentialDecay(learning_rate, decay_steps, decay_rate,
                                 staircase)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase: bool = False):
    return _opt.InverseTimeDecay(learning_rate, decay_steps, decay_rate,
                                 staircase)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase: bool = False):
    return _opt.NaturalExpDecay(learning_rate, decay_steps, decay_rate,
                                staircase)


def noam_decay(d_model, warmup_steps):
    return _opt.NoamDecay(d_model, warmup_steps)


def piecewise_decay(boundaries, values):
    return _opt.PiecewiseDecay(boundaries, values)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    return _opt.PolynomialDecay(learning_rate, decay_steps,
                                end_learning_rate, power, cycle)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    return _opt.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)


# --- data / reader layer (reference: layers/io.py) -------------------------
batch = _data.batch
shuffle = _data.shuffle
double_buffer = _data.buffered


def data(name: str, shape, dtype=None, lod_level: int = 0):
    """Declare a feed var on the current default static Program
    (reference: layers/io.py data). Inside dygraph/eager code, arrays are
    passed directly and this is not needed."""
    from .static import default_main_program

    return default_main_program().data(name, shape, dtype,
                                       lod_level=lod_level)


class _PyReader:
    """reference: fluid/layers/io.py py_reader / fluid/reader.py PyReader —
    decorate with a batch source, then iterate device-resident batches
    (data.DeviceLoader is the async host→device double-buffer)."""

    def __init__(self, capacity: int):
        self.capacity, self.loader = capacity, None

    def decorate(self, batches, transform=None, sharding=None):
        self.loader = _data.DeviceLoader(batches, transform, sharding,
                                         capacity=self.capacity)
        return self.loader

    decorate_sample_list_generator = decorate
    decorate_batch_generator = decorate
    decorate_sample_generator = decorate

    def start(self):
        """reference: reader.py PyReader.start — arm the pipeline; the
        DeviceLoader starts its prefetch thread on iteration."""
        return self

    def reset(self):
        """reference: PyReader.reset — drop buffered batches so the next
        epoch re-iterates the source."""
        if self.loader is not None and hasattr(self.loader, "reset"):
            self.loader.reset()
        return self

    def __iter__(self):
        return iter(self.loader)


def py_reader(capacity: int, shapes=None, dtypes=None, names=None):
    return _PyReader(capacity)


def create_py_reader_by_data(capacity: int = 2, feed_list=None):
    return _PyReader(capacity)


def read_file(reader):
    """reference: layers/io.py read_file — pull the NEXT element from a
    reader factory (readers are plain python iterables here); iterator
    state is kept per reader object so successive calls advance."""
    it = getattr(reader, "_pt_iter", None)
    if it is None:
        it = iter(reader())
        try:
            reader._pt_iter = it
        except AttributeError:
            pass  # unwritable callable: degrade to fresh iteration
    try:
        return next(it)
    except StopIteration:
        if hasattr(reader, "_pt_iter"):
            del reader._pt_iter
        raise


def open_files(filenames: _Sequence[str], batch_size: int = 1, **_kw):
    """Line-oriented multi-file reader (role of the reference's
    open_files/recordio readers on modern storage)."""
    def reader():
        for fname in filenames:
            with open(fname) as f:
                for line in f:
                    yield line.rstrip("\n")

    return reader


def random_data_generator(low: float, high: float, shapes, lod_levels=None,
                          seed: int = 0):
    """reference: reader/create_random_data_generator_op.cc."""
    import numpy as np

    rng = np.random.default_rng(seed)

    def reader():
        while True:
            yield tuple(rng.uniform(low, high, s).astype(np.float32)
                        for s in shapes)

    return reader


class Preprocessor:
    """reference: layers/io.py Preprocessor — map a transform over a
    reader pipeline."""

    def __init__(self, reader, name: _Optional[str] = None):
        self.reader, self._fn = reader, None

    def block(self, fn):
        self._fn = fn
        return self

    def inputs(self):
        return self.reader

    def outputs(self, *outs):
        return outs

    def __call__(self):
        return _data.map_readers(self._fn, self.reader)()


# --- static-graph var helpers ---------------------------------------------
def create_tensor(dtype="float32", name: _Optional[str] = None):
    """Eager analog of layers/tensor.py create_tensor: a 0-d placeholder
    value (assign into it via ordinary rebinding)."""
    return jnp.zeros((), dtype=dtype)


def create_global_var(shape, value, dtype="float32",
                      persistable: bool = False, force_cpu: bool = False,
                      name: _Optional[str] = None):
    return jnp.full(tuple(shape), value, dtype=dtype)


def create_parameter(shape, dtype="float32", name: _Optional[str] = None,
                     attr=None, is_bias: bool = False,
                     default_initializer=None):
    """Inside static.program_guard: creates a trainable Program parameter.
    Eager: returns the initialized array (nn.Layer owns named params)."""
    from .static import program as _prog_mod

    init = default_initializer or (_I.Constant(0.0) if is_bias
                                   else _I.XavierUniform())
    prog = _prog_mod.default_main_program()
    pname = name or prog.unique_name("param")
    return prog.create_parameter(pname, tuple(shape), dtype, initializer=init)


class _StepCounter:
    """Host-side persistent step counter (reference: layers/nn.py
    autoincreased_step_counter — jitted steps carry their own step state;
    this covers the host-loop bookkeeping role)."""

    def __init__(self, begin: int = 1, step: int = 1):
        self.value, self.step = begin - step, step

    def __call__(self):
        self.value += self.step
        return jnp.asarray(self.value, jnp.int64)


def autoincreased_step_counter(counter_name: _Optional[str] = None,
                               begin: int = 1, step: int = 1):
    return _StepCounter(begin, step)


def load(out, file_path: str, load_as_fp16: bool = False):
    """reference: operators/load_op.cc — load one saved array
    (checkpoint.py owns whole-state save/load)."""
    import numpy as np

    arr = np.load(file_path, allow_pickle=False)
    return jnp.asarray(arr, jnp.float16 if load_as_fp16 else None)


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """reference: operators/py_func_op.cc — in an eager/functional
    framework arbitrary python composes directly; provided for API parity."""
    xs = x if isinstance(x, (list, tuple)) else (x,)
    return func(*xs)



# --- static-graph polymorphism ---------------------------------------------
# Reference users call fluid.layers.* on Program Vars inside
# fluid.program_guard. Every function in this namespace dispatches: eager
# arrays run directly; static Vars record the SAME computation onto their
# Program (Program.apply traces it). Param-creating layers (fc, conv2d,
# embedding, batch_norm, ...) route to static.layers, which owns Program
# parameter creation (reference LayerHelper role).

def _wrap_static_dispatch(name, f):
    import functools

    import jax.tree_util as _jtu

    def _is_var(x):
        from .static.program import Var

        return isinstance(x, Var)

    @functools.wraps(f)
    def wrapper(*args, **kwargs):
        from .static import layers as _SL

        leaves, treedef = _jtu.tree_flatten((args, kwargs), is_leaf=_is_var)
        var_pos = [i for i, l in enumerate(leaves) if _is_var(l)]
        if not var_pos:
            return f(*args, **kwargs)
        static_impl = getattr(_SL, name, None)
        if static_impl is not None and static_impl is not wrapper:
            return static_impl(*args, **kwargs)
        prog = leaves[var_pos[0]].program

        def fn(*vals):
            new_leaves = list(leaves)
            for i, v in zip(var_pos, vals):
                new_leaves[i] = v
            a, kw = _jtu.tree_unflatten(treedef, new_leaves)
            return f(*a, **kw)

        return prog.apply(fn, [leaves[i] for i in var_pos], name=name)

    return wrapper


def _apply_static_dispatch():
    import types

    g = globals()
    skip = {"data", "create_parameter", "create_global_var", "create_tensor",
            "py_func", "Print", "py_reader", "create_py_reader_by_data",
            "read_file", "open_files", "random_data_generator", "batch",
            "shuffle", "double_buffer", "load", "fc",
            "autoincreased_step_counter", "create_array", "array_write",
            "array_read", "array_length", "tensor_array_to_tensor",
            "While", "IfElse", "StaticRNN", "DynamicRNN", "Switch",
            "fill_constant", "zeros"}
    for name, obj in list(g.items()):
        if name.startswith("_") or name in skip:
            continue
        if isinstance(obj, types.FunctionType) or (
                callable(obj) and not isinstance(obj, type)
                and hasattr(obj, "__module__")
                and str(getattr(obj, "__module__", "")).startswith(
                    "paddle_tpu")):
            g[name] = _wrap_static_dispatch(name, obj)


_apply_static_dispatch()
