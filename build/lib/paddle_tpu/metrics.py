"""Metrics — capability parity with the reference metrics stack
(reference: python/paddle/fluid/metrics.py — Accuracy, Precision, Recall, Auc,
EditDistance, CompositeMetric; metric ops operators/metrics/accuracy_op.cc,
auc_op.cc).

Two pieces, like the reference: an in-graph *op* part (pure functions usable
under jit) and host-side *accumulators* (the MetricBase role).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np


# --- in-graph metric ops ---------------------------------------------------

def accuracy(pred_logits, label, k: int = 1):
    """reference: operators/metrics/accuracy_op.cc — top-k accuracy."""
    label = label.reshape(-1)
    if k == 1:
        correct = (jnp.argmax(pred_logits, axis=-1) == label)
        return jnp.mean(correct.astype(jnp.float32))
    topk = jnp.argsort(pred_logits, axis=-1)[..., -k:]
    correct = jnp.any(topk == label[:, None], axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


def auc_terms(probs, label, num_thresholds: int = 200):
    """Histogram terms for streaming AUC (reference: operators/metrics/
    auc_op.cc) — returns (tp, fp) histograms to be accumulated host-side."""
    pos_prob = probs[:, 1] if probs.ndim == 2 else probs
    label = label.reshape(-1)
    idx = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                   num_thresholds)
    tp = jnp.zeros(num_thresholds + 1).at[idx].add(label.astype(jnp.float32))
    fp = jnp.zeros(num_thresholds + 1).at[idx].add(1.0 - label.astype(jnp.float32))
    return tp, fp


# --- host-side accumulators ------------------------------------------------

class MetricBase:
    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """reference: metrics.py Accuracy — weighted running average."""

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            return 0.0
        return self.value / self.weight


class Auc(MetricBase):
    """reference: metrics.py Auc — trapezoidal over threshold histogram."""

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self.tp = np.zeros(self.num_thresholds + 1)
        self.fp = np.zeros(self.num_thresholds + 1)

    def update(self, probs, label):
        tp, fp = auc_terms(jnp.asarray(probs), jnp.asarray(label),
                           self.num_thresholds)
        self.tp += np.asarray(tp)
        self.fp += np.asarray(fp)

    def eval(self):
        # cumulative from the top threshold down → ROC points
        tp_cum = np.cumsum(self.tp[::-1])
        fp_cum = np.cumsum(self.fp[::-1])
        total_pos = tp_cum[-1]
        total_neg = fp_cum[-1]
        if total_pos == 0 or total_neg == 0:
            return 0.0
        # prepend the (0,0) ROC anchor so mass in the top bucket still
        # integrates over the full curve (degenerate case → 0.5, not 0)
        tpr = np.concatenate([[0.0], tp_cum / total_pos])
        fpr = np.concatenate([[0.0], fp_cum / total_neg])
        return float(np.trapezoid(tpr, fpr))


class Precision(MetricBase):
    """reference: metrics.py Precision (binary)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self):
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class EditDistance(MetricBase):
    """reference: metrics.py EditDistance + operators/edit_distance_op.cc."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self.seq_right = 0

    @staticmethod
    def _levenshtein(a, b) -> int:
        m, n = len(a), len(b)
        dp = list(range(n + 1))
        for i in range(1, m + 1):
            prev = dp[0]
            dp[0] = i
            for j in range(1, n + 1):
                cur = dp[j]
                dp[j] = min(dp[j] + 1, dp[j - 1] + 1,
                            prev + (a[i - 1] != b[j - 1]))
                prev = cur
        return dp[n]

    def update(self, hyps, refs):
        for h, r in zip(hyps, refs):
            d = self._levenshtein(list(h), list(r))
            if self.normalized:
                d = d / max(len(r), 1)
            self.total += d
            self.count += 1
            if d == 0:
                self.seq_right += 1

    def eval(self):
        avg = self.total / self.count if self.count else 0.0
        instance_err = 1.0 - (self.seq_right / self.count if self.count else 0.0)
        return avg, instance_err


class CompositeMetric(MetricBase):
    """reference: metrics.py CompositeMetric."""

    def __init__(self, *metrics: MetricBase):
        self.metrics = list(metrics)

    def add_metric(self, m: MetricBase):
        self.metrics.append(m)

    def reset(self):
        for m in self.metrics:
            m.reset()

    def update(self, *args, **kwargs):
        for m in self.metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self.metrics]


def chunk_eval(input, label, chunk_scheme: str = "IOB",
               num_chunk_types: int = 1, excluded_chunk_types=None,
               seq_lens=None):
    """Sequence-chunking precision/recall/F1 (reference:
    operators/chunk_eval_op.cc + layers/nn.py chunk_eval). Thin wrapper
    over :func:`paddle_tpu.ops.sequence.chunk_eval` with the fluid
    argument order; ``seq_lens`` defaults to full rows (padded-dense
    representation — the LoD replacement)."""
    from .ops.sequence import chunk_eval as _ce

    input = jnp.asarray(input)
    if seq_lens is None:
        t = input.shape[-1] if input.ndim > 1 else input.shape[0]
        b = input.shape[0] if input.ndim > 1 else 1
        seq_lens = jnp.full((b,), t, jnp.int32)
    return _ce(input, label, seq_lens, num_chunk_types, chunk_scheme,
               tuple(excluded_chunk_types or ()))


class ChunkEvaluator(MetricBase):
    """reference: metrics.py:361 ChunkEvaluator — accumulates
    chunk_eval's counters over mini-batches; eval() returns
    (precision, recall, f1)."""

    def __init__(self, name=None):
        self.name = name
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


def mean_iou(pred, label, num_classes: int):
    """reference: operators/mean_iou_op.cc — mean intersection-over-union
    over classes present in pred or label. Returns (mean_iou, per-class
    intersection, per-class union)."""
    import jax

    pred = pred.reshape(-1).astype(jnp.int32)
    label = label.reshape(-1).astype(jnp.int32)
    onehot_p = jax.nn.one_hot(pred, num_classes)
    onehot_l = jax.nn.one_hot(label, num_classes)
    inter = jnp.sum(onehot_p * onehot_l, axis=0)
    union = jnp.sum(onehot_p, axis=0) + jnp.sum(onehot_l, axis=0) - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    return miou, inter, union


def precision_recall(pred_probs, label, num_classes: int):
    """reference: operators/metrics/precision_recall_op.cc — per-class and
    macro/micro precision/recall/F1 from argmax predictions. Returns a dict
    of scalars + per-class (tp, fp, fn)."""
    import jax

    pred = jnp.argmax(pred_probs, axis=-1)
    onehot_p = jax.nn.one_hot(pred, num_classes)
    onehot_l = jax.nn.one_hot(label.reshape(-1), num_classes)
    tp = jnp.sum(onehot_p * onehot_l, axis=0)
    fp = jnp.sum(onehot_p * (1 - onehot_l), axis=0)
    fn = jnp.sum((1 - onehot_p) * onehot_l, axis=0)
    prec = tp / jnp.maximum(tp + fp, 1.0)
    rec = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-9)
    micro_p = jnp.sum(tp) / jnp.maximum(jnp.sum(tp + fp), 1.0)
    micro_r = jnp.sum(tp) / jnp.maximum(jnp.sum(tp + fn), 1.0)
    return {
        "macro_precision": jnp.mean(prec), "macro_recall": jnp.mean(rec),
        "macro_f1": jnp.mean(f1), "micro_precision": micro_p,
        "micro_recall": micro_r,
        "micro_f1": 2 * micro_p * micro_r / jnp.maximum(
            micro_p + micro_r, 1e-9),
        "tp": tp, "fp": fp, "fn": fn,
    }


def positive_negative_pair(score, label, query_id):
    """reference: operators/metrics/positive_negative_pair_op.cc — ranking
    metric: among same-query item pairs with different labels, count pairs
    ranked correctly (higher label → higher score), wrong, or tied."""
    s = score.reshape(-1)
    l = label.reshape(-1).astype(jnp.float32)
    q = query_id.reshape(-1)
    same_q = q[:, None] == q[None, :]
    upper = jnp.triu(jnp.ones((s.size, s.size), jnp.bool_), k=1)
    valid = same_q & upper & (l[:, None] != l[None, :])
    sdiff = s[:, None] - s[None, :]
    ldiff = l[:, None] - l[None, :]
    pos = jnp.sum(valid & (sdiff * ldiff > 0))
    neg = jnp.sum(valid & (sdiff * ldiff < 0))
    neu = jnp.sum(valid & (sdiff == 0))
    return pos, neg, neu


def detection_map(det_boxes, det_scores, det_labels, gt_boxes, gt_labels,
                  *, num_classes: int, overlap_threshold: float = 0.5):
    """reference: operators/detection_map_op.cc — mean average precision
    (11-point interpolated) over classes for one image batch. Dense/static
    simplification: detections (D, 4)+(D,)+(D,); gts (G, 4)+(G,); padded
    entries have label < 0."""
    from .ops.detection import iou_similarity
    import numpy as np_  # host-side: mAP is an eval-time metric

    det_boxes = np_.asarray(det_boxes)
    det_scores = np_.asarray(det_scores)
    det_labels = np_.asarray(det_labels)
    gt_boxes = np_.asarray(gt_boxes)
    gt_labels = np_.asarray(gt_labels)
    aps = []
    for c in range(num_classes):
        d_idx = np_.where(det_labels == c)[0]
        g_idx = np_.where(gt_labels == c)[0]
        if len(g_idx) == 0:
            continue
        order = d_idx[np_.argsort(-det_scores[d_idx])]
        matched = set()
        tp = np_.zeros(len(order))
        fp = np_.zeros(len(order))
        for i, di in enumerate(order):
            if len(g_idx):
                ious = np_.asarray(iou_similarity(
                    det_boxes[di:di + 1], gt_boxes[g_idx]))[0]
                j = int(np_.argmax(ious))
                if ious[j] >= overlap_threshold and j not in matched:
                    tp[i] = 1
                    matched.add(j)
                else:
                    fp[i] = 1
            else:
                fp[i] = 1
        ctp = np_.cumsum(tp)
        cfp = np_.cumsum(fp)
        rec = ctp / len(g_idx)
        prec = ctp / np_.maximum(ctp + cfp, 1e-9)
        ap = 0.0
        for t in np_.linspace(0, 1, 11):
            p = prec[rec >= t].max() if np_.any(rec >= t) else 0.0
            ap += p / 11
        aps.append(ap)
    return float(np_.mean(aps)) if aps else 0.0


class DetectionMAP(MetricBase):
    """reference: python/paddle/fluid/metrics.py DetectionMAP accumulator."""

    def __init__(self, num_classes: int, overlap_threshold: float = 0.5,
                 name=None):
        super().__init__(name)
        self.num_classes = num_classes
        self.overlap_threshold = overlap_threshold
        self.reset()

    def reset(self):
        self._maps = []

    def update(self, det_boxes, det_scores, det_labels, gt_boxes, gt_labels):
        self._maps.append(detection_map(
            det_boxes, det_scores, det_labels, gt_boxes, gt_labels,
            num_classes=self.num_classes,
            overlap_threshold=self.overlap_threshold))

    def eval(self):
        import numpy as np_

        return float(np_.mean(self._maps)) if self._maps else 0.0
