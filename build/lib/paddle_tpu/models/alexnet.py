"""AlexNet — reference: benchmark/figs legacy comparison family (AlexNet/
GoogleNet/ResNet/VGG charts); rebuilt from framework layers (NCHW)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..ops import loss as L


class AlexNet(nn.Layer):
    def __init__(self, num_classes: int = 1000, in_ch: int = 3,
                 dropout: float = 0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(in_ch, 64, 11, stride=4, padding=2, act="relu"),
            nn.Pool2D(3, "max", stride=2),
            nn.Conv2D(64, 192, 5, padding=2, act="relu"),
            nn.Pool2D(3, "max", stride=2),
            nn.Conv2D(192, 384, 3, padding=1, act="relu"),
            nn.Conv2D(384, 256, 3, padding=1, act="relu"),
            nn.Conv2D(256, 256, 3, padding=1, act="relu"),
            nn.Pool2D(3, "max", stride=2),
        )
        self.classifier = nn.Sequential(
            nn.Flatten(),
            nn.Dropout(dropout),
            nn.Linear(256 * 6 * 6, 4096, act="relu"),
            nn.Dropout(dropout),
            nn.Linear(4096, 4096, act="relu"),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        return self.classifier(self.features(x))


def alexnet(num_classes: int = 1000, **kw) -> AlexNet:
    return AlexNet(num_classes, **kw)


def loss_fn(logits, labels):
    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))
