"""DeepFM — sparse CTR model (BASELINE config 5).

Capability target: the reference's CTR training stack — MultiSlot sparse
ids through PS-sharded lookup tables (reference: framework/data_feed.h:55,
operators/lookup_table_op.cc sparse-grad path, distributed/downpour.py:24).
Here the sparse tables are mesh-sharded dense arrays
(parallel.sharded_embedding) and the whole model is one jitted SPMD
computation: FM first/second-order terms + DNN tower, bf16-friendly.

Input convention (Criteo-style): ``sparse_ids`` (B, F) — one id per
categorical field, pre-offset into a single concatenated vocab of size
sum(field vocab sizes); ``dense`` (B, Dn) — continuous features.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import jax.numpy as jnp

from .. import nn
from ..core.enforce import enforce
from ..ops import loss as L


@dataclass
class DeepFMConfig:
    total_vocab: int = 1000          # sum of per-field vocab sizes
    num_fields: int = 26
    dense_dim: int = 13
    embed_dim: int = 16
    mlp_dims: Sequence[int] = (400, 400, 400)
    dropout: float = 0.0
    # 'ep' shards the tables over the mesh; None keeps them replicated
    embedding_axis: Optional[str] = "ep"
    # row-sparse gradient updates for the tables (SelectedRows capability;
    # reference: lookup_table is_sparse) — train via
    # optimizer.sparse_minimize_fn so each step touches O(B*fields) rows
    sparse_grads: bool = False

    @classmethod
    def criteo(cls, total_vocab: int = 1_000_000):
        return cls(total_vocab=total_vocab)

    @classmethod
    def tiny(cls):
        return cls(total_vocab=512, num_fields=8, dense_dim=4, embed_dim=8,
                   mlp_dims=(32, 16))


class DeepFM(nn.Layer):
    def __init__(self, cfg: Optional[DeepFMConfig] = None):
        super().__init__()
        from ..parallel.sharded_embedding import ShardedEmbedding

        self.cfg = cfg = cfg or DeepFMConfig()
        if cfg.embedding_axis:
            self.embedding = ShardedEmbedding(cfg.total_vocab, cfg.embed_dim,
                                              axis=cfg.embedding_axis,
                                              is_sparse=cfg.sparse_grads)
            self.linear_embed = ShardedEmbedding(cfg.total_vocab, 1,
                                                 axis=cfg.embedding_axis,
                                                 is_sparse=cfg.sparse_grads)
        else:
            self.embedding = nn.Embedding(cfg.total_vocab, cfg.embed_dim,
                                          is_sparse=cfg.sparse_grads)
            self.linear_embed = nn.Embedding(cfg.total_vocab, 1,
                                             is_sparse=cfg.sparse_grads)
        self.bias = self.create_parameter("bias", (1,), is_bias=True)
        mlp = []
        d_in = cfg.num_fields * cfg.embed_dim + cfg.dense_dim
        for d_out in cfg.mlp_dims:
            mlp.append(nn.Linear(d_in, d_out, act="relu"))
            if cfg.dropout:
                mlp.append(nn.Dropout(cfg.dropout))
            d_in = d_out
        mlp.append(nn.Linear(d_in, 1))
        self.mlp = nn.Sequential(*mlp)
        self.dense_linear = nn.Linear(cfg.dense_dim, 1)

    def forward(self, sparse_ids, dense=None):
        cfg = self.cfg
        b, f = sparse_ids.shape
        enforce(f == cfg.num_fields, "expected %s fields, got %s",
                cfg.num_fields, f)
        emb = self.embedding(sparse_ids)               # (B, F, K)
        # FM first order: per-id scalar weights (+ dense linear)
        first = jnp.sum(self.linear_embed(sparse_ids)[..., 0], axis=1)
        if dense is not None:
            first = first + self.dense_linear(dense)[:, 0]
        # FM second order: 0.5 * ((Σe)² − Σe²) summed over K
        s = jnp.sum(emb, axis=1)
        second = 0.5 * jnp.sum(s * s - jnp.sum(emb * emb, axis=1), axis=-1)
        # DNN tower over concatenated embeddings (+ dense)
        flat = emb.reshape(b, f * cfg.embed_dim)
        if dense is not None:
            flat = jnp.concatenate([flat, dense], axis=-1)
        deep = self.mlp(flat)[:, 0]
        return first + second + deep + self.bias[0]    # logits (B,)


def loss_fn(logits, labels):
    """Pointwise CTR loss: sigmoid BCE (reference:
    operators/sigmoid_cross_entropy_with_logits_op.cc)."""
    return jnp.mean(L.sigmoid_cross_entropy_with_logits(
        logits, labels.astype(logits.dtype)))
