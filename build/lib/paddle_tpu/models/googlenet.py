"""GoogLeNet (Inception v1) — reference: benchmark/figs legacy comparison
family; rebuilt from framework layers (NCHW, plain conv+relu as in the
v1 paper — no LRN, which XLA has no fast path for; aux heads included
for training parity)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..ops import loss as L


class Inception(nn.Layer):
    """One inception block: 1x1 | 1x1→3x3 | 1x1→5x5 | pool→1x1 branches."""

    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = nn.Conv2D(in_ch, c1, 1, act="relu")
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1, act="relu"),
                                nn.Conv2D(c3r, c3, 3, padding=1, act="relu"))
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1, act="relu"),
                                nn.Conv2D(c5r, c5, 5, padding=2, act="relu"))
        self.b4_pool = nn.Pool2D(3, "max", stride=1, padding=1)
        self.b4 = nn.Conv2D(in_ch, pp, 1, act="relu")

    def forward(self, x):
        return jnp.concatenate([self.b1(x), self.b2(x), self.b3(x),
                                self.b4(self.b4_pool(x))], axis=1)


class AuxHead(nn.Layer):
    def __init__(self, in_ch, num_classes):
        super().__init__()
        # v1 recipe: 5x5/3 avg pool (14x14 -> 4x4), 1x1 conv, 2 fc
        self.pool = nn.Pool2D(5, "avg", stride=3)
        self.conv = nn.Conv2D(in_ch, 128, 1, act="relu")
        self.fc1 = nn.Linear(128 * 4 * 4, 1024, act="relu")
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = x.reshape(x.shape[0], -1)
        return self.fc2(self.drop(self.fc1(x)))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes: int = 1000, in_ch: int = 3,
                 aux_heads: bool = True):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(in_ch, 64, 7, stride=2, padding=3, act="relu"),
            nn.Pool2D(3, "max", stride=2, padding=1),
            nn.Conv2D(64, 64, 1, act="relu"),
            nn.Conv2D(64, 192, 3, padding=1, act="relu"),
            nn.Pool2D(3, "max", stride=2, padding=1),
        )
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)    # 256
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)  # 480
        self.pool3 = nn.Pool2D(3, "max", stride=2, padding=1)
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)   # 512
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)  # 512
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)  # 512
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)  # 528
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)  # 832
        self.pool4 = nn.Pool2D(3, "max", stride=2, padding=1)
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)  # 832
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)  # 1024
        self.drop = nn.Dropout(0.4)
        self.head = nn.Linear(1024, num_classes)
        self.aux_heads = aux_heads
        if aux_heads:
            self.aux1 = AuxHead(512, num_classes)
            self.aux2 = AuxHead(528, num_classes)

    def forward(self, x):
        from ..ops.nn import adaptive_pool2d

        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        a1 = self.aux1(x) if (self.aux_heads and self.training) else None
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x) if (self.aux_heads and self.training) else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        x = adaptive_pool2d(x, 1, "avg").reshape(x.shape[0], -1)
        logits = self.head(self.drop(x))
        if a1 is not None:
            return logits, a1, a2
        return logits


def googlenet(num_classes: int = 1000, **kw) -> GoogLeNet:
    return GoogLeNet(num_classes, **kw)


def loss_fn(outputs, labels, aux_weight: float = 0.3):
    """Main CE + 0.3-weighted aux losses (the v1 training recipe)."""
    if isinstance(outputs, tuple):
        main, a1, a2 = outputs
        loss = jnp.mean(L.softmax_with_cross_entropy(main, labels))
        for aux in (a1, a2):
            loss = loss + aux_weight * jnp.mean(
                L.softmax_with_cross_entropy(aux, labels))
        return loss
    return jnp.mean(L.softmax_with_cross_entropy(outputs, labels))
