"""MNIST models — BASELINE config 1 (reference:
benchmark/fluid/models/mnist.py cnn_model, tests/book/test_recognize_digits.py
mlp + conv variants).
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..ops import loss as L
from ..metrics import accuracy


class MnistMLP(nn.Layer):
    """reference: tests/book/test_recognize_digits.py mlp — 784-128-64-10."""

    def __init__(self, hidden1: int = 128, hidden2: int = 64):
        super().__init__()
        self.fc1 = nn.Linear(784, hidden1, act="relu")
        self.fc2 = nn.Linear(hidden1, hidden2, act="relu")
        self.fc3 = nn.Linear(hidden2, 10)

    def forward(self, x):
        return self.fc3(self.fc2(self.fc1(x)))


class MnistCNN(nn.Layer):
    """reference: benchmark/fluid/models/mnist.py cnn_model — conv-pool x2 + fc."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2D(1, 20, 5, act="relu")
        self.pool1 = nn.Pool2D(2, "max", stride=2)
        self.conv2 = nn.Conv2D(20, 50, 5, act="relu")
        self.pool2 = nn.Pool2D(2, "max", stride=2)
        self.fc = nn.Linear(50 * 4 * 4, 10)

    def forward(self, x):
        if x.ndim == 2:
            x = x.reshape(-1, 1, 28, 28)
        h = self.pool1(self.conv1(x))
        h = self.pool2(self.conv2(h))
        return self.fc(h.reshape(h.shape[0], -1))


def loss_fn(logits, label):
    return jnp.mean(L.softmax_with_cross_entropy(logits, label))


def eval_metrics(logits, label):
    return {"acc": accuracy(logits, label)}
