"""Recommender system — book model (reference:
tests/book/test_recommender_system.py — movielens: user/movie feature
embeddings → fusion MLPs → cosine similarity rating regression)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..ops.math import cos_sim


class RecommenderNet(nn.Layer):
    def __init__(self, num_users: int = 6041, num_items: int = 3953,
                 num_genders: int = 2, num_ages: int = 7,
                 num_jobs: int = 21, num_categories: int = 19,
                 embed_dim: int = 32, fc_dim: int = 200):
        super().__init__()
        self.user_emb = nn.Embedding(num_users, embed_dim)
        self.gender_emb = nn.Embedding(num_genders, 16)
        self.age_emb = nn.Embedding(num_ages, 16)
        self.job_emb = nn.Embedding(num_jobs, 16)
        self.user_fc = nn.Linear(embed_dim + 48, fc_dim, act="tanh")
        self.item_emb = nn.Embedding(num_items, embed_dim)
        self.cat_emb = nn.Embedding(num_categories, embed_dim)
        self.item_fc = nn.Linear(2 * embed_dim, fc_dim, act="tanh")

    def forward(self, user, gender, age, job, item, categories):
        """categories: (B, K) multi-hot id list (padded with 0) — summed
        like the reference's sequence_pool over category embeddings."""
        u = jnp.concatenate([
            self.user_emb(user), self.gender_emb(gender),
            self.age_emb(age), self.job_emb(job)], axis=-1)
        u = self.user_fc(u)
        cat = jnp.sum(self.cat_emb(categories), axis=1)
        i = jnp.concatenate([self.item_emb(item), cat], axis=-1)
        i = self.item_fc(i)
        # reference scales cos similarity to the 5-star range
        return 5.0 * cos_sim(u, i)


def loss_fn(pred, rating):
    return jnp.mean((pred.reshape(-1) - rating) ** 2)
