"""ResNet family — BASELINE config 2 (reference:
benchmark/fluid/models/resnet.py model zoo entry; built here from the
framework's own layers, NCHW, bf16-policy aware).

Variants: resnet50/101/152 (ImageNet, bottleneck) and resnet20/32 (CIFAR,
basic block) — the reference benchmarks resnet on both cifar10 and
flowers/imagenet (reference: benchmark/fluid/README.md:15-23).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from .. import nn
from ..ops import loss as L


def _conv_bn(in_ch: int, out_ch: int, k: int, stride: int = 1,
             groups: int = 1, act: Optional[str] = "relu",
             data_format: str = "NCHW") -> nn.Layer:
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=(k - 1) // 2,
                  groups=groups, bias_attr=False, data_format=data_format),
        nn.BatchNorm(out_ch, act=act, data_layout=data_format),
    )


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, in_ch: int, ch: int, stride: int = 1,
                 groups: int = 1, base_width: int = 64,
                 data_format: str = "NCHW"):
        super().__init__()
        width = int(ch * (base_width / 64.0)) * groups
        out_ch = ch * self.expansion
        df = data_format
        self.conv1 = _conv_bn(in_ch, width, 1, data_format=df)
        self.conv2 = _conv_bn(width, width, 3, stride=stride, groups=groups,
                              data_format=df)
        self.conv3 = _conv_bn(width, out_ch, 1, act=None, data_format=df)
        self.short = (None if in_ch == out_ch and stride == 1
                      else _conv_bn(in_ch, out_ch, 1, stride=stride,
                                    act=None, data_format=df))

    def forward(self, x):
        y = self.conv3(self.conv2(self.conv1(x)))
        s = x if self.short is None else self.short(x)
        return jnp.maximum(y + s, 0.0)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, in_ch: int, ch: int, stride: int = 1,
                 data_format: str = "NCHW", **_):
        super().__init__()
        df = data_format
        self.conv1 = _conv_bn(in_ch, ch, 3, stride=stride, data_format=df)
        self.conv2 = _conv_bn(ch, ch, 3, act=None, data_format=df)
        self.short = (None if in_ch == ch and stride == 1
                      else _conv_bn(in_ch, ch, 1, stride=stride, act=None,
                                    data_format=df))

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        s = x if self.short is None else self.short(x)
        return jnp.maximum(y + s, 0.0)


class ResNet(nn.Layer):
    def __init__(self, block, depths: Sequence[int], num_classes: int = 1000,
                 in_ch: int = 3, cifar: bool = False, groups: int = 1,
                 base_width: int = 64, data_format: str = "NCHW"):
        super().__init__()
        self.cifar = cifar
        # NHWC is the TPU-preferred layout (channels-last tiles directly
        # onto the MXU without the transposes NCHW convs insert); inputs
        # stay NCHW at the API and transpose once at the stem
        self.data_format = data_format
        df = data_format
        ch = 16 if cifar else 64
        if cifar:
            self.stem = _conv_bn(in_ch, ch, 3, data_format=df)
            widths = [16, 32, 64]
        else:
            self.stem = _conv_bn(in_ch, ch, 7, stride=2, data_format=df)
            self.maxpool = nn.Pool2D(3, "max", stride=2, padding=1,
                                     data_format=df)
            widths = [64, 128, 256, 512]
        blocks = []
        cur = ch
        for stage, (w, n) in enumerate(zip(widths, depths)):
            for i in range(n):
                stride = 2 if (i == 0 and stage > 0) else 1
                blocks.append(block(cur, w, stride=stride, groups=groups,
                                    base_width=base_width, data_format=df))
                cur = w * block.expansion
        self.blocks = nn.LayerList(blocks)
        self.head = nn.Linear(cur, num_classes)

    def forward(self, x):
        if self.data_format == "NHWC":
            x = jnp.transpose(x, (0, 2, 3, 1))  # accept NCHW inputs
        x = self.stem(x)
        if not self.cifar:
            x = self.maxpool(x)
        for blk in self.blocks:
            x = blk(x)
        pool_axes = (2, 3) if self.data_format == "NCHW" else (1, 2)
        x = jnp.mean(x, axis=pool_axes)  # global average pool
        return self.head(x)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes, **kw)


def resnet20_cifar(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(BasicBlock, [3, 3, 3], num_classes, cifar=True, **kw)


def resnet32_cifar(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(BasicBlock, [5, 5, 5], num_classes, cifar=True, **kw)


def loss_fn(logits, labels):
    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))
