"""VGG — reference: benchmark/fluid/models/vgg.py zoo entry; rebuilt from
framework layers (NCHW, batch-norm variant as the reference uses)."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from .. import nn
from ..ops import loss as L

_CFGS = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}
_WIDTHS = (64, 128, 256, 512, 512)


class VGG(nn.Layer):
    def __init__(self, depth: int = 16, num_classes: int = 1000,
                 in_ch: int = 3, image_size: int = 224,
                 dropout: float = 0.5):
        super().__init__()
        reps = _CFGS[depth]
        feats = []
        cur = in_ch
        for width, n in zip(_WIDTHS, reps):
            for _ in range(n):
                feats.append(nn.Conv2D(cur, width, 3, padding=1,
                                       bias_attr=False))
                feats.append(nn.BatchNorm(width, act="relu"))
                cur = width
            feats.append(nn.Pool2D(2, "max", stride=2))
        self.features = nn.Sequential(*feats)
        spatial = image_size // 32
        self.classifier = nn.Sequential(
            nn.Flatten(),
            nn.Linear(cur * spatial * spatial, 4096, act="relu"),
            nn.Dropout(dropout),
            nn.Linear(4096, 4096, act="relu"),
            nn.Dropout(dropout),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        return self.classifier(self.features(x))


def vgg16(num_classes: int = 1000, **kw) -> VGG:
    return VGG(16, num_classes, **kw)


def loss_fn(logits, labels):
    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))
