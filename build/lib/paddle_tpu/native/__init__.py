"""Native (C++) runtime components, bound via ctypes (no pybind in this
environment). Currently: the multithreaded MultiSlot data feed
(src/datafeed.cc) — the reference's C++ ingestion role
(reference: framework/data_feed.h:55, operators/reader/buffered_reader.cc).

The shared library builds on demand with `make` (g++ is part of the
supported toolchain); import fails soft — ``available()`` reports status
and the pure-Python pipeline (paddle_tpu.data) is always there.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libptdatafeed.so")
_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _load():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C", _DIR], check=True,
                               capture_output=True, text=True, timeout=300)
            except Exception as e:  # toolchain missing → soft-fail
                _build_error = getattr(e, "stderr", str(e)) or str(e)
                return None
        lib = ctypes.CDLL(_SO)
        lib.ptdf_create.restype = ctypes.c_void_p
        lib.ptdf_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.ptdf_destroy.argtypes = [ctypes.c_void_p]
        lib.ptdf_next.restype = ctypes.c_void_p
        lib.ptdf_next.argtypes = [ctypes.c_void_p]
        lib.ptdf_batch_free.argtypes = [ctypes.c_void_p]
        lib.ptdf_batch_size.restype = ctypes.c_int64
        lib.ptdf_batch_size.argtypes = [ctypes.c_void_p]
        lib.ptdf_batch_maxlen.restype = ctypes.c_int64
        lib.ptdf_batch_maxlen.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_batch_ivalues.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptdf_batch_ivalues.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_batch_fvalues.restype = ctypes.POINTER(ctypes.c_float)
        lib.ptdf_batch_fvalues.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_batch_lengths.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptdf_batch_lengths.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptdf_error.restype = ctypes.c_int
        lib.ptdf_error.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is (or can be) built and loaded."""
    return _load() is not None


def build_error() -> Optional[str]:
    return _build_error


class MultiSlotFeed:
    """Iterate dense padded batches parsed by C++ worker threads.

    ``slots``: [(name, 'u'|'f'), ...] in file order. Yields
    {name: (values (B, maxlen), lengths (B,))} with int64/float32 values.
    The training thread never touches file IO or parsing — batches queue
    up to ``queue_capacity`` deep while the accelerator computes.
    """

    def __init__(self, files: Sequence[str],
                 slots: Sequence[Tuple[str, str]], batch_size: int,
                 num_threads: int = 2, queue_capacity: int = 8,
                 drop_last: bool = True):
        from ..core.enforce import enforce

        lib = _load()
        enforce(lib is not None,
                "native datafeed unavailable: %s", _build_error)
        for f in files:
            enforce(os.path.exists(f), "no such data file: %s", f)
        self._lib = lib
        self.slots = list(slots)
        spec = ",".join(f"{n}:{d}" for n, d in self.slots).encode()
        arr = (ctypes.c_char_p * len(files))(
            *[f.encode() for f in files])
        self._h = lib.ptdf_create(arr, len(files), spec, batch_size,
                                  num_threads, queue_capacity,
                                  1 if drop_last else 0)
        enforce(self._h is not None, "ptdf_create failed (bad slot spec?)")

    def __iter__(self) -> Iterator[Dict[str, Tuple[np.ndarray, np.ndarray]]]:
        lib = self._lib
        while True:
            b = lib.ptdf_next(self._h)
            if not b:
                break
            try:
                bs = lib.ptdf_batch_size(b)
                out = {}
                for i, (name, d) in enumerate(self.slots):
                    ml = lib.ptdf_batch_maxlen(b, i)
                    n = int(bs * ml)
                    if d == "u":
                        ptr = lib.ptdf_batch_ivalues(b, i)
                        vals = np.ctypeslib.as_array(ptr, (n,)).copy()
                    else:
                        ptr = lib.ptdf_batch_fvalues(b, i)
                        vals = np.ctypeslib.as_array(ptr, (n,)).copy()
                    lens = np.ctypeslib.as_array(
                        lib.ptdf_batch_lengths(b, i), (int(bs),)).copy()
                    out[name] = (vals.reshape(int(bs), int(ml)), lens)
                yield out
            finally:
                lib.ptdf_batch_free(b)
        err = ctypes.create_string_buffer(512)
        if lib.ptdf_error(self._h, err, 512):
            raise RuntimeError(f"native datafeed: {err.value.decode()}")

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptdf_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# C++ PJRT serving predictor (src/predictor.cc) — the Python-free serving
# path (reference: inference/api/analysis_predictor.h:46,
# train/demo/demo_trainer.cc). This wrapper drives the same C ABI that the
# standalone `ptserve` binary uses, so the artifact/npz/manifest parsing is
# testable from Python without a PJRT device.

_PRED_SO = os.path.join(_DIR, "libptpredictor.so")
_pred_lib = None


def _load_predictor_lib():
    global _pred_lib
    with _lib_lock:
        if _pred_lib is not None:
            return _pred_lib
        if not os.path.exists(_PRED_SO):
            try:
                subprocess.run(["make", "-C", _DIR, "libptpredictor.so"],
                               check=True, capture_output=True, text=True,
                               timeout=300)
            except Exception as e:
                raise RuntimeError(
                    f"cannot build libptpredictor.so: "
                    f"{getattr(e, 'stderr', e)}")
        lib = ctypes.CDLL(_PRED_SO)
        lib.ptpred_load.restype = ctypes.c_void_p
        lib.ptpred_load.argtypes = [ctypes.c_char_p]
        lib.ptpred_ok.argtypes = [ctypes.c_void_p]
        lib.ptpred_error.restype = ctypes.c_char_p
        lib.ptpred_error.argtypes = [ctypes.c_void_p]
        lib.ptpred_compile.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptpred_num_feeds.argtypes = [ctypes.c_void_p]
        lib.ptpred_feed_name.restype = ctypes.c_char_p
        lib.ptpred_feed_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpred_num_fetches.argtypes = [ctypes.c_void_p]
        lib.ptpred_fetch_name.restype = ctypes.c_char_p
        lib.ptpred_fetch_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpred_num_params.argtypes = [ctypes.c_void_p]
        lib.ptpred_param_dtype.restype = ctypes.c_char_p
        lib.ptpred_param_dtype.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptpred_param_rank.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptpred_param_dim.restype = ctypes.c_int64
        lib.ptpred_param_dim.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int]
        lib.ptpred_param_data.restype = ctypes.c_void_p
        lib.ptpred_param_data.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.POINTER(ctypes.c_int64)]
        lib.ptpred_run.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_void_p),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int)]
        lib.ptpred_out_rank.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpred_out_dim.restype = ctypes.c_int64
        lib.ptpred_out_dim.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int]
        lib.ptpred_out_dtype.restype = ctypes.c_char_p
        lib.ptpred_out_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpred_out_data.restype = ctypes.c_void_p
        lib.ptpred_out_data.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.ptpred_destroy.argtypes = [ctypes.c_void_p]
        _pred_lib = lib
        return lib


def default_pjrt_plugin() -> Optional[str]:
    """Plugin search: $PT_PJRT_PLUGIN, else libtpu from the environment."""
    p = os.environ.get("PT_PJRT_PLUGIN")
    if p:
        return p
    try:
        import libtpu

        return os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so")
    except ImportError:
        return None


class NativePredictor:
    """C++ serving predictor handle (artifact parse is hermetic; ``compile``
    needs a PJRT plugin + device)."""

    def __init__(self, model_dir: str):
        self._lib = _load_predictor_lib()
        self._h = self._lib.ptpred_load(model_dir.encode())
        if not self._lib.ptpred_ok(self._h):
            err = self._lib.ptpred_error(self._h).decode()
            self._lib.ptpred_destroy(self._h)
            self._h = None
            raise RuntimeError(f"native predictor load: {err}")

    @property
    def feed_names(self) -> List[str]:
        return [self._lib.ptpred_feed_name(self._h, i).decode()
                for i in range(self._lib.ptpred_num_feeds(self._h))]

    @property
    def fetch_names(self) -> List[str]:
        return [self._lib.ptpred_fetch_name(self._h, i).decode()
                for i in range(self._lib.ptpred_num_fetches(self._h))]

    def num_params(self) -> int:
        return self._lib.ptpred_num_params(self._h)

    def param(self, name: str) -> np.ndarray:
        """Parsed param tensor (exercises the C++ npz reader)."""
        rank = self._lib.ptpred_param_rank(self._h, name.encode())
        if rank < 0:
            raise KeyError(name)
        shape = [self._lib.ptpred_param_dim(self._h, name.encode(), i)
                 for i in range(rank)]
        dt = self._lib.ptpred_param_dtype(self._h, name.encode()).decode()
        n = ctypes.c_int64()
        ptr = self._lib.ptpred_param_data(self._h, name.encode(),
                                          ctypes.byref(n))
        buf = ctypes.string_at(ptr, n.value)
        return np.frombuffer(buf, dtype=np.dtype(dt)).reshape(shape).copy()

    def compile(self, plugin_path: Optional[str] = None) -> None:
        plugin = plugin_path or default_pjrt_plugin()
        if plugin is None:
            raise RuntimeError("no PJRT plugin found; set PT_PJRT_PLUGIN")
        if not self._lib.ptpred_compile(self._h, plugin.encode()):
            raise RuntimeError(
                f"compile: {self._lib.ptpred_error(self._h).decode()}")

    def run(self, feeds: Dict[str, np.ndarray]) -> List[np.ndarray]:
        names = self.feed_names
        arrs = [np.ascontiguousarray(feeds[n]) for n in names]
        ptrs = (ctypes.c_void_p * len(arrs))(
            *[a.ctypes.data_as(ctypes.c_void_p) for a in arrs])
        dims_flat = []
        ranks = []
        for a in arrs:
            dims_flat.extend(a.shape)
            ranks.append(a.ndim)
        dims = (ctypes.c_int64 * len(dims_flat))(*dims_flat)
        ranks_c = (ctypes.c_int * len(ranks))(*ranks)
        if not self._lib.ptpred_run(self._h, ptrs, dims, ranks_c):
            raise RuntimeError(
                f"run: {self._lib.ptpred_error(self._h).decode()}")
        outs = []
        for i in range(self._lib.ptpred_num_fetches(self._h)):
            rank = self._lib.ptpred_out_rank(self._h, i)
            shape = [self._lib.ptpred_out_dim(self._h, i, d)
                     for d in range(rank)]
            dt = self._lib.ptpred_out_dtype(self._h, i).decode()
            n = ctypes.c_int64()
            ptr = self._lib.ptpred_out_data(self._h, i, ctypes.byref(n))
            buf = ctypes.string_at(ptr, n.value)
            outs.append(np.frombuffer(buf, dtype=np.dtype(dt))
                        .reshape(shape).copy())
        return outs

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptpred_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
