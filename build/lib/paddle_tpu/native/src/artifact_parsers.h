// Dependency-free parsers for the serving artifact: tiny JSON (manifest),
// npy/npz (params), numpy-dtype table. Shared by predictor.cc and
// predictor_test.cc (reference test convention: units next to sources).
#pragma once

#include <cstdint>
#include <cstring>
#include <fstream>
#include <map>
#include <string>
#include <vector>
#include <zlib.h>

namespace ptnative {

// ---------------------------------------------------------------- errors --
struct Status {
  bool ok = true;
  std::string message;
  static Status Ok() { return {}; }
  static Status Err(std::string m) { return {false, std::move(m)}; }
};

// ------------------------------------------------------------ tiny JSON ---
// Parser for the machine-written manifest (objects, arrays, strings,
// numbers, bools). Not a general JSON library on purpose.
struct Json {
  enum Kind { kNull, kBool, kNum, kStr, kArr, kObj } kind = kNull;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  const Json* find(const std::string& k) const {
    auto it = obj.find(k);
    return it == obj.end() ? nullptr : &it->second;
  }
};

struct JsonParser {
  const char* p;
  const char* end;
  bool fail = false;

  void ws() { while (p < end && strchr(" \t\r\n", *p)) p++; }
  bool lit(const char* s) {
    size_t n = strlen(s);
    if (end - p >= (long)n && !strncmp(p, s, n)) { p += n; return true; }
    return false;
  }
  Json parse() {
    ws();
    Json j;
    if (p >= end) { fail = true; return j; }
    if (*p == '{') {
      j.kind = Json::kObj; p++;
      ws();
      if (p < end && *p == '}') { p++; return j; }
      while (p < end) {
        ws();
        Json key = parse_string();
        ws();
        if (p >= end || *p != ':') { fail = true; return j; }
        p++;
        j.obj[key.str] = parse();
        ws();
        if (p < end && *p == ',') { p++; continue; }
        if (p < end && *p == '}') { p++; break; }
        fail = true; return j;
      }
    } else if (*p == '[') {
      j.kind = Json::kArr; p++;
      ws();
      if (p < end && *p == ']') { p++; return j; }
      while (p < end) {
        j.arr.push_back(parse());
        ws();
        if (p < end && *p == ',') { p++; continue; }
        if (p < end && *p == ']') { p++; break; }
        fail = true; return j;
      }
    } else if (*p == '"') {
      j = parse_string();
    } else if (lit("true")) {
      j.kind = Json::kBool; j.b = true;
    } else if (lit("false")) {
      j.kind = Json::kBool; j.b = false;
    } else if (lit("null")) {
      j.kind = Json::kNull;
    } else {
      j.kind = Json::kNum;
      char* q = nullptr;
      j.num = strtod(p, &q);
      if (q == p) fail = true;
      p = q;
    }
    return j;
  }
  Json parse_string() {
    Json j; j.kind = Json::kStr;
    if (p >= end || *p != '"') { fail = true; return j; }
    p++;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        p++;
        switch (*p) {
          case 'n': j.str += '\n'; break;
          case 't': j.str += '\t'; break;
          default: j.str += *p;
        }
      } else {
        j.str += *p;
      }
      p++;
    }
    if (p < end) p++;  // closing quote
    return j;
  }
};

// ------------------------------------------------------------- npz/zip ----
struct NpyArray {
  std::string dtype;          // numpy descr, e.g. "<f4"
  std::vector<int64_t> shape;
  std::vector<uint8_t> data;  // raw little-endian payload
};

inline Status InflateRaw(const uint8_t* src, size_t n,
                         std::vector<uint8_t>* out) {
  z_stream zs{};
  if (inflateInit2(&zs, -MAX_WBITS) != Z_OK)
    return Status::Err("zlib init failed");
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = n;
  std::vector<uint8_t> buf(1 << 16);
  int ret = Z_OK;
  while (ret != Z_STREAM_END) {
    zs.next_out = buf.data();
    zs.avail_out = buf.size();
    ret = inflate(&zs, Z_NO_FLUSH);
    if (ret != Z_OK && ret != Z_STREAM_END) {
      inflateEnd(&zs);
      return Status::Err("zlib inflate failed");
    }
    out->insert(out->end(), buf.data(),
                buf.data() + (buf.size() - zs.avail_out));
  }
  inflateEnd(&zs);
  return Status::Ok();
}

inline Status ParseNpy(const std::vector<uint8_t>& raw, NpyArray* out) {
  if (raw.size() < 10 || memcmp(raw.data(), "\x93NUMPY", 6))
    return Status::Err("bad .npy magic");
  int major = raw[6];
  size_t hlen, hoff;
  if (major == 1) {
    hlen = raw[8] | (raw[9] << 8);
    hoff = 10;
  } else {
    hlen = raw[8] | (raw[9] << 8) | (raw[10] << 16) | ((size_t)raw[11] << 24);
    hoff = 12;
  }
  std::string hdr((const char*)raw.data() + hoff, hlen);
  // header is a python dict literal: {'descr': '<f4', 'fortran_order':
  // False, 'shape': (3, 4), }
  auto grab = [&](const char* key) -> std::string {
    auto k = hdr.find(key);
    if (k == std::string::npos) return "";
    auto c = hdr.find(':', k);
    auto e = hdr.find_first_of(",}", c);
    // tuples contain commas — extend to the closing paren
    auto open = hdr.find('(', c);
    if (open != std::string::npos && open < e) e = hdr.find(')', open) + 1;
    return hdr.substr(c + 1, e - c - 1);
  };
  std::string descr = grab("'descr'");
  auto q0 = descr.find('\'');
  auto q1 = descr.rfind('\'');
  if (q0 == std::string::npos || q1 <= q0)
    return Status::Err("bad descr in npy header");
  out->dtype = descr.substr(q0 + 1, q1 - q0 - 1);
  if (grab("'fortran_order'").find("True") != std::string::npos)
    return Status::Err("fortran_order arrays unsupported");
  std::string shp = grab("'shape'");
  out->shape.clear();
  const char* s = shp.c_str();
  while (*s) {
    while (*s && !isdigit(*s)) s++;
    if (!*s) break;
    out->shape.push_back(strtoll(s, const_cast<char**>(&s), 10));
  }
  out->data.assign(raw.begin() + hoff + hlen, raw.end());
  return Status::Ok();
}

// Minimal ZIP central-directory reader (stored + deflate entries).
inline Status ReadNpz(const std::string& path,
                      std::map<std::string, NpyArray>* out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return Status::Err("cannot open " + path);
  std::vector<uint8_t> buf((std::istreambuf_iterator<char>(f)),
                           std::istreambuf_iterator<char>());
  if (buf.size() < 22) return Status::Err("npz too small");
  // find end-of-central-directory record (no zip64 support; params files
  // beyond 4GB should use sharded checkpoints instead)
  size_t eocd = std::string::npos;
  for (size_t i = buf.size() - 22; i + 4 >= 4; i--) {
    if (buf[i] == 0x50 && buf[i + 1] == 0x4b && buf[i + 2] == 0x05 &&
        buf[i + 3] == 0x06) { eocd = i; break; }
    if (i == 0) break;
  }
  if (eocd == std::string::npos) return Status::Err("no zip EOCD");
  auto rd16 = [&](size_t o) { return (uint32_t)buf[o] | (buf[o + 1] << 8); };
  auto rd32 = [&](size_t o) {
    return (uint32_t)buf[o] | (buf[o + 1] << 8) | (buf[o + 2] << 16) |
           ((uint32_t)buf[o + 3] << 24);
  };
  uint32_t n_entries = rd16(eocd + 10);
  size_t cd = rd32(eocd + 16);
  for (uint32_t e = 0; e < n_entries; e++) {
    if (rd32(cd) != 0x02014b50) return Status::Err("bad central dir entry");
    uint16_t method = rd16(cd + 10);
    uint32_t csize = rd32(cd + 20);
    uint16_t nlen = rd16(cd + 28), xlen = rd16(cd + 30), clen = rd16(cd + 32);
    uint32_t lho = rd32(cd + 42);
    std::string name((const char*)&buf[cd + 46], nlen);
    // local header: skip its (possibly different) name/extra lengths
    uint16_t lnlen = rd16(lho + 26), lxlen = rd16(lho + 28);
    size_t data_off = lho + 30 + lnlen + lxlen;
    std::vector<uint8_t> raw;
    if (method == 0) {
      raw.assign(buf.begin() + data_off, buf.begin() + data_off + csize);
    } else if (method == 8) {
      Status st = InflateRaw(&buf[data_off], csize, &raw);
      if (!st.ok) return st;
    } else {
      return Status::Err("unsupported zip method for " + name);
    }
    if (name.size() > 4 && name.substr(name.size() - 4) == ".npy")
      name = name.substr(0, name.size() - 4);
    NpyArray arr;
    Status st = ParseNpy(raw, &arr);
    if (!st.ok) return Status::Err(name + ": " + st.message);
    (*out)[name] = std::move(arr);
    cd += 46 + nlen + xlen + clen;
  }
  return Status::Ok();
}

// PJRT-free dtype size table (the PJRT_Buffer_Type mapping lives in
// predictor.cc next to the PJRT calls).
inline size_t DtypeSize(const std::string& d) {
  if (d == "<f4" || d == "float32" || d == "<i4" || d == "int32") return 4;
  if (d == "<f8" || d == "float64" || d == "<i8" || d == "int64") return 8;
  if (d == "<f2" || d == "float16") return 2;
  if (d == "|i1" || d == "int8" || d == "|u1" || d == "uint8" ||
      d == "|b1" || d == "bool") return 1;
  return 0;  // unsupported
}

}  // namespace ptnative
