// Bounded MPMC blocking queue — the host-side hand-off primitive of the
// native data pipeline (role of the reference's
// operators/reader/blocking_queue.h + framework/blocking_queue.h, redesigned:
// close() semantics instead of exception-driven shutdown).
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>
#include <optional>

namespace ptnative {

template <typename T>
class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity) : capacity_(capacity) {}

  // Returns false if the queue was closed (item not enqueued).
  bool Push(T item) {
    std::unique_lock<std::mutex> lock(mu_);
    not_full_.wait(lock, [&] { return closed_ || q_.size() < capacity_; });
    if (closed_) return false;
    q_.push_back(std::move(item));
    not_empty_.notify_one();
    return true;
  }

  // Blocks until an item or close+drain; nullopt = finished.
  std::optional<T> Pop() {
    std::unique_lock<std::mutex> lock(mu_);
    not_empty_.wait(lock, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return std::nullopt;  // closed and drained
    T item = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return item;
  }

  // Producers done: wake all consumers; queue drains then reports end.
  void Close() {
    std::lock_guard<std::mutex> lock(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  bool Closed() {
    std::lock_guard<std::mutex> lock(mu_);
    return closed_;
  }

  size_t Size() {
    std::lock_guard<std::mutex> lock(mu_);
    return q_.size();
  }

 private:
  const size_t capacity_;
  std::mutex mu_;
  std::condition_variable not_full_, not_empty_;
  std::deque<T> q_;
  bool closed_ = false;
};

}  // namespace ptnative
