// Native multithreaded MultiSlot data feed.
//
// Capability role: the reference's C++ ingestion stack — MultiSlotDataFeed
// text parsing (reference: framework/data_feed.h:211, data_feed.proto:17)
// plus the double-buffered reader thread pool (reference:
// operators/reader/buffered_reader.cc) — rebuilt for a TPU host: worker
// threads parse sharded text files off the training thread and assemble
// *dense, padded* per-slot batches (values + row lengths: the framework's
// ragged canonicalization replacing LoD), handed to Python through a
// bounded blocking queue via a plain C ABI (ctypes — no pybind).
//
// Line format (one sample per line, whitespace-separated, per slot):
//   <n_i> v_1 ... v_{n_i}   repeated for each declared slot
// Slot dtypes: 'u' = int64 ids, 'f' = float32 values.
//
// Build: `make` in paddle_tpu/native (produces libptdatafeed.so).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "blocking_queue.h"

namespace ptnative {

struct SlotSpec {
  std::string name;
  char dtype;  // 'u' int64 | 'f' float32
};

struct Sample {
  // per slot: raw values (int64 stored in i64, floats in f32)
  std::vector<std::vector<int64_t>> ints;
  std::vector<std::vector<float>> floats;
};

struct Batch {
  // per slot: padded dense values + per-sample lengths
  std::vector<std::vector<int64_t>> ivalues;   // [slot][b * maxlen]
  std::vector<std::vector<float>> fvalues;     // [slot][b * maxlen]
  std::vector<std::vector<int64_t>> lengths;   // [slot][b]
  std::vector<int64_t> maxlen;                 // [slot]
  int64_t batch_size = 0;
};

class Feed {
 public:
  Feed(std::vector<std::string> files, std::vector<SlotSpec> slots,
       int batch_size, int num_threads, int queue_capacity, bool drop_last)
      : files_(std::move(files)),
        slots_(std::move(slots)),
        batch_size_(batch_size),
        drop_last_(drop_last),
        file_queue_(files_.size() + 1),
        batch_queue_(queue_capacity) {
    for (const auto& f : files_) file_queue_.Push(f);
    file_queue_.Close();
    live_workers_ = num_threads;
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Feed() {
    batch_queue_.Close();
    file_queue_.Close();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
  }

  Batch* Next() {
    auto b = batch_queue_.Pop();
    if (!b) return nullptr;
    return b->release();
  }

  std::string error() {
    std::lock_guard<std::mutex> lock(error_mu_);
    return error_;
  }

 private:
  using BatchPtr = std::unique_ptr<Batch>;

  void WorkerLoop() {
    std::vector<Sample> buf;
    buf.reserve(batch_size_);
    while (auto file = file_queue_.Pop()) {
      std::ifstream in(*file);
      if (!in) {
        SetError("cannot open " + *file);
        break;
      }
      std::string line;
      while (std::getline(in, line)) {
        if (line.empty()) continue;
        Sample s;
        if (!ParseLine(line, &s)) {
          SetError("parse error in " + *file + ": " + line.substr(0, 80));
          continue;  // skip malformed line, keep feeding
        }
        buf.push_back(std::move(s));
        if ((int)buf.size() == batch_size_) {
          EmitBatch(&buf);
        }
      }
    }
    if (!buf.empty() && !drop_last_) EmitBatch(&buf);
    if (--live_workers_ == 0) batch_queue_.Close();
  }

  bool ParseLine(const std::string& line, Sample* s) {
    std::istringstream is(line);
    s->ints.resize(slots_.size());
    s->floats.resize(slots_.size());
    for (size_t i = 0; i < slots_.size(); ++i) {
      long long n;
      if (!(is >> n) || n < 0) return false;
      if (slots_[i].dtype == 'u') {
        auto& v = s->ints[i];
        v.resize(n);
        for (long long j = 0; j < n; ++j)
          if (!(is >> v[j])) return false;
      } else {
        auto& v = s->floats[i];
        v.resize(n);
        for (long long j = 0; j < n; ++j)
          if (!(is >> v[j])) return false;
      }
    }
    return true;
  }

  void EmitBatch(std::vector<Sample>* buf) {
    auto batch = std::make_unique<Batch>();
    const size_t ns = slots_.size();
    const size_t bs = buf->size();
    batch->batch_size = (int64_t)bs;
    batch->ivalues.resize(ns);
    batch->fvalues.resize(ns);
    batch->lengths.resize(ns);
    batch->maxlen.resize(ns);
    for (size_t i = 0; i < ns; ++i) {
      int64_t maxlen = 1;  // pad to >=1 so fixed-width slots stay (B, n)
      auto& lens = batch->lengths[i];
      lens.resize(bs);
      for (size_t b = 0; b < bs; ++b) {
        int64_t n = slots_[i].dtype == 'u' ? (*buf)[b].ints[i].size()
                                           : (*buf)[b].floats[i].size();
        lens[b] = n;
        if (n > maxlen) maxlen = n;
      }
      batch->maxlen[i] = maxlen;
      if (slots_[i].dtype == 'u') {
        auto& out = batch->ivalues[i];
        out.assign(bs * maxlen, 0);
        for (size_t b = 0; b < bs; ++b)
          std::memcpy(out.data() + b * maxlen, (*buf)[b].ints[i].data(),
                      (*buf)[b].ints[i].size() * sizeof(int64_t));
      } else {
        auto& out = batch->fvalues[i];
        out.assign(bs * maxlen, 0.f);
        for (size_t b = 0; b < bs; ++b)
          std::memcpy(out.data() + b * maxlen, (*buf)[b].floats[i].data(),
                      (*buf)[b].floats[i].size() * sizeof(float));
      }
    }
    buf->clear();
    batch_queue_.Push(std::move(batch));
  }

  std::vector<std::string> files_;
  std::vector<SlotSpec> slots_;
  const int batch_size_;
  const bool drop_last_;
  BlockingQueue<std::string> file_queue_;
  BlockingQueue<BatchPtr> batch_queue_;
  std::vector<std::thread> workers_;
  std::atomic<int> live_workers_{0};
  std::mutex error_mu_;
  std::string error_;

  void SetError(std::string msg) {
    std::lock_guard<std::mutex> lock(error_mu_);
    if (error_.empty()) error_ = std::move(msg);
  }
};

}  // namespace ptnative

// ---------------------------------------------------------------------------
// C ABI (ctypes surface — pybind-free binding layer)
// ---------------------------------------------------------------------------

extern "C" {

// slots_spec: comma-separated "name:u" / "name:f"
void* ptdf_create(const char** files, int nfiles, const char* slots_spec,
                  int batch_size, int num_threads, int queue_capacity,
                  int drop_last) {
  std::vector<std::string> fs(files, files + nfiles);
  std::vector<ptnative::SlotSpec> slots;
  std::istringstream ss(slots_spec);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    auto pos = tok.rfind(':');
    if (pos == std::string::npos || pos + 2 != tok.size()) return nullptr;
    char d = tok[pos + 1];
    if (d != 'u' && d != 'f') return nullptr;
    slots.push_back({tok.substr(0, pos), d});
  }
  if (slots.empty() || batch_size <= 0 || num_threads <= 0) return nullptr;
  return new ptnative::Feed(std::move(fs), std::move(slots), batch_size,
                            num_threads, queue_capacity, drop_last != 0);
}

void ptdf_destroy(void* h) { delete static_cast<ptnative::Feed*>(h); }

// nullptr at end of data
void* ptdf_next(void* h) { return static_cast<ptnative::Feed*>(h)->Next(); }

void ptdf_batch_free(void* b) { delete static_cast<ptnative::Batch*>(b); }

int64_t ptdf_batch_size(void* b) {
  return static_cast<ptnative::Batch*>(b)->batch_size;
}

int64_t ptdf_batch_maxlen(void* b, int slot) {
  return static_cast<ptnative::Batch*>(b)->maxlen[slot];
}

const int64_t* ptdf_batch_ivalues(void* b, int slot) {
  return static_cast<ptnative::Batch*>(b)->ivalues[slot].data();
}

const float* ptdf_batch_fvalues(void* b, int slot) {
  return static_cast<ptnative::Batch*>(b)->fvalues[slot].data();
}

const int64_t* ptdf_batch_lengths(void* b, int slot) {
  return static_cast<ptnative::Batch*>(b)->lengths[slot].data();
}

int ptdf_error(void* h, char* out, int cap) {
  std::string e = static_cast<ptnative::Feed*>(h)->error();
  if (e.empty()) return 0;
  std::snprintf(out, cap, "%s", e.c_str());
  return (int)e.size();
}

}  // extern "C"
