// C++ unit tests for the native artifact parsers — the reference's
// next-to-source *_test.cc convention (reference:
// paddle/fluid/framework/lod_tensor_test.cc et al; gtest replaced by a
// tiny assert harness to keep the bare-image build dependency-free).
//
// Build+run: make -C paddle_tpu/native test

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "artifact_parsers.h"

using namespace ptnative;

static int failures = 0;
#define CHECK_TRUE(cond)                                              \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      failures++;                                                     \
    }                                                                 \
  } while (0)

static void test_json_parser() {
  const char* text =
      "{\"format\": \"stablehlo+npz/v2\", \"n\": 3.5, \"ok\": true,"
      " \"names\": [\"a\", \"b\"], \"shapes\": {\"x\": [-1, 8]}}";
  JsonParser jp{text, text + strlen(text)};
  Json j = jp.parse();
  CHECK_TRUE(!jp.fail);
  CHECK_TRUE(j.find("format")->str == "stablehlo+npz/v2");
  CHECK_TRUE(j.find("n")->num == 3.5);
  CHECK_TRUE(j.find("ok")->b);
  CHECK_TRUE(j.find("names")->arr.size() == 2);
  CHECK_TRUE(j.find("names")->arr[1].str == "b");
  const Json* shapes = j.find("shapes");
  CHECK_TRUE(shapes && shapes->find("x")->arr[0].num == -1);
}

static void test_json_escapes_and_errors() {
  const char* esc = "{\"s\": \"a\\nb\\\"c\"}";
  JsonParser jp{esc, esc + strlen(esc)};
  auto j = jp.parse();
  CHECK_TRUE(!jp.fail && j.find("s")->str == "a\nb\"c");
  const char* bad = "{\"x\": }";
  JsonParser jb{bad, bad + strlen(bad)};
  jb.parse();
  CHECK_TRUE(jb.fail);
}

static void test_npy_parser() {
  // hand-rolled v1.0 .npy: 2x3 float32
  std::string hdr =
      "{'descr': '<f4', 'fortran_order': False, 'shape': (2, 3), }";
  while ((10 + hdr.size() + 1) % 64 != 0) hdr += ' ';
  hdr += '\n';
  std::vector<uint8_t> raw;
  const char magic[] = "\x93NUMPY\x01\x00";
  raw.insert(raw.end(), magic, magic + 8);
  raw.push_back(hdr.size() & 0xff);
  raw.push_back((hdr.size() >> 8) & 0xff);
  raw.insert(raw.end(), hdr.begin(), hdr.end());
  float data[6] = {0, 1, 2, 3, 4, 5};
  raw.insert(raw.end(), (uint8_t*)data, (uint8_t*)data + sizeof(data));

  NpyArray arr;
  auto st = ParseNpy(raw, &arr);
  CHECK_TRUE(st.ok);
  CHECK_TRUE(arr.dtype == "<f4");
  CHECK_TRUE(arr.shape.size() == 2 && arr.shape[0] == 2 && arr.shape[1] == 3);
  CHECK_TRUE(arr.data.size() == 24);
  CHECK_TRUE(((float*)arr.data.data())[4] == 4.0f);
}

static void test_npy_rejects_garbage() {
  std::vector<uint8_t> bad = {1, 2, 3};
  NpyArray arr;
  CHECK_TRUE(!ParseNpy(bad, &arr).ok);
}

static void test_npz_missing_file() {
  std::map<std::string, NpyArray> out;
  CHECK_TRUE(!ReadNpz("/nonexistent/params.npz", &out).ok);
}

static void test_dtype_sizes() {
  CHECK_TRUE(DtypeSize("<f4") == 4);
  CHECK_TRUE(DtypeSize("int64") == 8);
  CHECK_TRUE(DtypeSize("bool") == 1);
  CHECK_TRUE(DtypeSize("complex128") == 0);
}

int main() {
  test_json_parser();
  test_json_escapes_and_errors();
  test_npy_parser();
  test_npy_rejects_garbage();
  test_npz_missing_file();
  test_dtype_sizes();
  if (failures) {
    fprintf(stderr, "%d failures\n", failures);
    return 1;
  }
  printf("predictor_test: all ok\n");
  return 0;
}
