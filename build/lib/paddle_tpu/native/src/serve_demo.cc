// Standalone C++ serving demo — Python-free model serving (capability
// parity with the reference's Python-free path: paddle/fluid/train/demo/
// demo_trainer.cc loads ProgramDescs and runs them from C++; here we load
// a save_inference_model StableHLO artifact and serve it via PJRT).
//
// Usage: ptserve <model_dir> <pjrt_plugin.so> [batch]
//   feeds zeros of the manifest-declared shapes, prints output shapes +
//   first values. Exit 0 on success.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* ptpred_load(const char* model_dir);
int ptpred_ok(void* h);
const char* ptpred_error(void* h);
int ptpred_compile(void* h, const char* plugin_path);
int ptpred_num_feeds(void* h);
const char* ptpred_feed_name(void* h, int i);
int ptpred_num_fetches(void* h);
const char* ptpred_fetch_name(void* h, int i);
int ptpred_run(void* h, const void** feed_ptrs, const int64_t* dims,
               const int* ranks);
int ptpred_out_rank(void* h, int i);
int64_t ptpred_out_dim(void* h, int i, int d);
const void* ptpred_out_data(void* h, int i, int64_t* nbytes);
void ptpred_destroy(void* h);
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <pjrt_plugin.so> [batch]\n",
            argv[0]);
    return 64;
  }
  int batch = argc > 3 ? atoi(argv[3]) : 1;
  void* p = ptpred_load(argv[1]);
  if (!ptpred_ok(p)) {
    fprintf(stderr, "load failed: %s\n", ptpred_error(p));
    return 1;
  }
  printf("model loaded: %d feeds, %d fetches\n", ptpred_num_feeds(p),
         ptpred_num_fetches(p));
  if (!ptpred_compile(p, argv[2])) {
    fprintf(stderr, "compile failed: %s\n", ptpred_error(p));
    return 2;
  }
  // feeds: zeros; shapes come from the manifest via the feed introspection
  // (simplest demo: assume rank-2 (batch, dim) float32 feeds; a real server
  // would read manifest feed_shapes — kept minimal like demo_trainer.cc)
  int nf = ptpred_num_feeds(p);
  std::vector<std::vector<float>> storage(nf);
  std::vector<const void*> ptrs(nf);
  std::vector<int64_t> dims;
  std::vector<int> ranks(nf, 2);
  for (int i = 0; i < nf; i++) {
    storage[i].assign((size_t)batch * 784, 0.0f);  // demo: mnist-sized
    ptrs[i] = storage[i].data();
    dims.push_back(batch);
    dims.push_back(784);
  }
  if (!ptpred_run(p, ptrs.data(), dims.data(), ranks.data())) {
    fprintf(stderr, "run failed: %s\n", ptpred_error(p));
    return 3;
  }
  for (int i = 0; i < ptpred_num_fetches(p); i++) {
    printf("fetch %s: shape(", ptpred_fetch_name(p, i));
    for (int d = 0; d < ptpred_out_rank(p, i); d++)
      printf("%s%lld", d ? "," : "", (long long)ptpred_out_dim(p, i, d));
    int64_t nbytes = 0;
    const float* data = (const float*)ptpred_out_data(p, i, &nbytes);
    printf(") first=%g\n", nbytes >= 4 ? data[0] : 0.0);
  }
  ptpred_destroy(p);
  printf("ok\n");
  return 0;
}
