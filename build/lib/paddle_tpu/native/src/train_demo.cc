// Python-free TRAINING demo over a save_train_program artifact
// (capability parity with the reference's C++ training path:
// paddle/fluid/train/demo/demo_trainer.cc — load ProgramDescs, run the
// startup then loop the main program from C++; here the whole train step is
// one compiled StableHLO function whose state outputs feed back as inputs,
// staying device-resident between steps).
//
// Usage: pttrain <model_dir> <pjrt_plugin.so> [steps]
//   feeds random normal x / zero labels of the manifest shapes, prints the
//   loss per step. Exit 0 when the loss decreased.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

extern "C" {
void* ptpred_load(const char* model_dir);
int ptpred_ok(void* h);
const char* ptpred_error(void* h);
int ptpred_compile(void* h, const char* plugin_path);
int ptpred_num_feeds(void* h);
const char* ptpred_feed_name(void* h, int i);
int ptpred_feed_rank(void* h, int i);
int64_t ptpred_feed_dim(void* h, int i, int d);
const char* ptpred_feed_dtype(void* h, int i);
int ptpred_run(void* h, const void** feed_ptrs, const int64_t* dims,
               const int* ranks);
const void* ptpred_out_data(void* h, int i, int64_t* nbytes);
void ptpred_destroy(void* h);
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <pjrt_plugin.so> [steps]\n",
            argv[0]);
    return 64;
  }
  int steps = argc > 3 ? atoi(argv[3]) : 10;
  void* p = ptpred_load(argv[1]);
  if (!ptpred_ok(p)) {
    fprintf(stderr, "load failed: %s\n", ptpred_error(p));
    return 1;
  }
  printf("train program loaded: %d feeds\n", ptpred_num_feeds(p));
  if (!ptpred_compile(p, argv[2])) {
    fprintf(stderr, "compile failed: %s\n", ptpred_error(p));
    return 2;
  }
  int nf = ptpred_num_feeds(p);
  std::mt19937 rng(0);
  std::normal_distribution<float> dist(0.0f, 1.0f);
  std::vector<std::vector<char>> storage(nf);
  std::vector<const void*> ptrs(nf);
  std::vector<int64_t> dims;
  std::vector<int> ranks(nf);
  for (int i = 0; i < nf; i++) {
    ranks[i] = ptpred_feed_rank(p, i);
    int64_t n = 1;
    for (int d = 0; d < ranks[i]; d++) {
      int64_t dim = ptpred_feed_dim(p, i, d);
      dims.push_back(dim);
      n *= dim;
    }
    std::string dt = ptpred_feed_dtype(p, i);
    if (dt == "float32") {
      storage[i].resize(n * 4);
      float* f = (float*)storage[i].data();
      for (int64_t k = 0; k < n; k++) f[k] = dist(rng);
    } else if (dt == "int32" || dt == "int64") {
      size_t width = dt == "int32" ? 4 : 8;
      storage[i].assign(n * width, 0);  // labels: class 0
    } else {
      fprintf(stderr, "unsupported feed dtype %s\n", dt.c_str());
      return 3;
    }
    ptrs[i] = storage[i].data();
  }
  double first = 0, last = 0;
  for (int s = 0; s < steps; s++) {
    if (!ptpred_run(p, ptrs.data(), dims.data(), ranks.data())) {
      fprintf(stderr, "step %d failed: %s\n", s, ptpred_error(p));
      return 4;
    }
    int64_t nbytes = 0;
    const float* loss = (const float*)ptpred_out_data(p, 0, &nbytes);
    double l = nbytes >= 4 ? loss[0] : 0.0;
    if (s == 0) first = l;
    last = l;
    printf("step %d loss %.6f\n", s, l);
  }
  ptpred_destroy(p);
  if (last < first) {
    printf("ok: loss %.4f -> %.4f\n", first, last);
    return 0;
  }
  fprintf(stderr, "loss did not decrease (%.4f -> %.4f)\n", first, last);
  return 5;
}
