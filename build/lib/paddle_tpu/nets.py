"""Convenience network compositions (reference:
python/paddle/fluid/nets.py — simple_img_conv_pool, img_conv_group,
sequence_conv_pool, glu, scaled_dot_product_attention built from
primitives)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from . import nn
from .core.enforce import enforce
from .ops import rnn as R
from .ops.attention import scaled_dot_product_attention  # re-export (nets.py:343)
from .ops.sequence import sequence_pool


def simple_img_conv_pool(in_channels: int, num_filters: int,
                         filter_size: int, pool_size: int, pool_stride: int,
                         act: Optional[str] = "relu",
                         pool_type: str = "max") -> nn.Layer:
    """reference: nets.py simple_img_conv_pool — conv + act + pool."""
    return nn.Sequential(
        nn.Conv2D(in_channels, num_filters, filter_size, act=act),
        nn.Pool2D(pool_size, pool_type, stride=pool_stride))


def img_conv_group(in_channels: int, conv_num_filter: Sequence[int],
                   conv_filter_size: int = 3, pool_size: int = 2,
                   pool_stride: int = 2, conv_act: Optional[str] = "relu",
                   conv_with_batchnorm: bool = False,
                   pool_type: str = "max") -> nn.Layer:
    """reference: nets.py img_conv_group — VGG-style conv stack + pool."""
    layers = []
    cur = in_channels
    for nf in conv_num_filter:
        pad = (conv_filter_size - 1) // 2
        if conv_with_batchnorm:
            layers.append(nn.Conv2D(cur, nf, conv_filter_size, padding=pad,
                                    bias_attr=False))
            layers.append(nn.BatchNorm(nf, act=conv_act))
        else:
            layers.append(nn.Conv2D(cur, nf, conv_filter_size, padding=pad,
                                    act=conv_act))
        cur = nf
    layers.append(nn.Pool2D(pool_size, pool_type, stride=pool_stride))
    return nn.Sequential(*layers)


class SequenceConvPool(nn.Layer):
    """reference: nets.py sequence_conv_pool — sequence conv + act +
    sequence pool over padded (B, T, D) + lengths."""

    def __init__(self, input_dim: int, num_filters: int, filter_size: int,
                 act: str = "tanh", pool_type: str = "max"):
        super().__init__()
        from . import initializer as I

        self.filter_size = filter_size
        self.pool_type = pool_type
        self.act = act
        self.create_parameter("weight", (filter_size * input_dim,
                                         num_filters), None,
                              I.XavierUniform())
        self.create_parameter("bias", (num_filters,), None, I.Constant(0.0),
                              is_bias=True)

    def forward(self, x, lengths):
        h = R.sequence_conv(x, self.weight, lengths=lengths,
                            context_length=self.filter_size, bias=self.bias)
        if self.act == "tanh":
            h = jnp.tanh(h)
        elif self.act == "relu":
            h = jnp.maximum(h, 0.0)
        return sequence_pool(h, lengths, self.pool_type)


def glu(x, axis: int = -1):
    """Gated linear unit (reference: nets.py glu): split in half along
    ``axis``; a * sigmoid(b)."""
    enforce(x.shape[axis] % 2 == 0,
            "glu axis dim must be even, got %s", x.shape[axis])
    a, b = jnp.split(x, 2, axis=axis)
    return a * (1.0 / (1.0 + jnp.exp(-b)))


__all__ = ["simple_img_conv_pool", "img_conv_group", "SequenceConvPool",
           "glu", "scaled_dot_product_attention"]


def sequence_conv_pool(input, lengths, weight, bias=None, *,
                       filter_size: int = 3, act: str = "tanh",
                       pool_type: str = "max"):
    """Functional form of SequenceConvPool (fluid nets.py name): sequence
    conv with explicit weights + activation + masked sequence pool."""
    h = R.sequence_conv(input, weight, lengths=lengths,
                        context_length=filter_size, bias=bias)
    if act == "tanh":
        h = jnp.tanh(h)
    elif act == "relu":
        h = jnp.maximum(h, 0.0)
    return sequence_pool(h, lengths, pool_type)
