"""NCE and hierarchical-sigmoid layers (reference:
python/paddle/fluid/dygraph/nn.py NCE / HSigmoid classes)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import initializer as I
from ..ops import sampling as SP
from .layer import Layer


class NCE(Layer):
    """Noise-contrastive estimation head (reference: dygraph/nn.py NCE)."""

    def __init__(self, dim: int, num_total_classes: int,
                 num_neg_samples: int = 10, sampler: str = "uniform",
                 bias_attr: bool = True, dtype=None):
        super().__init__()
        self.num_neg_samples = num_neg_samples
        self.sampler = sampler
        self.create_parameter("weight", (num_total_classes, dim), dtype,
                              I.XavierUniform())
        self.has_bias = bias_attr
        if bias_attr:
            self.create_parameter("bias", (num_total_classes,), dtype,
                                  I.Constant(0.0), is_bias=True)

    def forward(self, x, label, custom_neg=None):
        return SP.nce_loss(
            x, label, self.weight,
            bias=self.bias if self.has_bias else None,
            num_neg_samples=self.num_neg_samples, sampler=self.sampler,
            key=None if custom_neg is not None else self.rng("nce"),
            custom_neg=custom_neg)


class HSigmoid(Layer):
    """Hierarchical sigmoid head (reference: dygraph/nn.py HSigmoid /
    operators/hierarchical_sigmoid_op.cc)."""

    def __init__(self, dim: int, num_classes: int, path_table=None,
                 path_code=None, bias_attr: bool = True, dtype=None):
        super().__init__()
        self.num_classes = num_classes
        if path_table is not None:
            self.path_table = jnp.asarray(path_table)
            self.path_code = jnp.asarray(path_code)
            num_nodes = int(jnp.max(self.path_table)) + 1
        else:
            # precompute the complete-binary-tree paths once; rebuilding per
            # forward would be a 100k-iteration host loop on big vocabularies
            from ..ops.sampling import _default_tree_codes

            self.path_table, self.path_code = _default_tree_codes(num_classes)
            num_nodes = num_classes  # internal nodes of a complete tree < C
        self.create_parameter("weight", (num_nodes, dim), dtype,
                              I.XavierUniform())
        self.has_bias = bias_attr
        if bias_attr:
            self.create_parameter("bias", (num_nodes,), dtype,
                                  I.Constant(0.0), is_bias=True)

    def forward(self, x, label):
        return SP.hsigmoid_loss(
            x, label, self.weight,
            bias=self.bias if self.has_bias else None,
            num_classes=self.num_classes, path_table=self.path_table,
            path_code=self.path_code)
