"""Sparse-gradient capture/inject contexts for embedding layers.

The SelectedRows capability (reference: framework/selected_rows.h:32,
lookup_table_op.cc is_sparse=True emits SelectedRows grads) redesigned
for XLA: autodiff of a dense gather scatter-adds into a dense (V, D)
zeros — an O(V) materialization and O(V) optimizer update per step. The
TPU-native train step instead splits at the gather boundary:

1. CAPTURE pass: the model forward runs once inside a capture context;
   each sparse embedding records the ids it consumes (tracers — trace
   structure only; XLA CSEs the duplicate forward away).
2. Row gather ``take(table, ids)`` runs OUTSIDE the differentiated
   function; the loss is differentiated w.r.t. the gathered ROWS
   (O(B*T, D)), whose cotangent feeds the row-sparse optimizer update
   (optimizer/sparse.py).

An INJECT context replays the same forward with the pre-gathered rows
substituted, in the same call order — embedding layers consult
``active()`` and never touch their table inside the diff'd function.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_STACK: List["_Ctx"] = []


def active() -> Optional["_Ctx"]:
    return _STACK[-1] if _STACK else None


class _Ctx:
    def __init__(self, layer_ids):
        self.layer_ids = set(layer_ids)
        self._order: Dict[int, int] = {}  # id(layer) -> call count

    def handles(self, layer) -> bool:
        return id(layer) in self.layer_ids

    def _slot(self, layer) -> str:
        k = id(layer)
        n = self._order.get(k, 0)
        self._order[k] = n + 1
        return f"{k}:{n}"

    def __enter__(self):
        _STACK.append(self)
        return self

    def __exit__(self, *exc):
        _STACK.pop()
        return False


class Capture(_Ctx):
    """Records (slot -> ids) for every sparse-embedding call."""

    def __init__(self, layer_ids):
        super().__init__(layer_ids)
        self.ids: Dict[str, Any] = {}
        self.owner: Dict[str, int] = {}  # slot -> id(layer)

    def record(self, layer, ids):
        slot = self._slot(layer)
        self.ids[slot] = ids
        self.owner[slot] = id(layer)
        return slot


class Inject(_Ctx):
    """Replays pre-gathered rows in the same call order."""

    def __init__(self, layer_ids, rows: Dict[str, Any]):
        super().__init__(layer_ids)
        self.rows = rows

    def pop(self, layer):
        return self.rows[self._slot(layer)]
