"""Structured control flow — the XLA-native replacement for the reference's
sub-block interpreter ops (reference: paddle/fluid/operators/controlflow/
while_op.cc, conditional_block_op.cc, recurrent_op.cc and the python
StaticRNN/DynamicRNN/While/IfElse layers in layers/control_flow.py).

Design stance (SURVEY §7): no data-dependent Python control flow inside jit —
these wrap `lax.while_loop/cond/scan/switch` with reference-flavored names so
user code ports cleanly. `static_rnn` is the recurrent_op analog; `case`/
`switch_case` mirror the python layers of the same name. Compare/logical ops
(reference: controlflow/compare_op.cc:113-134, logical_op.cc) live here too.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


# --- compare ops (REGISTER_COMPARE_OP family) ------------------------------

def less_than(x, y):
    return jnp.less(x, y)


def less_equal(x, y):
    return jnp.less_equal(x, y)


def greater_than(x, y):
    return jnp.greater(x, y)


def greater_equal(x, y):
    return jnp.greater_equal(x, y)


def equal(x, y):
    return jnp.equal(x, y)


def not_equal(x, y):
    return jnp.not_equal(x, y)


# --- logical ops -----------------------------------------------------------

def logical_and(x, y):
    return jnp.logical_and(x, y)


def logical_or(x, y):
    return jnp.logical_or(x, y)


def logical_xor(x, y):
    return jnp.logical_xor(x, y)


def logical_not(x):
    return jnp.logical_not(x)


# --- structured control flow ----------------------------------------------

def while_loop(cond: Callable, body: Callable, loop_vars: Any):
    """reference: while_op.cc — trace-compatible while. `loop_vars` is a pytree."""
    return lax.while_loop(cond, body, loop_vars)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """reference: conditional_block_op.cc / layers.cond."""
    return lax.cond(pred, true_fn, false_fn, *operands)


def case(pred_fn_pairs: Sequence[Tuple[Any, Callable]], default: Callable = None):
    """reference: python layers.case — first true predicate wins."""
    def build(i):
        if i == len(pred_fn_pairs):
            if default is None:
                raise ValueError("case: no predicate matched and no default")
            return default()
        pred, fn = pred_fn_pairs[i]
        return lax.cond(pred, fn, lambda: build(i + 1))

    return build(0)


def switch_case(branch_index, branch_fns: Sequence[Callable], *operands):
    """reference: python layers.switch_case → lax.switch."""
    return lax.switch(branch_index, list(branch_fns), *operands)


def scan(f: Callable, init: Any, xs: Any, length: int = None, reverse: bool = False,
         unroll: int = 1):
    """The workhorse loop — replaces StaticRNN/recurrent_op
    (reference: operators/recurrent_op.cc)."""
    return lax.scan(f, init, xs, length=length, reverse=reverse, unroll=unroll)


def static_rnn(step_fn: Callable, inputs, initial_states,
               time_major: bool = False):
    """StaticRNN analog (reference: layers/control_flow.py StaticRNN).

    ``step_fn(x_t, states) -> (output_t, new_states)``; inputs is a pytree of
    (B, T, ...) arrays (or (T, B, ...) when time_major).
    Returns (outputs stacked on time axis, final_states).
    """
    if not time_major:
        inputs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), inputs)

    def body(states, x_t):
        out_t, new_states = step_fn(x_t, states)
        return new_states, out_t

    final_states, outs = lax.scan(body, initial_states, inputs)
    if not time_major:
        outs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), outs)
    return outs, final_states


def fori_loop(lower, upper, body: Callable, init):
    return lax.fori_loop(lower, upper, body, init)


# --- tensor array ----------------------------------------------------------

class TensorArray:
    """Trace-compatible tensor array of fixed max size — the
    write_to_array/read_from_array/array_to_lod_tensor capability (reference:
    operators/tensor_array_read_write_op.cc) on a dense preallocated buffer."""

    def __init__(self, size: int, element_shape, dtype=jnp.float32, buffer=None):
        self.size = size
        if buffer is not None:
            self.buffer = buffer
        else:
            self.buffer = jnp.zeros((size,) + tuple(element_shape), dtype)

    def write(self, index, value) -> "TensorArray":
        return TensorArray(self.size, value.shape, value.dtype,
                           buffer=lax.dynamic_update_index_in_dim(
                               self.buffer, value, index, 0))

    def read(self, index):
        return lax.dynamic_index_in_dim(self.buffer, index, 0, keepdims=False)

    def stack(self):
        return self.buffer


jax.tree_util.register_pytree_node(
    TensorArray,
    lambda ta: ((ta.buffer,), (ta.size,)),
    lambda aux, children: TensorArray(aux[0], children[0].shape[1:],
                                      children[0].dtype, buffer=children[0]),
)
