"""Sequence decoding + structured-prediction ops.

Capability parity with the reference's CTC, beam-search and CRF operators
(reference: operators/warpctc_op.cc — external warp-ctc library;
ctc_align_op.cc; beam_search_op.cc + beam_search_decode_op.cc — LoD-based
per-step beam bookkeeping; linear_chain_crf_op.cc; crf_decoding_op.cc;
edit_distance_op.cc), redesigned for XLA: log-space dynamic programs as
``lax.scan`` over time with static shapes and length masks — no external
CTC library (the MXU-friendly formulation IS the framework's kernel), no
LoD (ragged = dense + lengths, SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce

__all__ = ["ctc_loss", "ctc_align", "ctc_greedy_decode", "beam_search_step",
           "beam_search", "beam_search_decode", "beam_search_batch_step",
           "beam_search_decode_lod", "gather_beams", "linear_chain_crf",
           "crf_decoding", "edit_distance"]

_NEG = -1e30


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    dead = m <= _NEG
    m_safe = jnp.where(dead, 0.0, m)
    s = jnp.exp(a - m_safe) + jnp.exp(b - m_safe)
    # the dead branch must stay NaN-free under grad: log(0) -> log(1)
    out = m_safe + jnp.log(jnp.where(dead, 1.0, s))
    return jnp.where(dead, _NEG, out)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, *,
             blank: int = 0):
    """CTC negative log-likelihood (reference: operators/warpctc_op.cc wraps
    the external warp-ctc kernel; here the alpha recursion runs in log space
    as one lax.scan over time — batched, static, differentiable by JAX).

    log_probs: (B, T, V) log-softmax outputs; labels: (B, L) padded;
    input_lengths (B,), label_lengths (B,). Returns (B,) losses.
    """
    B, T, V = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    # extended label sequence: blank l1 blank l2 ... blank
    ext = jnp.full((B, S), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    # transitions: alpha[s] <- alpha[s] + alpha[s-1] (+ alpha[s-2] if the
    # symbol differs from the one two back and isn't blank)
    prev2_ok = jnp.zeros((B, S), bool)
    prev2_ok = prev2_ok.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    def step(alpha, t):
        lp = log_probs[:, t]  # (B, V)
        emit = jnp.take_along_axis(lp, ext, axis=1)  # (B, S)
        a1 = jnp.concatenate([jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        a2 = jnp.where(prev2_ok, a2, _NEG)
        new = _logsumexp2(_logsumexp2(alpha, a1), a2) + emit
        # frozen past input_length: keep alpha (final read below)
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    alpha0 = jnp.full((B, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(
        jnp.take_along_axis(log_probs[:, 0], ext[:, :1], axis=1)[:, 0])
    has1 = label_lengths > 0
    a01 = jnp.take_along_axis(log_probs[:, 0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(has1, a01, _NEG))
    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # total prob = alpha[last blank] + alpha[last label]
    send = 2 * label_lengths  # index of final blank
    a_end = jnp.take_along_axis(alpha, send[:, None], axis=1)[:, 0]
    a_lab = jnp.take_along_axis(alpha,
                                jnp.maximum(send - 1, 0)[:, None],
                                axis=1)[:, 0]
    a_lab = jnp.where(label_lengths > 0, a_lab, _NEG)
    return -_logsumexp2(a_end, a_lab)


def ctc_align(ids, lengths, *, blank: int = 0):
    """Collapse repeats then drop blanks (reference:
    operators/ctc_align_op.cc). ids (B, T) -> (out (B, T), out_lengths (B,))
    padded with ``blank`` — fixed capacity instead of LoD shrinkage."""
    B, T = ids.shape
    prev = jnp.concatenate([jnp.full((B, 1), -1, ids.dtype), ids[:, :-1]],
                           axis=1)
    t_idx = jnp.arange(T)[None, :]
    keep = (ids != blank) & (ids != prev) & (t_idx < lengths[:, None])
    # stable compaction: position = cumsum of keep - 1
    pos = jnp.cumsum(keep, axis=1) - 1
    out_len = jnp.max(jnp.where(keep, pos + 1, 0), axis=1)
    out = jnp.full((B, T), blank, ids.dtype)
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    # dropped writes: position T is out of bounds -> mode="drop" discards
    scatter_pos = jnp.where(keep, pos, T)
    out = out.at[b_idx, scatter_pos].set(ids, mode="drop")
    return out, out_len


def ctc_greedy_decode(log_probs, lengths, *, blank: int = 0):
    """argmax per frame + ctc_align — the reference's greedy CTC decoder
    composition (ctc_align over top-1 ids)."""
    ids = jnp.argmax(log_probs, axis=-1)
    return ctc_align(ids, lengths, blank=blank)


# ---------------------------------------------------------------------------
# Beam search
# ---------------------------------------------------------------------------

def beam_search_step(scores, beam_log_probs, finished, *, beam_size: int,
                     end_id: int, length_penalty: float = 0.0, step=1,
                     lengths=None):
    """One expansion step (the reference's beam_search op,
    operators/beam_search_op.cc, minus LoD bookkeeping): scores (K, V)
    log-probs for each live beam, beam_log_probs (K,) accumulated.

    GNMT length normalization: candidates are RANKED by
    ``total / ((5 + len) / 6) ** length_penalty`` where ``len`` is each
    hypothesis's OWN token count — live candidates grow to ``step``,
    finished beams keep the frozen length carried in ``lengths`` (K,).
    The per-hypothesis lengths are what make the penalty observable: a
    step-uniform divisor could never change a top-k. Accumulated scores
    stay un-penalized. ``lengths=None`` starts every beam at ``step``.

    Returns (next_acc (K,), parent (K,), token (K,), next_finished (K,),
    next_lengths (K,)). Finished beams propagate with only the end_id
    continuation.
    """
    K, V = scores.shape
    if lengths is None:
        lengths = jnp.full((K,), step, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    # finished beams: freeze score, only end_id continues
    frozen = jnp.full((V,), _NEG).at[end_id].set(0.0)
    total = jnp.where(finished[:, None], beam_log_probs[:, None] + frozen,
                      beam_log_probs[:, None] + scores)  # (K, V)
    step_i = jnp.asarray(step, jnp.int32)
    cand_len = jnp.where(finished[:, None], lengths[:, None],
                         step_i)                           # (K, V)
    lp = ((5.0 + cand_len.astype(total.dtype)) / 6.0) ** length_penalty
    ranked = total / lp
    top, flat = lax.top_k(ranked.reshape(-1), K)
    parent = flat // V
    token = flat % V
    next_acc = total.reshape(-1)[flat]
    next_fin = finished[parent] | (token == end_id)
    # already-finished keep their frozen length; newly-finished and live
    # candidates are `step` tokens long
    next_len = jnp.where(finished[parent], lengths[parent], step_i)
    return next_acc, parent, token, next_fin, next_len


def beam_search(init_state, step_fn: Callable, *, beam_size: int,
                max_len: int, bos_id: int, end_id: int,
                length_penalty: float = 0.0):
    """Full decode loop (the reference composes beam_search +
    beam_search_decode ops inside a While block, layers/control_flow.py
    DynamicRNN; here it's one lax.scan with pointer backtracking).

    step_fn(state, token (K,)) -> (log_probs (K, V), new_state); state
    leaves must carry a leading beam axis (K, ...).

    Returns (sequences (K, max_len), scores (K,)) best-first.
    """
    tok0 = jnp.full((beam_size,), bos_id, jnp.int32)
    acc0 = jnp.full((beam_size,), _NEG).at[0].set(0.0)  # only beam 0 live
    fin0 = jnp.zeros((beam_size,), bool)
    len0 = jnp.zeros((beam_size,), jnp.int32)

    def tick(carry, t):
        state, tok, acc, fin, lens = carry
        logp, state = step_fn(state, tok)
        acc, parent, tok, fin, lens = beam_search_step(
            logp, acc, fin, beam_size=beam_size, end_id=end_id,
            length_penalty=length_penalty, step=t + 1, lengths=lens)
        state = jax.tree_util.tree_map(lambda s: s[parent], state)
        return (state, tok, acc, fin, lens), (parent, tok)

    (_, _, acc, _, lens), (parents, tokens) = lax.scan(
        tick, (init_state, tok0, acc0, fin0, len0), jnp.arange(max_len))

    # backtrack: walk parent pointers from the end (reference:
    # beam_search_decode_op.cc walks the LoD sentence tree)
    def backtrack(beam_idx):
        def body(carry, t):
            bi, = carry
            tok = tokens[t][bi]
            return (parents[t][bi],), tok

        _, seq = lax.scan(body, (beam_idx,), jnp.arange(max_len)[::-1])
        return seq[::-1]

    seqs = jax.vmap(backtrack)(jnp.arange(beam_size))
    # final ranking is length-normalized (GNMT); returned scores stay raw
    lp = ((5.0 + jnp.maximum(lens, 1).astype(acc.dtype)) / 6.0
          ) ** length_penalty
    order = jnp.argsort(-(acc / lp))
    return seqs[order], acc[order]


# ---------------------------------------------------------------------------
# Linear-chain CRF
# ---------------------------------------------------------------------------

def linear_chain_crf(emissions, transitions, labels, lengths, *,
                     start_transitions=None, stop_transitions=None):
    """Negative log-likelihood of a linear-chain CRF (reference:
    operators/linear_chain_crf_op.cc — its transition matrix packs start/
    stop weights in rows 0/1; here they are explicit optional args).

    emissions (B, T, N), labels (B, T), lengths (B,) -> (B,) nll.
    """
    B, T, N = emissions.shape
    start = (start_transitions if start_transitions is not None
             else jnp.zeros((N,)))
    stop = (stop_transitions if stop_transitions is not None
            else jnp.zeros((N,)))

    # --- partition via forward algorithm ---
    def fwd(alpha, t):
        e = emissions[:, t]  # (B, N)
        new = jax.nn.logsumexp(alpha[:, :, None] + transitions[None], axis=1)
        new = new + e
        new = jnp.where((t < lengths)[:, None], new, alpha)
        return new, None

    alpha0 = start[None] + emissions[:, 0]
    alpha, _ = lax.scan(fwd, alpha0, jnp.arange(1, T))
    log_z = jax.nn.logsumexp(alpha + stop[None], axis=1)

    # --- gold path score ---
    t_idx = jnp.arange(T)[None, :]
    emit = jnp.take_along_axis(emissions, labels[..., None], axis=2)[..., 0]
    emit = jnp.where(t_idx < lengths[:, None], emit, 0.0).sum(axis=1)
    trans = transitions[labels[:, :-1], labels[:, 1:]]  # (B, T-1)
    trans = jnp.where(t_idx[:, 1:] < lengths[:, None], trans, 0.0).sum(axis=1)
    first = start[labels[:, 0]]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_lab = jnp.take_along_axis(labels, last_idx[:, None], axis=1)[:, 0]
    gold = emit + trans + first + stop[last_lab]
    return log_z - gold


def crf_decoding(emissions, transitions, lengths, *,
                 start_transitions=None, stop_transitions=None):
    """Viterbi decode (reference: operators/crf_decoding_op.cc) ->
    (paths (B, T), scores (B,)). Positions past ``lengths`` hold 0."""
    B, T, N = emissions.shape
    start = (start_transitions if start_transitions is not None
             else jnp.zeros((N,)))
    stop = (stop_transitions if stop_transitions is not None
            else jnp.zeros((N,)))

    def fwd(carry, t):
        score = carry  # (B, N)
        cand = score[:, :, None] + transitions[None]  # (B, N, N)
        best_prev = jnp.argmax(cand, axis=1)  # (B, N)
        new = jnp.max(cand, axis=1) + emissions[:, t]
        new = jnp.where((t < lengths)[:, None], new, score)
        ptr = jnp.where((t < lengths)[:, None], best_prev,
                        jnp.broadcast_to(jnp.arange(N)[None], (B, N)))
        return new, ptr

    score0 = start[None] + emissions[:, 0]
    score, ptrs = lax.scan(fwd, score0, jnp.arange(1, T))  # ptrs (T-1, B, N)
    final = score + stop[None]
    best_last = jnp.argmax(final, axis=1)  # (B,)
    best_score = jnp.max(final, axis=1)

    def backtrack(b):
        def body(carry, t):
            cur = carry
            prev = ptrs[t, b, cur]
            return prev, cur

        last, path_rev = lax.scan(body, best_last[b],
                                  jnp.arange(T - 1)[::-1])
        return jnp.concatenate([jnp.asarray([last]), path_rev[::-1]])

    paths = jax.vmap(backtrack)(jnp.arange(B))
    paths = jnp.where(jnp.arange(T)[None] < lengths[:, None], paths, 0)
    return paths, best_score


def edit_distance(hyp, hyp_lengths, ref, ref_lengths, *,
                  normalized: bool = False):
    """Levenshtein distance on padded id sequences (reference:
    operators/edit_distance_op.cc) — DP over the hypothesis axis as a scan,
    static (B, Lr) rows. Returns (B,) distances (float)."""
    B, Lh = hyp.shape
    Lr = ref.shape[1]

    def per_batch(h, hl, r, rl):
        row0 = jnp.arange(Lr + 1, dtype=jnp.float32)

        def step(row, i):
            # row: distances vs ref prefix for hyp prefix i
            ins = row[0] + 1

            def inner(carry, j):
                left = carry  # new_row[j]
                sub = row[j] + (h[i] != r[j])
                dele = row[j + 1] + 1
                best = jnp.minimum(jnp.minimum(left + 1, dele), sub)
                return best, best

            _, rest = lax.scan(inner, ins, jnp.arange(Lr))
            new_row = jnp.concatenate([jnp.asarray([ins]), rest])
            new_row = jnp.where(i < hl, new_row, row)
            return new_row, None

        row, _ = lax.scan(step, row0, jnp.arange(Lh))
        d = row[rl]
        return d / jnp.maximum(rl, 1) if normalized else d

    return jax.vmap(per_batch)(hyp, hyp_lengths, ref, ref_lengths)


def beam_search_decode(step_ids, step_parents, step_scores=None, *,
                       end_id: int = 1):
    """Backtrack per-step beam candidates into full sequences (reference:
    operators/beam_search_decode_op.cc — walks the LoD parent links; here
    parents are an explicit array, the padded-dense form of that link).

    step_ids (T, B, K): token chosen by each beam at each step.
    step_parents (T, B, K): index in [0, K) of the parent beam at t-1.
    step_scores (T, B, K) optional: cumulative scores per beam.

    Returns (sequences (B, K, T) backtracked token ids, scores (B, K) —
    each beam's final cumulative score, zeros if none given).
    """
    T, B, K = step_ids.shape

    def backtrack_one(ids_tb, parents_tb):
        # ids_tb, parents_tb: (T, K)
        def run(k):
            def step(carry, t):
                beam_idx, acc = carry
                tok = ids_tb[t][beam_idx]
                parent = parents_tb[t][beam_idx]
                return (parent, acc.at[t].set(tok)), None

            init = (jnp.asarray(k), jnp.zeros((T,), step_ids.dtype))
            (final_parent, acc), _ = lax.scan(
                step, init, jnp.arange(T - 1, -1, -1))
            return acc

        return jax.vmap(run)(jnp.arange(K))  # (K, T)

    seqs = jax.vmap(backtrack_one)(jnp.transpose(step_ids, (1, 0, 2)),
                                   jnp.transpose(step_parents, (1, 0, 2)))
    scores = (step_scores[-1] if step_scores is not None
              else jnp.zeros((B, K), jnp.float32))
    return seqs, scores


def beam_search_batch_step(log_probs, pre_scores, finished, step,
                           lengths=None, *, beam_size: int, end_id: int,
                           length_penalty: float = 0.0):
    """Batched form of :func:`beam_search_step` — the op the reference
    runs INSIDE its decode While block (reference:
    operators/beam_search_op.cc; layers/nn.py beam_search), redesigned
    for static shapes: each source keeps exactly K live beams.

    log_probs (B, K, V), pre_scores (B, K), finished (B, K) bool-ish,
    step scalar (the loop counter — drives the length penalty),
    lengths (B, K) frozen hypothesis lengths (None starts at ``step``).
    Returns (acc (B, K), parent (B, K) int32, token (B, K) int32,
    finished (B, K) bool, lengths (B, K) int32).
    """
    t = jnp.reshape(step, ()).astype(jnp.int32)
    if lengths is None:
        lengths = jnp.broadcast_to(t, pre_scores.shape)

    def one(lp, acc, fin, lens):
        return beam_search_step(lp, acc, fin.astype(bool),
                                beam_size=beam_size, end_id=end_id,
                                length_penalty=length_penalty, step=t,
                                lengths=lens)

    acc, parent, token, fin, lens = jax.vmap(one)(
        log_probs, pre_scores, finished, lengths)
    return (acc, parent.astype(jnp.int32), token.astype(jnp.int32), fin,
            lens)


def gather_beams(x, parent):
    """Reorder per-beam state by parent index: x (B, K, ...),
    parent (B, K) -> x[b, parent[b, k]] (the state shuffle the
    reference gets implicitly from beam_search's LoD selection)."""
    idx = parent.astype(jnp.int32)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - idx.ndim))
    return jnp.take_along_axis(x, jnp.broadcast_to(
        idx, idx.shape[:2] + x.shape[2:]), axis=1)


def beam_search_decode_lod(step_ids, step_parents, final_scores, *,
                           end_id: int = 1,
                           length_penalty: float = 0.0):
    """Backtrack + rank + measure: the full beam_search_decode contract
    (reference: operators/beam_search_decode_op.cc returns a LoD
    level-2 tensor — level 1 = per-source candidate list, level 2 =
    each candidate's tokens). The padded-dense equivalent of that
    nested LoD is the triple returned here:

    - sequences (B, K, T): candidate k of source b, best-first
      (ranked by final score),
    - lengths (B, K): its true token count (up to and including the
      first ``end_id``; T when the beam never finished) — the level-2
      offsets; K itself is the uniform level-1 fan-out,
    - scores (B, K): final cumulative log-prob, descending.
    """
    seqs, _ = beam_search_decode(step_ids, step_parents, end_id=end_id)
    T = step_ids.shape[0]
    is_end = seqs == end_id
    has_end = is_end.any(axis=-1)
    first = jnp.argmax(is_end, axis=-1)
    lengths = jnp.where(has_end, first + 1, T).astype(jnp.int32)
    # rank length-normalized (GNMT); returned scores stay raw
    lp = ((5.0 + jnp.maximum(lengths, 1).astype(final_scores.dtype))
          / 6.0) ** length_penalty
    order = jnp.argsort(-(final_scores / lp), axis=1)   # (B, K)
    seqs = gather_beams(seqs, order)
    lengths = jnp.take_along_axis(lengths, order, axis=1)
    scores = jnp.take_along_axis(final_scores, order, axis=1)
    return seqs, lengths, scores
