"""Detection op suite — capability parity with the reference's
`paddle/fluid/operators/detection/` (56 files: anchors, bbox coding, IoU,
NMS, RoI pooling, YOLO decoding, proposal generation...), re-designed for
XLA: **every op is static-shape**. Where the reference returns
variable-length LoD outputs (e.g. multiclass_nms keeps "however many
survive", detection/multiclass_nms_op.cc), the TPU-native contract returns
fixed-capacity buffers plus a validity mask/count — the compiler-friendly
ragged encoding used throughout this framework (SURVEY.md §5.7).

Boxes are [x1, y1, x2, y2] unless noted, matching the reference layout.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce

__all__ = [
    "iou_similarity", "box_coder", "box_clip", "prior_box",
    "density_prior_box", "anchor_generator", "yolo_box", "nms",
    "multiclass_nms", "matrix_nms", "roi_align", "roi_pool",
    "generate_proposals", "bipartite_match", "target_assign",
    "distribute_fpn_proposals", "collect_fpn_proposals", "polygon_box_transform",
]


# ---------------------------------------------------------------------------
# IoU + coding
# ---------------------------------------------------------------------------

def _area(boxes):
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.maximum(boxes[..., 3] - boxes[..., 1], 0)


def iou_similarity(boxes1, boxes2):
    """Pairwise IoU, (N, 4) x (M, 4) -> (N, M).
    reference: operators/detection/iou_similarity_op.cc"""
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(boxes1)[:, None] + _area(boxes2)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_coder(prior_boxes, prior_variances, target, *,
              code_type: str = "encode_center_size",
              box_normalized: bool = True):
    """Encode boxes against priors (or decode deltas back to boxes).
    reference: operators/detection/box_coder_op.cc — center-size coding.

    encode: target (N, 4) gt boxes, priors (M, 4) -> (N, M, 4) deltas
    decode: target (N, M, 4) (or (M, 4)) deltas -> boxes
    """
    pv = jnp.asarray(prior_variances)
    norm = 0.0 if box_normalized else 1.0
    pw = prior_boxes[:, 2] - prior_boxes[:, 0] + norm
    ph = prior_boxes[:, 3] - prior_boxes[:, 1] + norm
    pcx = prior_boxes[:, 0] + pw * 0.5
    pcy = prior_boxes[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + norm
        th = target[:, 3] - target[:, 1] + norm
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        return out / pv if pv.ndim <= 1 else out / pv[None, :, :]
    enforce(code_type == "decode_center_size",
            "unknown code_type %s", code_type)
    deltas = target if target.ndim == 3 else target[None]
    d = deltas * (pv if pv.ndim <= 1 else pv[None])
    cx = d[..., 0] * pw + pcx
    cy = d[..., 1] * ph + pcy
    w = jnp.exp(d[..., 2]) * pw
    h = jnp.exp(d[..., 3]) * ph
    boxes = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                       cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)
    return boxes if target.ndim == 3 else boxes[0]


def box_clip(boxes, im_shape):
    """Clip boxes into [0, w-1] x [0, h-1].
    reference: operators/detection/box_clip_op.cc"""
    h, w = im_shape[0], im_shape[1]
    x1 = jnp.clip(boxes[..., 0], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3], 0, h - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def polygon_box_transform(x):
    """(B, 8, H, W) quad offsets -> absolute coords (EAST-style).
    reference: operators/detection/polygon_box_transform_op.cc"""
    B, C, H, W = x.shape
    gy = jnp.arange(H).reshape(1, 1, H, 1)
    gx = jnp.arange(W).reshape(1, 1, 1, W)
    is_x = (jnp.arange(C) % 2 == 0).reshape(1, C, 1, 1)
    grid = jnp.where(is_x, 4 * gx, 4 * gy)
    return grid - x


# ---------------------------------------------------------------------------
# Anchors
# ---------------------------------------------------------------------------

def expand_aspect_ratios(aspect_ratios: Sequence[float],
                         flip: bool = False) -> list:
    """The SSD prior aspect-ratio expansion (dedup + optional reciprocal),
    shared by prior_box and nn.MultiBoxHead so conv channel counts always
    match generated prior counts."""
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    return ars


def prior_box_count(min_sizes: Sequence[float], max_sizes: Sequence[float],
                    aspect_ratios: Sequence[float],
                    flip: bool = False) -> int:
    """Number of priors per spatial cell that prior_box will generate."""
    ars = expand_aspect_ratios(aspect_ratios, flip)
    return len(min_sizes) * len(ars) + len(list(zip(min_sizes, max_sizes)))


def prior_box(feature_hw: Tuple[int, int], image_hw: Tuple[int, int],
              min_sizes: Sequence[float], max_sizes: Sequence[float] = (),
              aspect_ratios: Sequence[float] = (1.0,), *,
              variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
              flip: bool = False, clip: bool = False,
              step: Tuple[float, float] = (0.0, 0.0),
              offset: float = 0.5):
    """SSD prior boxes for one feature map -> ((H, W, A, 4) boxes, vars).
    reference: operators/detection/prior_box_op.cc"""
    H, W = feature_hw
    img_h, img_w = image_hw
    step_h = step[0] or img_h / H
    step_w = step[1] or img_w / W
    ars = expand_aspect_ratios(aspect_ratios, flip)
    whs = []
    for ms in min_sizes:
        for ar in ars:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
    for ms, mx in zip(min_sizes, max_sizes):
        whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
    wh = jnp.asarray(whs, jnp.float32)  # (A, 2)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # (H, W)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]  # (H, W, 1, 2)
    half = wh[None, None] / 2.0
    boxes = jnp.concatenate([c - half, c + half], axis=-1)
    boxes = boxes / jnp.asarray([img_w, img_h, img_w, img_h], jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return boxes, var


def density_prior_box(feature_hw, image_hw, fixed_sizes, fixed_ratios,
                      densities, *, variances=(0.1, 0.1, 0.2, 0.2),
                      offset: float = 0.5, clip: bool = False,
                      step=(0.0, 0.0)):
    """Densified priors (multiple shifted centers per cell).
    reference: operators/detection/density_prior_box_op.cc"""
    H, W = feature_hw
    img_h, img_w = image_hw
    step_h = step[0] or img_h / H
    step_w = step[1] or img_w / W
    all_boxes = []
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    for size, density in zip(fixed_sizes, densities):
        shift = step_w / density
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            for di in range(density):
                for dj in range(density):
                    ccx = cxg - step_w / 2.0 + shift / 2.0 + dj * shift
                    ccy = cyg - step_h / 2.0 + shift / 2.0 + di * shift
                    b = jnp.stack([ccx - bw / 2, ccy - bh / 2,
                                   ccx + bw / 2, ccy + bh / 2], -1)
                    all_boxes.append(b)
    boxes = jnp.stack(all_boxes, axis=2)  # (H, W, A, 4)
    boxes = boxes / jnp.asarray([img_w, img_h, img_w, img_h], jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return boxes, var


def anchor_generator(feature_hw, anchor_sizes, aspect_ratios, stride, *,
                     variances=(0.1, 0.1, 0.2, 0.2), offset: float = 0.5):
    """RPN anchors -> ((H, W, A, 4), vars), absolute pixel coords.
    reference: operators/detection/anchor_generator_op.cc"""
    H, W = feature_hw
    whs = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            area = float(s) * float(s)
            w = (area / ar) ** 0.5
            whs.append((w, w * ar))
    wh = jnp.asarray(whs, jnp.float32)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]
    half = wh[None, None] / 2.0
    anchors = jnp.concatenate([c - half, c + half], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    return anchors, var


def yolo_box(x, img_size, anchors: Sequence[int], class_num: int,
             conf_thresh: float, downsample_ratio: int):
    """Decode one YOLOv3 head: (B, A*(5+C), H, W) -> boxes (B, H*W*A, 4),
    scores (B, H*W*A, C). reference: operators/detection/yolo_box_op.cc"""
    B, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    x = x.reshape(B, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32).reshape(1, 1, 1, W)
    gy = jnp.arange(H, dtype=jnp.float32).reshape(1, 1, H, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + gx) / W
    by = (jax.nn.sigmoid(x[:, :, 1]) + gy) / H
    input_w = downsample_ratio * W
    input_h = downsample_ratio * H
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img_h = img_size[..., 0].reshape(B, 1, 1, 1).astype(jnp.float32)
    img_w = img_size[..., 1].reshape(B, 1, 1, 1).astype(jnp.float32)
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # (B, A, H, W, 4)
    keep = conf > conf_thresh
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    probs = jnp.where(keep[:, :, None], probs, 0.0)  # (B, A, C, H, W)
    # flatten both in (h, w, a) order so scores[b, i] matches boxes[b, i]
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(B, H * W * A, 4)
    scores = probs.transpose(0, 3, 4, 1, 2).reshape(B, H * W * A, class_num)
    return boxes, scores


# ---------------------------------------------------------------------------
# NMS family — fixed-capacity outputs
# ---------------------------------------------------------------------------

def nms(boxes, scores, *, iou_threshold: float = 0.3,
        score_threshold: float = -jnp.inf, max_out: int = 100):
    """Greedy hard-NMS. Returns (indices (max_out,), valid_mask (max_out,)).

    TPU-native contract for the reference's variable-output NMS
    (reference: operators/detection/multiclass_nms_op.cc NMSFast): output
    capacity is static; invalid slots have index 0 and mask False. O(K*N)
    masked iterations instead of data-dependent loops.
    """
    n = boxes.shape[0]
    k = min(max_out, n)
    iou = iou_similarity(boxes, boxes)
    live = scores > score_threshold

    def body(carry, _):
        live, = carry
        masked = jnp.where(live, scores, -jnp.inf)
        i = jnp.argmax(masked)
        ok = masked[i] > -jnp.inf
        # kill the chosen box and everything overlapping it
        suppress = iou[i] >= iou_threshold
        live = live & ~suppress & (jnp.arange(n) != i)
        return (live,), (jnp.where(ok, i, 0), ok)

    (_, ), (idx, ok) = lax.scan(body, (live,), None, length=k)
    if k < max_out:
        idx = jnp.pad(idx, (0, max_out - k))
        ok = jnp.pad(ok, (0, max_out - k))
    return idx, ok


def multiclass_nms(boxes, scores, *, score_threshold: float = 0.01,
                   nms_threshold: float = 0.3, nms_top_k: int = 64,
                   keep_top_k: int = 100, background_label: int = 0):
    """Per-class NMS then global top-k, one image.

    boxes (N, 4), scores (C, N) -> (keep_top_k, 6) [label, score, x1, y1,
    x2, y2] + valid mask. reference: detection/multiclass_nms_op.cc.
    """
    C, N = scores.shape

    def per_class(c_scores):
        top = min(nms_top_k, N)
        s, order = lax.top_k(c_scores, top)
        idx, ok = nms(boxes[order], s, iou_threshold=nms_threshold,
                      score_threshold=score_threshold, max_out=top)
        return order[idx], s[idx], ok

    cls_idx, cls_score, cls_ok = jax.vmap(per_class)(scores)  # (C, top)
    labels = jnp.broadcast_to(jnp.arange(C)[:, None], cls_idx.shape)
    is_bg = labels == background_label
    flat_score = jnp.where(cls_ok & ~is_bg, cls_score, -jnp.inf).reshape(-1)
    k = min(keep_top_k, flat_score.shape[0])
    best, flat_i = lax.top_k(flat_score, k)
    sel_box = boxes[cls_idx.reshape(-1)[flat_i]]
    sel_label = labels.reshape(-1)[flat_i].astype(jnp.float32)
    valid = best > -jnp.inf
    out = jnp.concatenate([sel_label[:, None],
                           jnp.where(valid, best, 0.0)[:, None],
                           jnp.where(valid[:, None], sel_box, 0.0)], axis=1)
    if k < keep_top_k:
        out = jnp.pad(out, ((0, keep_top_k - k), (0, 0)))
        valid = jnp.pad(valid, (0, keep_top_k - k))
    return out, valid


def matrix_nms(boxes, scores, *, score_threshold: float = 0.01,
               post_threshold: float = 0.0, keep_top_k: int = 100,
               use_gaussian: bool = False, gaussian_sigma: float = 2.0):
    """Parallel (non-iterative) NMS via pairwise decay — one matmul-friendly
    pass, no sequential loop: the NMS variant that actually fits the TPU
    execution model. scores (C, N)."""
    C, N = scores.shape
    iou = iou_similarity(boxes, boxes)

    def per_class(s):
        order = jnp.argsort(-s)
        s_sorted = s[order]
        iou_s = iou[order][:, order]
        upper = jnp.triu(iou_s, k=1)  # upper[i, j]: iou of box j with
        max_iou = jnp.max(upper, axis=0)  # higher-scored box i
        # decay_j = min_i f(iou_ij) / f(max_iou_i): compensation is per
        # SUPPRESSING row i (its own worst overlap), not per column
        if use_gaussian:
            decay = jnp.min(jnp.exp(-(upper ** 2 - max_iou[:, None] ** 2)
                                    / gaussian_sigma), axis=0)
        else:
            comp = (1 - upper) / jnp.maximum(1 - max_iou[:, None], 1e-10)
            decay = jnp.min(jnp.where(upper > 0, comp, 1.0), axis=0)
        return s_sorted * jnp.minimum(decay, 1.0), order

    dec_scores, orders = jax.vmap(per_class)(scores)
    labels = jnp.broadcast_to(jnp.arange(C)[:, None], dec_scores.shape)
    flat = jnp.where(dec_scores > jnp.maximum(score_threshold,
                                              post_threshold),
                     dec_scores, -jnp.inf).reshape(-1)
    k = min(keep_top_k, flat.shape[0])
    best, fi = lax.top_k(flat, k)
    sel_box = boxes[orders.reshape(-1)[fi]]
    valid = best > -jnp.inf
    out = jnp.concatenate([labels.reshape(-1)[fi].astype(jnp.float32)[:, None],
                           jnp.where(valid, best, 0.0)[:, None],
                           jnp.where(valid[:, None], sel_box, 0.0)], axis=1)
    if k < keep_top_k:
        out = jnp.pad(out, ((0, keep_top_k - k), (0, 0)))
        valid = jnp.pad(valid, (0, keep_top_k - k))
    return out, valid


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------

def roi_align(x, rois, *, output_size: Tuple[int, int],
              spatial_scale: float = 1.0, sampling_ratio: int = 2,
              aligned: bool = False):
    """RoIAlign: x (C, H, W), rois (R, 4) -> (R, C, oh, ow). Bilinear
    sampling at sampling_ratio^2 points per output bin, averaged — a pure
    gather+interp formulation (reference: detection/roi_align_op.cc's
    PreCalc bilinear weights, as one vectorized einsum-free computation).
    """
    C, H, W = x.shape
    oh, ow = output_size
    s = max(sampling_ratio, 1)
    off = 0.5 if aligned else 0.0
    x1 = rois[:, 0] * spatial_scale - off
    y1 = rois[:, 1] * spatial_scale - off
    x2 = rois[:, 2] * spatial_scale - off
    y2 = rois[:, 3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    bw = rw / ow
    bh = rh / oh
    # sample grid: (R, oh*s) y coords, (R, ow*s) x coords
    iy = (jnp.arange(oh * s) // s)
    fy = (jnp.arange(oh * s) % s + 0.5) / s
    ys = y1[:, None] + (iy[None, :] + fy[None, :]) * bh[:, None]
    ix = (jnp.arange(ow * s) // s)
    fx = (jnp.arange(ow * s) % s + 0.5) / s
    xs = x1[:, None] + (ix[None, :] + fx[None, :]) * bw[:, None]

    def bilinear(grid_y, grid_x):
        y0 = jnp.clip(jnp.floor(grid_y), 0, H - 1)
        x0 = jnp.clip(jnp.floor(grid_x), 0, W - 1)
        y1c = jnp.clip(y0 + 1, 0, H - 1)
        x1c = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(grid_y, 0, H - 1) - y0
        wx = jnp.clip(grid_x, 0, W - 1) - x0
        y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1c, x1c))
        # advanced indexing: (C, R, Sy, Sx) per corner
        v00 = x[:, y0i[:, :, None], x0i[:, None, :]]
        v01 = x[:, y0i[:, :, None], x1i[:, None, :]]
        v10 = x[:, y1i[:, :, None], x0i[:, None, :]]
        v11 = x[:, y1i[:, :, None], x1i[:, None, :]]
        wy_ = wy[None, :, :, None]
        wx_ = wx[None, :, None, :]
        val = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_ +
               v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        # reference semantics (roi_align_op.cc): samples beyond one pixel
        # outside the map contribute 0, not edge-extended values
        oky = (grid_y >= -1.0) & (grid_y <= H)   # (R, Sy)
        okx = (grid_x >= -1.0) & (grid_x <= W)   # (R, Sx)
        mask = (oky[:, :, None] & okx[:, None, :])[None]  # (1, R, Sy, Sx)
        return jnp.where(mask, val, 0.0)

    samples = bilinear(ys, xs)  # (C, R, oh*s, ow*s)
    samples = samples.reshape(C, -1, oh, s, ow, s).mean(axis=(3, 5))
    return samples.transpose(1, 0, 2, 3)


def roi_pool(x, rois, *, output_size: Tuple[int, int],
             spatial_scale: float = 1.0):
    """RoI max-pool with quantized bins (reference:
    detection/roi_pool_op.cc) — exact: each bin takes a masked max over the
    full rows/columns it spans (two separable (bin, axis) masks), scanned
    over RoIs so memory stays (C, oh, H, W)-bounded. Empty bins yield 0."""
    C, H, W = x.shape
    oh, ow = output_size
    rows = jnp.arange(H)
    cols = jnp.arange(W)

    def one(roi):
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        bh = jnp.maximum(y2 - y1 + 1, 1.0) / oh
        bw = jnp.maximum(x2 - x1 + 1, 1.0) / ow
        i = jnp.arange(oh, dtype=jnp.float32)
        j = jnp.arange(ow, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(i * bh) + y1, 0, H)
        hend = jnp.clip(jnp.ceil((i + 1) * bh) + y1, 0, H)
        wstart = jnp.clip(jnp.floor(j * bw) + x1, 0, W)
        wend = jnp.clip(jnp.ceil((j + 1) * bw) + x1, 0, W)
        my = (rows[None, :] >= hstart[:, None]) & \
            (rows[None, :] < hend[:, None])        # (oh, H)
        mx = (cols[None, :] >= wstart[:, None]) & \
            (cols[None, :] < wend[:, None])        # (ow, W)
        neg = jnp.finfo(x.dtype).min
        tmp = jnp.max(jnp.where(my[None, :, :, None], x[:, None, :, :], neg),
                      axis=2)                      # (C, oh, W)
        out = jnp.max(jnp.where(mx[None, None, :, :], tmp[:, :, None, :],
                                neg), axis=3)      # (C, oh, ow)
        return jnp.where(out == neg, 0.0, out)

    return lax.map(one, rois)


# ---------------------------------------------------------------------------
# Proposals + matching
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, anchors, variances, im_shape, *,
                       pre_nms_top_n: int = 6000, post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.7, min_size: float = 0.0):
    """RPN proposal generation, one image: objectness (A,), deltas (A, 4),
    anchors (A, 4) -> (post_nms_top_n, 4) + mask.
    reference: detection/generate_proposals_op.cc"""
    A = scores.shape[0]
    k = min(pre_nms_top_n, A)
    top_scores, order = lax.top_k(scores, k)
    d = bbox_deltas[order] * variances[order]
    boxes = box_coder(anchors[order], jnp.ones((k, 4), jnp.float32),
                      d, code_type="decode_center_size")
    boxes = box_clip(boxes, im_shape)
    w = boxes[:, 2] - boxes[:, 0]
    h = boxes[:, 3] - boxes[:, 1]
    ok_size = (w >= min_size) & (h >= min_size)
    sc = jnp.where(ok_size, top_scores, -jnp.inf)
    idx, ok = nms(boxes, sc, iou_threshold=nms_thresh,
                  max_out=post_nms_top_n)
    return jnp.where(ok[:, None], boxes[idx], 0.0), ok


def bipartite_match(sim):
    """Greedy bipartite matching (N rows to M cols, N<=M assumed by caller).

    Returns (match_indices (M,), match_dist (M,)): for each column, the row
    it matched or -1. reference: detection/bipartite_match_op.cc
    (BipartiteMatchFunctor greedy max path).
    """
    N, M = sim.shape
    steps = min(N, M)

    def body(carry, _):
        s, col_match, col_dist = carry
        flat = jnp.argmax(s)
        i, j = flat // M, flat % M
        best = s[i, j]
        ok = best > -jnp.inf
        col_match = jnp.where(ok, col_match.at[j].set(i), col_match)
        col_dist = jnp.where(ok, col_dist.at[j].set(best), col_dist)
        s = jnp.where(ok, s.at[i, :].set(-jnp.inf).at[:, j].set(-jnp.inf), s)
        return (s, col_match, col_dist), None

    init = (jnp.where(sim > 0, sim, -jnp.inf),
            jnp.full((M,), -1, jnp.int32), jnp.zeros((M,), sim.dtype))
    (_, match, dist), _ = lax.scan(body, init, None, length=steps)
    return match, dist


def target_assign(gt, match_indices, *, mismatch_value=0.0):
    """Scatter matched gt rows to prediction slots: gt (N, K),
    match_indices (M,) -> out (M, K), weights (M,).
    reference: detection/target_assign_op.cc"""
    matched = match_indices >= 0
    safe = jnp.maximum(match_indices, 0)
    out = jnp.where(matched[:, None], gt[safe],
                    jnp.full_like(gt[safe], mismatch_value))
    return out, matched.astype(gt.dtype)


def distribute_fpn_proposals(rois, *, min_level: int = 2, max_level: int = 5,
                             refer_level: int = 4, refer_scale: int = 224):
    """FPN level routing: (R, 4) -> per-level boolean masks (L, R) +
    level index per roi. Static alternative to the reference's dynamic
    splits (detection/distribute_fpn_proposals_op.cc)."""
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    levels = jnp.arange(min_level, max_level + 1)
    masks = lvl[None, :] == levels[:, None]
    return masks, lvl


def collect_fpn_proposals(multi_rois, multi_scores, *, post_nms_top_n: int):
    """Concat per-level (rois, scores) and keep the global top-n.
    reference: detection/collect_fpn_proposals_op.cc"""
    rois = jnp.concatenate(multi_rois, axis=0)
    scores = jnp.concatenate(multi_scores, axis=0)
    k = min(post_nms_top_n, scores.shape[0])
    top, idx = lax.top_k(scores, k)
    return rois[idx], top


# ---------------------------------------------------------------------------
# SSD head: matching, loss, inference decode
# ---------------------------------------------------------------------------

def _encode_matched(prior_boxes, prior_variances, gt):
    """Center-size encode each prior's matched gt box (M, 4) -> (M, 4)
    deltas (the per-prior form of box_coder's pairwise encode)."""
    pw = prior_boxes[:, 2] - prior_boxes[:, 0]
    ph = prior_boxes[:, 3] - prior_boxes[:, 1]
    pcx = prior_boxes[:, 0] + pw * 0.5
    pcy = prior_boxes[:, 1] + ph * 0.5
    tw = gt[:, 2] - gt[:, 0]
    th = gt[:, 3] - gt[:, 1]
    tcx = gt[:, 0] + tw * 0.5
    tcy = gt[:, 1] + th * 0.5
    out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                     jnp.log(jnp.maximum(tw / pw, 1e-10)),
                     jnp.log(jnp.maximum(th / ph, 1e-10))], axis=-1)
    pv = jnp.asarray(prior_variances)
    return out / (pv if pv.ndim == 2 else pv[None, :])


def ssd_match(gt_boxes, gt_mask, prior_boxes, *,
              overlap_threshold: float = 0.5,
              match_type: str = "per_prediction"):
    """SSD matching for one image: bipartite (every gt claims its best
    prior) + optionally per-prediction (any prior with IoU above threshold
    matches its best gt). Padded gt slots (gt_mask False) never match.

    Returns (match_idx (M,) int32, matched (M,) bool).
    reference: operators/detection/bipartite_match_op.cc +
    layers/detection.py ssd_loss matching stage.
    """
    G = gt_boxes.shape[0]
    iou = iou_similarity(gt_boxes, prior_boxes)          # (G, M)
    iou = jnp.where(gt_mask[:, None], iou, -1.0)
    match_idx = jnp.argmax(iou, axis=0)                  # (M,)
    best_iou = jnp.max(iou, axis=0)
    matched = (best_iou > (overlap_threshold
                           if match_type == "per_prediction" else 1.1))
    # bipartite stage: greedy one-to-one, highest IoU pair first
    def body(carry, _):
        iou_live, midx, mok = carry
        flat = jnp.argmax(iou_live)
        g, m = flat // iou_live.shape[1], flat % iou_live.shape[1]
        ok = iou_live[g, m] > 0.0
        midx = jnp.where(ok & (jnp.arange(midx.shape[0]) == m), g, midx)
        mok = mok | (ok & (jnp.arange(mok.shape[0]) == m))
        iou_live = jnp.where(ok, iou_live.at[g, :].set(-1.0)
                             .at[:, m].set(-1.0), iou_live)
        return (iou_live, midx, mok), None

    (_, match_idx, matched), _ = lax.scan(
        body, (iou, match_idx, matched), None, length=G)
    return match_idx.astype(jnp.int32), matched


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, gt_mask=None, *,
             background_label: int = 0, overlap_threshold: float = 0.5,
             neg_pos_ratio: float = 3.0, loc_loss_weight: float = 1.0,
             conf_loss_weight: float = 1.0,
             match_type: str = "per_prediction",
             mining_type: str = "max_negative", normalize: bool = True):
    """SSD multibox loss (reference: python/paddle/fluid/layers/detection.py
    ssd_loss; ops mine_hard_examples/target_assign/bipartite_match).

    Ragged gt lists use the framework's padded+mask convention (SURVEY §5.7)
    instead of LoD: gt_box (N, G, 4), gt_label (N, G), gt_mask (N, G) bool.
    location (N, M, 4) deltas, confidence (N, M, C) logits, priors (M, 4).
    Returns per-image loss (N,), already hard-negative mined and normalized
    by matched count when ``normalize``.
    """
    from .loss import smooth_l1_loss, softmax_with_cross_entropy
    from .detection_extra import mine_hard_examples

    N, M, _ = location.shape
    if gt_mask is None:
        gt_mask = jnp.ones(gt_box.shape[:2], bool)
    if prior_box_var is None:
        prior_box_var = jnp.ones_like(prior_box)

    def one(loc, conf, gtb, gtl, gmask):
        midx, matched = ssd_match(gtb, gmask, prior_box,
                                  overlap_threshold=overlap_threshold,
                                  match_type=match_type)
        tgt_label = jnp.where(matched, gtl[midx], background_label)
        conf_loss = softmax_with_cross_entropy(conf, tgt_label)
        conf_loss = conf_loss.reshape(-1)                            # (M,)
        sel = mine_hard_examples(conf_loss[None],
                                 matched[None].astype(jnp.int32),
                                 neg_pos_ratio=neg_pos_ratio,
                                 mining_type=mining_type)[0]
        tgt_loc = _encode_matched(prior_box, prior_box_var, gtb[midx])
        loc_l = jnp.sum(smooth_l1_loss(loc, tgt_loc), axis=-1)
        total = (conf_loss_weight * jnp.sum(conf_loss * sel)
                 + loc_loss_weight * jnp.sum(loc_l * matched))
        if normalize:
            total = total / jnp.maximum(jnp.sum(matched.astype(total.dtype)),
                                        1.0)
        return total

    return jax.vmap(one)(location, confidence, gt_box, gt_label, gt_mask)


def detection_output(loc, scores, prior_box, prior_box_var=None, *,
                     background_label: int = 0,
                     nms_threshold: float = 0.3, nms_top_k: int = 400,
                     keep_top_k: int = 200, score_threshold: float = 0.01):
    """SSD inference decode: per-image box decode + softmax + multiclass
    NMS (reference: layers/detection.py detection_output →
    box_coder decode + multiclass_nms ops).

    loc (N, M, 4) deltas, scores (N, M, C) logits, priors (M, 4).
    Returns ((N, keep_top_k, 6) [label, score, x1, y1, x2, y2], valid mask).
    """
    if prior_box_var is None:
        prior_box_var = jnp.ones_like(prior_box)

    def one(loc_i, score_i):
        boxes = box_coder(prior_box, prior_box_var, loc_i[None],
                          code_type="decode_center_size")[0]      # (M, 4)
        probs = jax.nn.softmax(score_i, axis=-1).T                # (C, M)
        return multiclass_nms(boxes, probs,
                              score_threshold=score_threshold,
                              nms_threshold=nms_threshold,
                              nms_top_k=min(nms_top_k, loc.shape[1]),
                              keep_top_k=keep_top_k,
                              background_label=background_label)

    return jax.vmap(one)(loc, scores)
