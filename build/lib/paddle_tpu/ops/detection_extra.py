"""Detection-suite gap-fill vs Appendix A (reference:
paddle/fluid/operators/detection/{psroi_pool_op.cc,
roi_perspective_transform_op.cc, rpn_target_assign_op.cc,
mine_hard_examples_op.cc, box_decoder_and_assign_op.cc,
generate_proposal_labels_op.cc, yolov3_loss_op.cc} and
operators/detection_map_op.cc)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.enforce import enforce
from .detection import _area, iou_similarity


def psroi_pool(x, rois, *, output_size: Tuple[int, int],
               spatial_scale: float = 1.0):
    """Position-sensitive RoI pooling (reference: detection/
    psroi_pool_op.cc — R-FCN): input channels C = out_c * ph * pw; each
    output bin (i, j) average-pools its OWN channel group over its spatial
    cell. x: (N, C, H, W); rois: (R, 5) [batch_idx, x1, y1, x2, y2]."""
    ph, pw = output_size
    n, c, h, w = x.shape
    enforce(c % (ph * pw) == 0,
            "psroi_pool needs C %% (ph*pw) == 0, got C=%s bins=%s", c,
            ph * pw)
    out_c = c // (ph * pw)
    r = rois.shape[0]
    batch_idx = rois[:, 0].astype(jnp.int32)
    boxes = rois[:, 1:] * spatial_scale

    ys = jnp.arange(h, dtype=x.dtype)
    xs = jnp.arange(w, dtype=x.dtype)

    def one_roi(b, box):
        x1, y1, x2, y2 = box
        rh = jnp.maximum(y2 - y1, 1e-4) / ph
        rw = jnp.maximum(x2 - x1, 1e-4) / pw
        feat = x[b]  # (C, H, W)
        # bin index of every pixel, clipped into [0, ph)x[0, pw)
        bin_y = jnp.clip(jnp.floor((ys - y1) / rh), 0, ph - 1)
        bin_x = jnp.clip(jnp.floor((xs - x1) / rw), 0, pw - 1)
        in_y = ((ys >= y1) & (ys < y2)).astype(x.dtype)
        in_x = ((xs >= x1) & (xs < x2)).astype(x.dtype)
        outs = []
        for i in range(ph):
            for j in range(pw):
                mask = ((bin_y[:, None] == i) * (bin_x[None, :] == j)
                        * in_y[:, None] * in_x[None, :])
                group = feat[(i * pw + j) * out_c:(i * pw + j + 1) * out_c]
                s = jnp.sum(group * mask[None], axis=(1, 2))
                cnt = jnp.maximum(jnp.sum(mask), 1.0)
                outs.append(s / cnt)
        return jnp.stack(outs, axis=1).reshape(out_c, ph, pw)

    return jax.vmap(one_roi)(batch_idx, boxes)


def roi_perspective_transform(x, rois, *, transformed_height: int,
                              transformed_width: int,
                              spatial_scale: float = 1.0):
    """reference: detection/roi_perspective_transform_op.cc — warp each
    quadrilateral RoI to a fixed rectangle via its perspective transform,
    bilinear sampling. rois: (R, 9) [batch_idx, x1,y1,...,x4,y4] corners in
    (tl, tr, br, bl) order."""
    th, tw = transformed_height, transformed_width
    n, c, h, w = x.shape
    batch_idx = rois[:, 0].astype(jnp.int32)
    quads = rois[:, 1:].reshape(-1, 4, 2) * spatial_scale

    # normalized target grid
    gy, gx = jnp.meshgrid(jnp.linspace(0.0, 1.0, th),
                          jnp.linspace(0.0, 1.0, tw), indexing="ij")

    def one(b, quad):
        tl, tr, br, bl = quad[0], quad[1], quad[2], quad[3]
        # bilinear interpolation of the quad corners (projective warp
        # approximated by the bilinear surface — exact for parallelograms,
        # matches the sampling role; keeps the op jit-friendly)
        top = tl[None, None] + (tr - tl)[None, None] * gx[..., None]
        bot = bl[None, None] + (br - bl)[None, None] * gx[..., None]
        pts = top + (bot - top) * gy[..., None]  # (th, tw, 2) source coords
        sx = jnp.clip(pts[..., 0], 0, w - 1)
        sy = jnp.clip(pts[..., 1], 0, h - 1)
        # clamp so x0 < x1 always (keeps bilinear weights summing to 1 at
        # the exact right/bottom edge)
        x0 = jnp.clip(jnp.floor(sx), 0, w - 2).astype(jnp.int32)
        y0 = jnp.clip(jnp.floor(sy), 0, h - 2).astype(jnp.int32)
        x1 = x0 + 1
        y1 = y0 + 1
        wa = (x1 - sx) * (y1 - sy)
        wb = (sx - x0) * (y1 - sy)
        wc = (x1 - sx) * (sy - y0)
        wd = (sx - x0) * (sy - y0)
        feat = x[b]  # (C, H, W)
        gathered = (feat[:, y0, x0] * wa + feat[:, y0, x1] * wb +
                    feat[:, y1, x0] * wc + feat[:, y1, x1] * wd)
        return gathered

    return jax.vmap(one)(batch_idx, quads)


def rpn_target_assign(anchors, gt_boxes, *, rpn_batch_size_per_im: int = 256,
                      rpn_positive_overlap: float = 0.7,
                      rpn_negative_overlap: float = 0.3,
                      key: Optional[jax.Array] = None):
    """reference: detection/rpn_target_assign_op.cc — label anchors as
    fg (IoU > pos thresh or best-per-gt), bg (IoU < neg thresh), or ignore
    (-1). Static-shape form: returns per-anchor labels + matched gt index
    (subsampling is a masked score here; the reference randomly drops to
    the batch quota — do that host-side with `key` if needed)."""
    iou = iou_similarity(anchors, gt_boxes)  # (A, G)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    labels = -jnp.ones(anchors.shape[0], jnp.int32)
    labels = jnp.where(best_iou < rpn_negative_overlap, 0, labels)
    labels = jnp.where(best_iou >= rpn_positive_overlap, 1, labels)
    # every gt's best anchor is positive regardless of threshold
    best_anchor_per_gt = jnp.argmax(iou, axis=0)  # (G,)
    labels = labels.at[best_anchor_per_gt].set(1)
    return labels, best_gt


def mine_hard_examples(cls_loss, labels, *, neg_pos_ratio: float = 3.0,
                       mining_type: str = "max_negative"):
    """reference: detection/mine_hard_examples_op.cc — SSD hard-negative
    mining: keep all positives and the top-(ratio * #pos) highest-loss
    negatives. Returns a 0/1 selection mask (static shape)."""
    enforce(mining_type == "max_negative",
            "only max_negative mining is supported, got %s", mining_type)
    pos = labels > 0
    num_pos = jnp.sum(pos, axis=1, keepdims=True)
    num_neg = (num_pos * neg_pos_ratio).astype(jnp.int32)
    neg_loss = jnp.where(pos, -jnp.inf, cls_loss)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.argsort(order, axis=1)  # rank of each anchor by neg loss
    neg_sel = rank < num_neg
    return (pos | neg_sel).astype(jnp.float32)


def box_decoder_and_assign(prior_box, prior_var, target_box, box_score, *,
                           box_clip: float = 4.135):
    """reference: detection/box_decoder_and_assign_op.cc — decode per-class
    box deltas then pick each box's best-scoring class decode.
    target_box: (N, 4*C) deltas; box_score: (N, C)."""
    n, c4 = target_box.shape
    c = c4 // 4
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    px = prior_box[:, 0] + pw * 0.5
    py = prior_box[:, 1] + ph * 0.5
    t = target_box.reshape(n, c, 4) * prior_var.reshape(n, 1, 4)
    dx, dy, dw, dh = t[..., 0], t[..., 1], t[..., 2], t[..., 3]
    dw = jnp.clip(dw, -box_clip, box_clip)
    dh = jnp.clip(dh, -box_clip, box_clip)
    cx = px[:, None] + dx * pw[:, None]
    cy = py[:, None] + dy * ph[:, None]
    ow = jnp.exp(dw) * pw[:, None]
    oh = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([cx - ow / 2, cy - oh / 2, cx + ow / 2,
                         cy + oh / 2], axis=-1)  # (N, C, 4)
    best = jnp.argmax(box_score, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, axis=2), axis=1)[:, 0]
    return decoded, assigned


def generate_proposal_labels(rois, gt_boxes, gt_classes, *,
                             fg_thresh: float = 0.5,
                             bg_thresh_hi: float = 0.5,
                             bg_thresh_lo: float = 0.0):
    """reference: detection/generate_proposal_labels_op.cc — label RoIs
    against ground truth for the second stage: returns (labels (R,) int32
    with 0 = background, matched gt index (R,), fg mask)."""
    iou = iou_similarity(rois, gt_boxes)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    fg = best_iou >= fg_thresh
    bg = (best_iou < bg_thresh_hi) & (best_iou >= bg_thresh_lo)
    labels = jnp.where(fg, gt_classes[best_gt], 0)
    labels = jnp.where(fg | bg, labels, -1)  # neither: ignore
    return labels.astype(jnp.int32), best_gt, fg


def yolov3_loss(x, gt_box, gt_label, *, anchors: Sequence[int],
                anchor_mask: Sequence[int], class_num: int,
                ignore_thresh: float = 0.7, downsample_ratio: int = 32,
                use_label_smooth: bool = False):
    """reference: detection/yolov3_loss_op.cc — single-scale YOLOv3 loss:
    objectness + box (x,y sigmoid-BCE; w,h L2) + class BCE, with
    best-anchor responsibility assignment per gt.

    x: (N, A*(5+C), H, W) raw head output; gt_box: (N, B, 4) in [0,1]
    (cx, cy, w, h); gt_label: (N, B) int; padded gts have w==0."""
    n, _, h, w = x.shape
    a = len(anchor_mask)
    c = class_num
    x = x.reshape(n, a, 5 + c, h, w)
    pred_xy = jax.nn.sigmoid(x[:, :, 0:2])
    pred_wh = x[:, :, 2:4]
    pred_obj = x[:, :, 4]
    pred_cls = x[:, :, 5:]

    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    mask_anchors = all_anchors[jnp.asarray(anchor_mask)]
    input_w = w * downsample_ratio
    input_h = h * downsample_ratio

    # responsibility: for each gt, the best anchor (by IoU of (w,h) at the
    # origin) among ALL anchors; the loss counts it only if that anchor is
    # in this scale's mask
    gw = gt_box[..., 2] * input_w  # (N, B)
    gh = gt_box[..., 3] * input_h
    inter = (jnp.minimum(gw[..., None], all_anchors[:, 0]) *
             jnp.minimum(gh[..., None], all_anchors[:, 1]))
    union = (gw[..., None] * gh[..., None] +
             all_anchors[:, 0] * all_anchors[:, 1] - inter)
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)

    valid = gt_box[..., 2] > 1e-6  # (N, B)
    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)

    def bce(logit, target):
        return jax.nn.softplus(logit) - target * logit

    total = jnp.zeros((), x.dtype)
    obj_target = jnp.zeros((n, a, h, w))
    # scatter per-gt losses (B is small/static)
    bsz = gt_box.shape[1]
    for bi in range(bsz):
        vb = valid[:, bi].astype(x.dtype)  # (N,)
        in_mask = jnp.zeros((n,), jnp.int32)
        local_a = jnp.zeros((n,), jnp.int32)
        for k, am in enumerate(anchor_mask):
            hit = (best_anchor[:, bi] == am).astype(jnp.int32)
            in_mask = in_mask | hit
            local_a = jnp.where(hit == 1, k, local_a)
        sel = vb * in_mask.astype(x.dtype)  # (N,)
        bidx = jnp.arange(n)
        px = pred_xy[bidx, local_a, 0, gj[:, bi], gi[:, bi]]
        py = pred_xy[bidx, local_a, 1, gj[:, bi], gi[:, bi]]
        pw_ = pred_wh[bidx, local_a, 0, gj[:, bi], gi[:, bi]]
        ph_ = pred_wh[bidx, local_a, 1, gj[:, bi], gi[:, bi]]
        tx = gt_box[:, bi, 0] * w - gi[:, bi]
        ty = gt_box[:, bi, 1] * h - gj[:, bi]
        aw = mask_anchors[local_a, 0]
        ah = mask_anchors[local_a, 1]
        tw = jnp.log(jnp.maximum(gw[:, bi], 1e-9) / aw)
        th = jnp.log(jnp.maximum(gh[:, bi], 1e-9) / ah)
        scale = 2.0 - gt_box[:, bi, 2] * gt_box[:, bi, 3]
        box_loss = (jnp.abs(px - tx) ** 2 + jnp.abs(py - ty) ** 2 +
                    jnp.abs(pw_ - tw) ** 2 + jnp.abs(ph_ - th) ** 2) * scale
        po = pred_obj[bidx, local_a, gj[:, bi], gi[:, bi]]
        obj_loss = bce(po, jnp.ones_like(po))
        tgt = (jax.nn.one_hot(gt_label[:, bi], c) if not use_label_smooth
               else jax.nn.one_hot(gt_label[:, bi], c) * (1 - 1.0 / c)
               + 1.0 / (2 * c))
        pc = pred_cls[bidx, local_a, :, gj[:, bi], gi[:, bi]]
        cls_loss = jnp.sum(bce(pc, tgt), axis=-1)
        total = total + jnp.sum(sel * (box_loss + obj_loss + cls_loss))
        obj_target = obj_target.at[bidx, local_a, gj[:, bi], gi[:, bi]].max(
            sel)
    # negative objectness for unassigned cells
    neg_loss = bce(pred_obj, jnp.zeros_like(pred_obj)) * (1.0 - obj_target)
    total = total + jnp.sum(neg_loss)
    return total / n

def poly2mask(xy, h: int, w: int):
    """Rasterize one polygon to an (h, w) binary mask with the COCO
    frPoly algorithm (reference: operators/detection/mask_util.cc
    Poly2Mask, whose contract is pycocotools frPyObjects+decode — the
    reference's own test documents that): vertices upsampled x5, edges
    traced, x-boundary crossings downsampled, column-major parity fill.
    Boundary-inclusive, bit-exact with the reference's golden vectors."""
    import numpy as np

    pts = np.asarray(xy, np.float64).reshape(-1, 2)
    k = len(pts)
    scale = 5.0
    x = np.trunc(scale * pts[:, 0] + 0.5).astype(np.int64)
    y = np.trunc(scale * pts[:, 1] + 0.5).astype(np.int64)
    x = np.append(x, x[0])
    y = np.append(y, y[0])
    us, vs = [], []
    for j in range(k):
        xs, xe, ys, ye = int(x[j]), int(x[j + 1]), int(y[j]), int(y[j + 1])
        dx, dy = abs(xe - xs), abs(ys - ye)
        flip = (dx >= dy and xs > xe) or (dx < dy and ys > ye)
        if flip:
            xs, xe, ys, ye = xe, xs, ye, ys
        if dx >= dy:
            s = 0.0 if dx == 0 else (ye - ys) / dx
            d = np.arange(dx + 1)
            t = (dx - d) if flip else d
            us.append(t + xs)
            vs.append(np.trunc(ys + s * t + 0.5).astype(np.int64))
        else:
            s = 0.0 if dy == 0 else (xe - xs) / dy
            d = np.arange(dy + 1)
            t = (dy - d) if flip else d
            vs.append(t + ys)
            us.append(np.trunc(xs + s * t + 0.5).astype(np.int64))
    u = np.concatenate(us)
    v = np.concatenate(vs)
    # x-boundary crossings, downsampled back to pixel space
    bx, by = [], []
    for j in range(1, len(u)):
        if u[j] == u[j - 1]:
            continue
        xd = float(u[j] if u[j] < u[j - 1] else u[j] - 1)
        xd = (xd + 0.5) / scale - 0.5
        if np.floor(xd) != xd or xd < 0 or xd > w - 1:
            continue
        yd = float(min(v[j], v[j - 1]))
        yd = (yd + 0.5) / scale - 0.5
        yd = min(max(yd, 0.0), float(h))
        yd = np.ceil(yd)
        bx.append(int(xd))
        by.append(int(yd))
    # run-length fill over the column-major index space
    a = np.array([cx * h + cy for cx, cy in zip(bx, by)], np.int64)
    a = np.append(a, np.int64(h * w))
    a.sort()
    d = np.diff(np.concatenate([[np.int64(0)], a]))
    runs = [int(d[0])]
    j = 1
    while j < len(d):
        if d[j] > 0:
            runs.append(int(d[j]))
            j += 1
        else:
            j += 1
            if j < len(d):
                runs[-1] += int(d[j])
                j += 1
    msk = np.zeros(h * w, np.uint8)
    pos, val = 0, 0
    for run in runs:
        msk[pos:pos + run] = val
        pos += run
        val = 1 - val
    return msk.reshape(w, h).T


def polys_to_mask_wrt_box(polygons, box, mask_size: int):
    """Rasterize an instance's polygon list into a (mask_size, mask_size)
    grid over ``box`` (reference: mask_util.cc Polys2MaskWrtBox): map each
    polygon into box-relative pixel space, frPoly-rasterize, union."""
    import numpy as np

    box = np.asarray(box, np.float32)
    x0, y0 = box[0], box[1]
    w = np.maximum(box[2] - box[0], np.float32(1.0))
    h = np.maximum(box[3] - box[1], np.float32(1.0))
    mask = np.zeros((mask_size, mask_size), np.uint8)
    M = np.float32(mask_size)
    for poly in polygons:
        # the whole coordinate mapping runs in float32, like the
        # reference's C float math — only then may a pixel-boundary tie
        # quantize identically in poly2mask
        p = np.asarray(poly, np.float32).reshape(-1, 2)
        p = np.stack([(p[:, 0] - x0) * M / w,
                      (p[:, 1] - y0) * M / h], axis=1)
        mask |= poly2mask(p.reshape(-1), mask_size, mask_size)
    return mask


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         roi_labels, num_classes: int, resolution: int = 14):
    """Mask R-CNN mask targets (reference:
    operators/detection/generate_mask_labels_op.cc). Host-side numpy —
    ragged polygon lists are data prep, not device work, in this design
    (OP_COVERAGE.md).

    gt_segms: list (per gt) of polygon lists ([x0, y0, x1, y1, ...]).
    rois (R, 4), roi_labels (R,) class per roi (0 = background).
    Returns (mask_rois (P, 4), roi_has_mask (R,), mask_targets
    (P, num_classes * resolution**2) with -1 outside the roi's class
    section, P = number of foreground rois).
    """
    import numpy as np

    rois = np.asarray(rois, np.float64)
    roi_labels = np.asarray(roi_labels, np.int64)
    if len(gt_segms) == 0:  # no gt instances: no mask targets
        return (np.zeros((0, 4), np.float32),
                np.zeros(len(rois), np.int32),
                np.zeros((0, num_classes * resolution ** 2), np.float32))
    gt_boxes = []
    for segs in gt_segms:
        allpts = np.concatenate([np.asarray(s, np.float64).reshape(-1, 2)
                                 for s in segs], axis=0)
        gt_boxes.append([allpts[:, 0].min(), allpts[:, 1].min(),
                         allpts[:, 0].max(), allpts[:, 1].max()])
    gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
    fg = np.flatnonzero(roi_labels > 0)
    # pair each roi with its best-IoU gt in one vectorized numpy pass
    # (host-side data prep: no device round-trips in this loop)
    lt = np.maximum(rois[:, None, :2], gt_boxes[None, :, :2])
    rb = np.minimum(rois[:, None, 2:], gt_boxes[None, :, 2:])
    wh = np.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = lambda b: np.maximum(b[:, 2] - b[:, 0], 0) * \
        np.maximum(b[:, 3] - b[:, 1], 0)
    union = area(rois)[:, None] + area(gt_boxes)[None, :] - inter
    iou = np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)
    # crowd gts never provide mask targets; a roi only matches a gt of its
    # own class (the reference op's crowd filter + per-class matching)
    if is_crowd is not None:
        crowd = np.asarray(is_crowd, bool).reshape(-1)
        iou[:, crowd] = -1.0
    if gt_classes is not None:
        gcls = np.asarray(gt_classes, np.int64).reshape(-1)
        iou = np.where(gcls[None, :] == roi_labels[:, None], iou, -1.0)
    best_gt = iou.argmax(axis=1)
    has_match = iou.max(axis=1) > 0
    mask_rois, targets = [], []
    for r in fg:
        if not has_match[r]:
            continue  # fg roi with no same-class non-crowd gt: no target
        box = rois[r]
        g = int(best_gt[r])
        m = polys_to_mask_wrt_box(gt_segms[g], box, resolution)
        cls = int(roi_labels[r])
        tgt = np.full((num_classes, resolution * resolution), -1.0,
                      np.float32)
        tgt[cls] = m.reshape(-1).astype(np.float32)
        mask_rois.append(box)
        targets.append(tgt.reshape(-1))
    roi_has_mask = ((roi_labels > 0) & has_match).astype(np.int32)
    if not mask_rois:
        return (np.zeros((0, 4), np.float32), roi_has_mask,
                np.zeros((0, num_classes * resolution ** 2), np.float32))
    return (np.asarray(mask_rois, np.float32), roi_has_mask,
            np.stack(targets))
