"""Fused linear + softmax-cross-entropy over a chunked vocabulary.

The classifier head ``loss = CE(h @ W + b, labels)`` materializes a
``(B*T, V)`` logits tensor — at BERT scale (32x128 tokens, 30k vocab,
fp32) that is ~0.5 GB live twice (fwd activation + bwd softmax), pure HBM
traffic. This op computes the SAME loss by scanning vocabulary chunks:
per chunk one ``(N, C)`` logits tile feeds an online logsumexp (forward)
and the softmax-weighted matmuls (backward), so peak memory is
``O(N*C + D*C)`` instead of ``O(N*V)`` while every FLOP stays an MXU
matmul. This is the capability slot of the reference's hand-fused
CPU kernels (fused_embedding_seq_pool / jit kernel niche — SURVEY §2.2)
applied to the modern transformer hot spot.

Numerics match ops.loss.softmax_with_cross_entropy to fp32 roundoff; the
custom VJP recomputes chunk logits in the backward pass (rematerialize >
store — HBM is the bottleneck, MXU has headroom).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce


def _chunk_w(weight, bias, num_chunks, chunk):
    """(D, V) → (num_chunks, D, C) [+ bias (num_chunks, C)], zero-padded."""
    d, v = weight.shape
    pad = num_chunks * chunk - v
    wp = jnp.pad(weight, ((0, 0), (0, pad)))
    wc = jnp.transpose(wp.reshape(d, num_chunks, chunk), (1, 0, 2))
    if bias is None:
        bc = jnp.zeros((num_chunks, chunk), weight.dtype)
    else:
        bc = jnp.pad(bias, (0, pad)).reshape(num_chunks, chunk)
    return wc, bc


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def linear_cross_entropy(hidden, weight, bias, labels, chunk: int = 4096,
                         ignore_index: int = -100):
    """Per-row CE of ``hidden @ weight + bias`` against ``labels`` without
    materializing the full logits.

    hidden (N, D) float; weight (D, V); bias (V,) or None; labels (N,) int.
    Rows with ``labels == ignore_index`` contribute 0. Returns (N,) losses.
    """
    loss, _ = _lce_fwd_impl(hidden, weight, bias, labels, chunk,
                            ignore_index)
    return loss


def _lce_fwd_impl(hidden, weight, bias, labels, chunk, ignore_index):
    n, d = hidden.shape
    d2, v = weight.shape
    enforce(d == d2, "hidden dim %s != weight dim %s", d, d2)
    num_chunks = -(-v // chunk)
    wc, bc = _chunk_w(weight, bias, num_chunks, chunk)
    valid_cols = jnp.arange(num_chunks * chunk).reshape(num_chunks, chunk) < v

    def body(carry, xs):
        m, s = carry                       # running max (N,), sumexp (N,)
        w_c, b_c, mask_c = xs
        # bf16 inputs on the MXU, fp32 accumulation — MUST match t_logit's
        # precision or confident rows go negative (lse < target logit)
        logits = jnp.matmul(hidden, w_c,
                            preferred_element_type=jnp.float32) \
            + b_c.astype(jnp.float32)
        logits = jnp.where(mask_c[None, :], logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        return (m_new, s), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    (m, s), _ = lax.scan(body, (m0, s0), (wc, bc, valid_cols))
    lse = m + jnp.log(s)                   # (N,)

    safe = jnp.clip(labels, 0, v - 1)
    w_t = jnp.take(weight, safe, axis=1).T          # (N, D) target columns
    # fp32 products + fp32 sum, EXACTLY like the preferred_element_type
    # matmul tiles — a bf16-rounded product here would make lse < t_logit
    # (negative loss) on confident rows
    t_logit = jnp.sum(hidden.astype(jnp.float32)
                      * w_t.astype(jnp.float32), axis=1)
    if bias is not None:
        t_logit = t_logit + jnp.take(bias, safe).astype(jnp.float32)
    valid = labels != ignore_index
    loss = jnp.where(valid, lse - t_logit, 0.0)
    return loss, (hidden, weight, bias, labels, lse)


def _lce_bwd(chunk, ignore_index, res, g):
    hidden, weight, bias, labels, lse = res
    n, d = hidden.shape
    v = weight.shape[1]
    num_chunks = -(-v // chunk)
    wc, bc = _chunk_w(weight, bias, num_chunks, chunk)
    valid = (labels != ignore_index)
    gv = jnp.where(valid, g, 0.0)          # (N,) upstream per-row grads
    safe = jnp.clip(labels, 0, v - 1)

    def body(dh, xs):
        w_c, b_c, idx0 = xs
        logits = jnp.matmul(hidden, w_c,
                            preferred_element_type=jnp.float32) \
            + b_c.astype(jnp.float32)
        col = idx0 + jnp.arange(chunk)
        p = jnp.where(col[None, :] < v,
                      jnp.exp(logits - lse[:, None]), 0.0)  # softmax tile
        # dlogits = gv * (p - onehot)
        onehot = (col[None, :] == safe[:, None]).astype(p.dtype)
        dl = (gv[:, None] * (p - onehot)).astype(hidden.dtype)  # (N, C)
        dh = dh + (dl @ w_c.T).astype(jnp.float32)  # fp32 accumulator
        dw_c = hidden.T @ dl               # (D, C)
        db_c = jnp.sum(dl.astype(jnp.float32), axis=0)
        return dh, (dw_c, db_c)

    idx0s = jnp.arange(num_chunks) * chunk
    dh0 = jnp.zeros(hidden.shape, jnp.float32)
    dh, (dw_chunks, db_chunks) = lax.scan(body, dh0, (wc, bc, idx0s))
    dw = jnp.transpose(dw_chunks, (1, 0, 2)).reshape(d, num_chunks * chunk)
    dw = dw[:, :v].astype(weight.dtype)
    dh = dh.astype(hidden.dtype)
    db = (db_chunks.reshape(-1)[:v].astype(bias.dtype)
          if bias is not None else None)
    return dh, dw, db, None


linear_cross_entropy.defvjp(_lce_fwd_impl, _lce_bwd)


def mean_linear_cross_entropy(hidden, weight, bias, labels,
                              chunk: int = 4096, ignore_index: int = -100):
    """Mean over non-ignored rows (the training-loss form)."""
    losses = linear_cross_entropy(hidden, weight, bias, labels, chunk,
                                  ignore_index)
    count = jnp.maximum(jnp.sum((labels != ignore_index)
                                .astype(losses.dtype)), 1.0)
    return jnp.sum(losses) / count
