"""Loss ops.

Capability parity with the reference loss op set (reference:
paddle/fluid/operators/cross_entropy_op.cc, softmax_with_cross_entropy_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, huber_loss_op.cc, hinge_loss_op.cc,
log_loss_op.cc, smooth_l1_loss_op.cc, bpr_loss_op.cc, kldiv_loss_op.cc,
margin_rank_loss_op.cc, rank_loss_op.cc, label_smooth_op.cc,
teacher_student_sigmoid_loss_op.cc, npair/modified_huber ...).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.enforce import enforce


def _index_label(label, logits_ndim: int, axis: int):
    """Normalize a hard-label tensor to have a singleton class dim at `axis`."""
    axis = axis % logits_ndim
    label = jnp.asarray(label)
    if label.ndim == logits_ndim:
        # came in with a singleton class dim already (paddle's (N, 1) style)
        return label.astype(jnp.int32)
    return jnp.expand_dims(label.astype(jnp.int32), axis)


def cross_entropy(probs, label, soft_label: bool = False, axis: int = -1,
                  eps: float = 1e-8):
    """Takes probabilities (reference cross_entropy_op takes softmax output)."""
    logp = jnp.log(jnp.maximum(probs, eps))
    if soft_label:
        return -jnp.sum(label * logp, axis=axis, keepdims=True)
    picked = jnp.take_along_axis(logp, _index_label(label, logp.ndim, axis),
                                 axis=axis)
    return -picked


def softmax_with_cross_entropy(logits, label, soft_label: bool = False,
                               axis: int = -1, ignore_index: int = -100,
                               return_softmax: bool = False):
    """Fused, numerically-stable version (reference:
    operators/softmax_with_cross_entropy_op.cc)."""
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = _index_label(label, logp.ndim, axis)
        valid = lbl != ignore_index
        # Clamp before gathering so ignored (possibly negative) labels can't
        # index out of bounds; their loss is masked to 0 below.
        safe = jnp.clip(lbl, 0, logits.shape[axis] - 1)
        loss = -jnp.take_along_axis(logp, safe, axis=axis)
        loss = loss * valid.astype(loss.dtype)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index: int = -100,
                                      normalize: bool = False):
    """reference: operators/sigmoid_cross_entropy_with_logits_op.cc."""
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = (label != ignore_index).astype(loss.dtype)
    loss = loss * mask
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(mask), 1.0)
    return loss


def square_error_cost(input, label):
    """reference: python layers square_error_cost → elementwise_sub+square."""
    return jnp.square(input - label)


def smooth_l1_loss(x, y, sigma: float = 1.0, inside_weight=None,
                   outside_weight=None):
    """reference: operators/smooth_l1_loss_op.cc — returns per-row summed loss."""
    sigma2 = sigma * sigma
    d = x - y
    if inside_weight is not None:
        d = d * inside_weight
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * d * d, ad - 0.5 / sigma2)
    if outside_weight is not None:
        loss = loss * outside_weight
    return jnp.sum(loss, axis=tuple(range(1, loss.ndim)), keepdims=False)[..., None]


def huber_loss(x, y, delta: float = 1.0):
    """reference: operators/huber_loss_op.cc."""
    d = y - x
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))


def modified_huber_loss(x, y):
    """reference: operators/modified_huber_loss_op.cc — y in {0,1}."""
    s = 2.0 * y - 1.0
    z = x * s
    return jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), jnp.zeros_like(z)))


def hinge_loss(logits, label):
    """reference: operators/hinge_loss_op.cc — label in {0,1}."""
    s = 2.0 * label - 1.0
    return jnp.maximum(0.0, 1.0 - logits * s)


def log_loss(predicted, label, epsilon: float = 1e-4):
    """reference: operators/log_loss_op.cc."""
    return (-label * jnp.log(predicted + epsilon)
            - (1.0 - label) * jnp.log(1.0 - predicted + epsilon))


def bpr_loss(logits, label):
    """reference: operators/bpr_loss_op.cc — Bayesian personalized ranking."""
    n, d = logits.shape
    pos = jnp.take_along_axis(logits, label.reshape(n, 1).astype(jnp.int32), axis=1)
    diff = pos - logits  # (n, d)
    lse = jnp.log1p(jnp.exp(-diff))
    mask = jnp.ones((n, d)).at[jnp.arange(n), label.reshape(-1).astype(jnp.int32)].set(0.0)
    return jnp.sum(lse * mask, axis=1, keepdims=True) / (d - 1)


def kldiv_loss(x, target, reduction: str = "mean"):
    """reference: operators/kldiv_loss_op.cc — x is log-prob."""
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    loss = jnp.where(target > 0, loss, jnp.zeros_like(loss))
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return loss


def margin_rank_loss(label, left, right, margin: float = 0.0):
    """reference: operators/margin_rank_loss_op.cc."""
    return jnp.maximum(0.0, -label * (left - right) + margin)


def rank_loss(label, left, right):
    """reference: operators/rank_loss_op.cc — RankNet pairwise loss."""
    d = left - right
    return jnp.log1p(jnp.exp(d)) - label * d


def label_smooth(label, epsilon: float = 0.1, prior_dist=None):
    """reference: operators/label_smooth_op.cc."""
    k = label.shape[-1]
    if prior_dist is not None:
        return (1.0 - epsilon) * label + epsilon * prior_dist
    return (1.0 - epsilon) * label + epsilon / k


def teacher_student_sigmoid_loss(x, label, soft_max_up_bound: float = 15.0,
                                 soft_max_lower_bound: float = -15.0):
    """reference: operators/teacher_student_sigmoid_loss_op.cc."""
    xc = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    # label < -1: teacher part active with soft label = label + 2
    return jnp.where(
        label < -1.0,
        jnp.maximum(xc, 0.0) - xc * (label + 2.0) + jnp.log1p(jnp.exp(-jnp.abs(xc))),
        jnp.maximum(xc, 0.0) - xc * label + jnp.log1p(jnp.exp(-jnp.abs(xc))),
    )


def npair_loss(anchor, positive, labels, l2_reg: float = 0.002):
    """reference: python layers npair_loss."""
    batch = anchor.shape[0]
    sim = anchor @ positive.T
    lbl = labels.reshape(-1)
    target = (lbl[:, None] == lbl[None, :]).astype(sim.dtype)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.sum(target * logp, axis=1).mean()
    # reference layers/nn.py npair_loss: l2loss *= Beta (0.25) * l2_reg
    beta = 0.25
    reg = beta * l2_reg * (jnp.sum(jnp.square(anchor))
                           + jnp.sum(jnp.square(positive))) / batch
    return ce + reg


def mse_loss(input, label):
    return jnp.mean(jnp.square(input - label))


def sampled_softmax_with_cross_entropy(logits, label, num_samples: int,
                                       key: Optional[jax.Array] = None):
    """Capability analog of reference sample_logits + softmax (operators/
    sample_logits_op.cc): subsample negatives for huge softmax."""
    enforce(key is not None, "sampled softmax requires a PRNG key")
    n, v = logits.shape
    sampled = jax.random.randint(key, (n, num_samples), 0, v)
    lbl = label.reshape(n, 1).astype(jnp.int32)
    idx = jnp.concatenate([lbl, sampled], axis=1)  # (n, 1+S); col 0 = true class
    picked = jnp.take_along_axis(logits, idx, axis=1)
    return softmax_with_cross_entropy(picked, jnp.zeros((n,), jnp.int32))


def dice_loss(input, label, epsilon: float = 1e-5):
    """Dice coefficient loss (reference: layers/nn.py dice_loss): input
    (..., D) class probabilities, label (..., 1) or (...,) int ids."""
    if label.ndim == input.ndim:
        label = label[..., 0]
    one_hot = jax.nn.one_hot(label, input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * one_hot, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(one_hot,
                                                       axis=reduce_dims)
    dice = (2.0 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1.0 - dice)


# fluid name (layers/nn.py smooth_l1 — summed over the trailing dim)
def smooth_l1(x, y, inside_weight=None, outside_weight=None,
              sigma: float = 1.0):
    l = smooth_l1_loss(x, y, sigma=sigma, inside_weight=inside_weight,
                       outside_weight=outside_weight)
    return jnp.sum(l.reshape(l.shape[0], -1), axis=1, keepdims=True)
