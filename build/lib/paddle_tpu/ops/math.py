"""Math ops: activations, elementwise (with reference broadcast semantics),
matmul, scale/clip/cumsum etc.

Capability parity with the reference's activation family
(reference: paddle/fluid/operators/activation_op.h:1520-1559 functor table),
``elementwise/`` ops (reference: operators/elementwise/, axis-based broadcast)
and ``matmul_op`` / ``mul_op``. Everything lowers to XLA; gradients come from
JAX autodiff (the GradOpDescMaker role, reference:
framework/grad_op_desc_maker.h:36, is played by VJP rules).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce

# ---------------------------------------------------------------------------
# Activations — full reference functor-table coverage (activation_op.h:1520).
# ---------------------------------------------------------------------------

def sigmoid(x):
    return jax.nn.sigmoid(x)


def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


def exp(x):
    return jnp.exp(x)


def gelu(x, approximate: bool = False):
    return jax.nn.gelu(x, approximate=approximate)


def relu(x):
    return jax.nn.relu(x)


def tanh(x):
    return jnp.tanh(x)


def atan(x):
    return jnp.arctan(x)


def softshrink(x, lambda_: float = 0.5):
    return jnp.where(x > lambda_, x - lambda_,
                     jnp.where(x < -lambda_, x + lambda_, jnp.zeros_like(x)))


def sqrt(x):
    return jnp.sqrt(x)


def rsqrt(x):
    return lax.rsqrt(x)


def abs(x):  # noqa: A001 - matches reference op name
    return jnp.abs(x)


def ceil(x):
    return jnp.ceil(x)


def floor(x):
    return jnp.floor(x)


def cos(x):
    return jnp.cos(x)


def acos(x):
    return jnp.arccos(x)


def sin(x):
    return jnp.sin(x)


def asin(x):
    return jnp.arcsin(x)


def round(x):  # noqa: A001
    return jnp.round(x)


def reciprocal(x):
    return 1.0 / x


def log(x):
    return jnp.log(x)


def square(x):
    return jnp.square(x)


def brelu(x, t_min: float = 0.0, t_max: float = 24.0):
    return jnp.clip(x, t_min, t_max)


def soft_relu(x, threshold: float = 40.0):
    xc = jnp.clip(x, -threshold, threshold)
    return jnp.log1p(jnp.exp(xc))


def pow(x, factor: float = 1.0):  # noqa: A001
    return jnp.power(x, factor)


def stanh(x, scale_a: float = 0.67, scale_b: float = 1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def relu6(x, threshold: float = 6.0):
    return jnp.clip(x, 0.0, threshold)


def leaky_relu(x, alpha: float = 0.02):
    return jnp.where(x >= 0, x, alpha * x)


def tanh_shrink(x):
    return x - jnp.tanh(x)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha=alpha)


def hard_shrink(x, threshold: float = 0.5):
    return jnp.where((x > threshold) | (x < -threshold), x, jnp.zeros_like(x))


def hard_sigmoid(x, slope: float = 0.2, offset: float = 0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def swish(x, beta: float = 1.0):
    return x * jax.nn.sigmoid(beta * x)


def thresholded_relu(x, threshold: float = 1.0):
    return jnp.where(x > threshold, x, jnp.zeros_like(x))


def maxout(x, groups: int, axis: int = 1):
    """reference: operators/maxout_op.cc — max over channel groups."""
    shape = list(x.shape)
    c = shape[axis]
    enforce(c % groups == 0, "channels %s not divisible by groups %s", c, groups)
    new_shape = shape[:axis] + [c // groups, groups] + shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def prelu(x, alpha, mode: str = "all"):
    """reference: operators/prelu_op.cc — modes all/channel/element."""
    if mode == "channel":
        # alpha shaped (C,), x shaped (N, C, ...)
        extra = x.ndim - 2
        alpha = alpha.reshape((1, -1) + (1,) * extra)
    return jnp.where(x >= 0, x, alpha * x)


def selu(x, scale: float = 1.0507009873554805, alpha: float = 1.6732632423543772):
    return scale * jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0))


# ---------------------------------------------------------------------------
# Elementwise binary ops with the reference's axis-broadcast semantics
# (reference: operators/elementwise/elementwise_op.h — y's shape is matched to
# a contiguous run of x's dims starting at `axis`).
# ---------------------------------------------------------------------------

def _broadcast_y(x, y, axis: int):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.shape == y.shape or axis == -1:
        return y
    # Reshape y to align with x dims [axis, axis+y.ndim) then rely on numpy
    # broadcasting for the trailing 1s.
    enforce(axis >= 0 and axis + y.ndim <= x.ndim,
            "bad elementwise axis %s for shapes %s, %s", axis, x.shape, y.shape)
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def elementwise_add(x, y, axis: int = -1):
    return x + _broadcast_y(x, y, axis)


def elementwise_sub(x, y, axis: int = -1):
    return x - _broadcast_y(x, y, axis)


def elementwise_mul(x, y, axis: int = -1):
    return x * _broadcast_y(x, y, axis)


def elementwise_div(x, y, axis: int = -1):
    return x / _broadcast_y(x, y, axis)


def elementwise_min(x, y, axis: int = -1):
    return jnp.minimum(x, _broadcast_y(x, y, axis))


def elementwise_max(x, y, axis: int = -1):
    return jnp.maximum(x, _broadcast_y(x, y, axis))


def elementwise_pow(x, y, axis: int = -1):
    return jnp.power(x, _broadcast_y(x, y, axis))


def elementwise_mod(x, y, axis: int = -1):
    return jnp.mod(x, _broadcast_y(x, y, axis))


def elementwise_floordiv(x, y, axis: int = -1):
    return jnp.floor_divide(x, _broadcast_y(x, y, axis))


# ---------------------------------------------------------------------------
# Matmul family — the MXU path. Keep operands large & batched; prefer bf16
# compute via the active dtype policy (SURVEY §7: op set v0).
# ---------------------------------------------------------------------------

def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False,
           alpha: float = 1.0, precision=None):
    """reference: operators/matmul_op.cc — batched matmul with transposes."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    out = jnp.matmul(x, y, precision=precision)
    if alpha != 1.0:
        out = out * alpha
    return out


def mul(x, y, x_num_col_dims: int = 1, y_num_col_dims: int = 1):
    """reference: operators/mul_op.cc — flatten-to-2D matmul."""
    import math as _math

    xm = x.reshape((_math.prod(x.shape[:x_num_col_dims]), -1)) if x.ndim > 2 else x
    ym = y.reshape((_math.prod(y.shape[:y_num_col_dims]), -1)) if y.ndim > 2 else y
    return jnp.matmul(xm, ym)


def bilinear_tensor_product(x, y, weight, bias=None):
    """reference: operators/bilinear_tensor_product_op.cc.
    out[b, k] = x[b] @ W[k] @ y[b] (+ bias)."""
    out = jnp.einsum("bi,kij,bj->bk", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Scalar/shape utility math ops.
# ---------------------------------------------------------------------------

def scale(x, scale: float = 1.0, bias: float = 0.0,  # noqa: A002
          bias_after_scale: bool = True):
    """reference: operators/scale_op.cc."""
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def clip(x, min: float, max: float):  # noqa: A002
    return jnp.clip(x, min, max)


def clip_by_norm(x, max_norm: float):
    """reference: operators/clip_by_norm_op.cc."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


def sign(x):
    return jnp.sign(x)


def cumsum(x, axis: Optional[int] = None, exclusive: bool = False,
           reverse: bool = False):
    """reference: operators/cumsum_op.cc."""
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


def increment(x, value: float = 1.0):
    return x + value


def l1_norm(x):
    return jnp.sum(jnp.abs(x))


def squared_l2_norm(x):
    return jnp.sum(jnp.square(x))


def squared_l2_distance(x, y):
    d = x - y
    return jnp.sum(jnp.square(d), axis=tuple(range(1, d.ndim))), d


def cos_sim(x, y, eps: float = 1e-12):
    """reference: operators/cos_sim_op.cc — row-wise cosine similarity."""
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    num = jnp.sum(x * y, axis=-1, keepdims=True)
    return num / jnp.maximum(xn * yn, eps)


def logsumexp(x, axis=None, keepdims: bool = False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)


def isfinite(x):
    """reference: operators/isfinite_op.cc — scalar all-finite check."""
    return jnp.all(jnp.isfinite(x))


def has_inf(x):
    """reference: operators/isfinite_op.cc (has_inf)."""
    return jnp.any(jnp.isinf(x))


def has_nan(x):
    """reference: operators/isfinite_op.cc (has_nan)."""
    return jnp.any(jnp.isnan(x))
