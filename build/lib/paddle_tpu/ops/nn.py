"""Neural-net ops: conv, pool, norms, softmax, dropout, embedding, interpolate.

Capability parity with the reference's dense NN op set (reference:
paddle/fluid/operators/conv_op.cc, batch_norm_op.cc, softmax_op.cc,
dropout_op.cc, lookup_table_op.cc, pool_op.cc, layer_norm_op.cc,
group_norm_op.cc, interpolate_op.cc ...). Data layout is NCHW to match the
reference's default; XLA's conv lowering handles layout internally (MXU tiling
is the compiler's job — SURVEY §7 design stance).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce

IntOrPair = Union[int, Sequence[int]]


def _pair(v: IntOrPair, n: int = 2) -> Tuple[int, ...]:
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    enforce(len(t) == n, "expected %s values, got %s", n, t)
    return t


# ---------------------------------------------------------------------------
# Convolutions (reference: operators/conv_op.* + conv_transpose_op.*)
# ---------------------------------------------------------------------------

def conv2d(x, weight, stride: IntOrPair = 1, padding: IntOrPair = 0,
           dilation: IntOrPair = 1, groups: int = 1,
           data_format: str = "NCHW"):
    """Conv with the reference's NCHW/OIHW default layout; pass
    ``data_format="NHWC"`` for the TPU-native channels-last path (weight
    stays OIHW at the API — it is transposed to HWIO internally, which XLA
    folds into the kernel constant; NHWC avoids the layout transposes TPU
    convs otherwise insert around NCHW activations)."""
    stride, dilation = _pair(stride), _pair(dilation)
    pad = _pair(padding)
    enforce(data_format in ("NCHW", "NHWC"),
            "conv2d data_format must be NCHW|NHWC, got %s", data_format)
    if data_format == "NHWC":
        return lax.conv_general_dilated(
            x, jnp.transpose(weight, (2, 3, 1, 0)),  # OIHW -> HWIO
            window_strides=stride,
            padding=[(pad[0], pad[0]), (pad[1], pad[1])],
            rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    return lax.conv_general_dilated(
        x, weight,
        window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dilation,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def depthwise_conv2d(x, weight, stride: IntOrPair = 1, padding: IntOrPair = 0,
                     dilation: IntOrPair = 1):
    """reference: operators/conv_op.cc depthwise_conv2d — groups == C_in."""
    return conv2d(x, weight, stride, padding, dilation, groups=x.shape[1])


def conv2d_transpose(x, weight, stride: IntOrPair = 1, padding: IntOrPair = 0,
                     dilation: IntOrPair = 1, groups: int = 1):
    """reference: operators/conv_transpose_op.cc. weight is IOHW
    (in_channels, out_channels/groups, kh, kw); output spatial size follows the
    reference formula (in-1)*stride - 2*pad + dilation*(k-1) + 1.

    Implemented as a fractionally-strided conv: lhs_dilation=stride, spatially
    flipped kernel, per-side pads dilation*(k-1) - pad.
    """
    stride, dilation = _pair(stride), _pair(dilation)
    pad = _pair(padding)
    kh, kw = weight.shape[2], weight.shape[3]
    pads = [(dilation[0] * (kh - 1) - pad[0],) * 2,
            (dilation[1] * (kw - 1) - pad[1],) * 2]

    def one_group(xg, wg):
        w = jnp.flip(wg, axis=(2, 3)).swapaxes(0, 1)  # IOHW -> OIHW, flipped
        return lax.conv_general_dilated(
            xg, w, window_strides=(1, 1), padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    if groups == 1:
        return one_group(x, weight)
    cin = x.shape[1]
    enforce(cin % groups == 0, "in channels %s not divisible by groups %s",
            cin, groups)
    xs = jnp.split(x, groups, axis=1)
    ws = jnp.split(weight, groups, axis=0)
    return jnp.concatenate([one_group(xg, wg) for xg, wg in zip(xs, ws)], axis=1)


def conv3d(x, weight, stride: IntOrPair = 1, padding: IntOrPair = 0,
           dilation: IntOrPair = 1, groups: int = 1):
    stride, dilation = _pair(stride, 3), _pair(dilation, 3)
    pad = _pair(padding, 3)
    return lax.conv_general_dilated(
        x, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilation,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )


# ---------------------------------------------------------------------------
# Pooling (reference: operators/pool_op.*)
# ---------------------------------------------------------------------------

def pool2d(x, kernel_size: IntOrPair, pool_type: str = "max",
           stride: Optional[IntOrPair] = None, padding: IntOrPair = 0,
           ceil_mode: bool = False, exclusive: bool = True,
           global_pooling: bool = False, data_format: str = "NCHW"):
    enforce(data_format in ("NCHW", "NHWC"),
            "pool2d data_format must be NCHW|NHWC, got %s", data_format)
    spatial = (2, 3) if data_format == "NCHW" else (1, 2)
    if global_pooling:
        kernel_size = (x.shape[spatial[0]], x.shape[spatial[1]])
        padding = 0
        stride = kernel_size
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _pair(padding)
    if data_format == "NCHW":
        dims = (1, 1) + k
        strides = (1, 1) + s
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    if ceil_mode:
        # extend right/bottom padding so the last partial window is included
        pads = list(pads)
        hw = (x.shape[spatial[0]], x.shape[spatial[1]])
        for i, (dim, kk, ss, pp) in enumerate(zip(hw, k, s, p)):
            out = -(-(dim + 2 * pp - kk) // ss) + 1
            need = (out - 1) * ss + kk - dim - 2 * pp
            pads[spatial[0] + i] = (pp, pp + max(0, need))
        pads = tuple(pads)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, dims, strides, pads)
    enforce(pool_type == "avg", "pool_type must be max|avg, got %s", pool_type)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    if exclusive:
        counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides, pads)
        return summed / counts
    return summed / (k[0] * k[1])


def adaptive_pool2d(x, output_size: IntOrPair, pool_type: str = "avg"):
    oh, ow = _pair(output_size)
    n, c, h, w = x.shape
    enforce(h % oh == 0 and w % ow == 0,
            "adaptive pool needs divisible sizes (%s,%s)->(%s,%s)", h, w, oh, ow)
    x = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if pool_type == "avg":
        return x.mean(axis=(3, 5))
    return x.max(axis=(3, 5))


# ---------------------------------------------------------------------------
# Normalization (reference: batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
# norm_op.cc, data_norm_op.cc)
# ---------------------------------------------------------------------------

def batch_norm(x, scale, bias, mean, variance, *, training: bool = False,
               momentum: float = 0.9, epsilon: float = 1e-5,
               data_layout: str = "NCHW"):
    """Returns (y, new_mean, new_var). Functional: running stats are inputs and
    outputs, not hidden state (reference batch_norm_op.cc mutates in place)."""
    axis = 1 if data_layout == "NCHW" else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(x.shape[axis] if i == axis else 1 for i in range(x.ndim))
    if training:
        batch_mean = jnp.mean(x, axis=reduce_axes)
        batch_var = jnp.var(x, axis=reduce_axes)
        new_mean = momentum * mean + (1 - momentum) * batch_mean
        new_var = momentum * variance + (1 - momentum) * batch_var
        use_mean, use_var = batch_mean, batch_var
    else:
        new_mean, new_var = mean, variance
        use_mean, use_var = mean, variance
    inv = lax.rsqrt(use_var + epsilon)
    y = (x - use_mean.reshape(bshape)) * inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    return y, new_mean, new_var


def layer_norm(x, scale=None, bias=None, *, begin_norm_axis: int = 1,
               epsilon: float = 1e-5):
    """reference: operators/layer_norm_op.cc — normalize over dims
    [begin_norm_axis, ndim)."""
    axes = tuple(range(begin_norm_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + epsilon)
    norm_shape = x.shape[begin_norm_axis:]
    if scale is not None:
        y = y * scale.reshape(norm_shape)
    if bias is not None:
        y = y + bias.reshape(norm_shape)
    return y


def group_norm(x, scale=None, bias=None, *, groups: int = 32,
               epsilon: float = 1e-5):
    """reference: operators/group_norm_op.cc (NCHW)."""
    n, c = x.shape[:2]
    enforce(c % groups == 0, "channels %s not divisible by groups %s", c, groups)
    orig = x.shape
    x = x.reshape(n, groups, c // groups, *orig[2:])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = ((x - mean) * lax.rsqrt(var + epsilon)).reshape(orig)
    bshape = (1, c) + (1,) * (len(orig) - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return y


def l2_normalize(x, axis: int = -1, epsilon: float = 1e-12):
    """reference: operators/norm_op.cc."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return x / jnp.maximum(norm, epsilon)


def rms_norm(x, scale=None, *, epsilon: float = 1e-6):
    """Modern-transformer norm (no reference analog; needed for model zoo)."""
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * lax.rsqrt(var + epsilon)
    if scale is not None:
        y = y * scale
    return y


def lrn(x, n: int = 5, k: float = 1.0, alpha: float = 1e-4, beta: float = 0.75):
    """reference: operators/lrn_op.cc — local response norm across channels."""
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    den = k + alpha * sum(pad[:, i:i + x.shape[1]] for i in range(n))
    return x / jnp.power(den, beta)


# ---------------------------------------------------------------------------
# Softmax & friends
# ---------------------------------------------------------------------------

def softmax(x, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


# ---------------------------------------------------------------------------
# Dropout & noise (functional: key in, reference seeds via op attr)
# ---------------------------------------------------------------------------

def dropout(x, p: float, key: Optional[jax.Array] = None, *,
            training: bool = True, mode: str = "upscale_in_train"):
    """reference: operators/dropout_op.cc (dropout_implementation attr)."""
    if not training or p == 0.0:
        if mode == "downgrade_in_infer" and not training:
            return x * (1.0 - p)
        return x
    enforce(key is not None, "dropout in training mode requires a PRNG key")
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


# ---------------------------------------------------------------------------
# Embedding / lookup (reference: operators/lookup_table_op.cc). Sparse-grad
# SelectedRows semantics are subsumed by XLA gather/scatter-add fusion.
# ---------------------------------------------------------------------------

def embedding(ids, table, padding_idx: Optional[int] = None):
    out = jnp.take(table, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def one_hot(ids, depth: int, dtype=jnp.float32):
    return jax.nn.one_hot(ids, depth, dtype=dtype)


# ---------------------------------------------------------------------------
# Resize / interpolate (reference: operators/interpolate_op.cc)
# ---------------------------------------------------------------------------

def interpolate(x, size: Sequence[int], method: str = "nearest"):
    """NCHW resize. method in {nearest, bilinear}."""
    methods = {"nearest": "nearest", "bilinear": "linear"}
    enforce(method in methods, "interpolate method must be one of %s, got %s",
            sorted(methods), method)
    n, c = x.shape[:2]
    out_shape = (n, c) + tuple(size)
    return jax.image.resize(x, out_shape, method=methods[method])


def pixel_shuffle(x, upscale_factor: int):
    """reference: operators/pixel_shuffle_op.cc."""
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, c // (r * r), h * r, w * r)


def pad2d(x, paddings: Sequence[int], mode: str = "constant", value: float = 0.0):
    """reference: operators/pad2d_op.cc — NCHW [top, bottom, left, right]."""
    t, b, l, r = paddings
    cfg = ((0, 0), (0, 0), (t, b), (l, r))
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    enforce(mode in ("reflect", "edge"),
            "pad2d mode must be constant|reflect|edge, got %s", mode)
    return jnp.pad(x, cfg, mode=mode)


def space_to_depth(x, blocksize: int):
    """reference: operators/space_to_depth_op.cc (NCHW)."""
    n, c, h, w = x.shape
    bs = blocksize
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * bs * bs, h // bs, w // bs)


def shuffle_channel(x, group: int):
    """reference: operators/shuffle_channel_op.cc."""
    n, c, h, w = x.shape
    x = x.reshape(n, group, c // group, h, w)
    return x.swapaxes(1, 2).reshape(n, c, h, w)


def grid_sampler(x, grid):
    """reference: operators/grid_sampler_op.cc — bilinear sample at normalized
    grid coords. x: (N,C,H,W); grid: (N,H',W',2) in [-1,1]."""
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1, wy1 = gx - x0, gy - y0
    wx0, wy0 = 1.0 - wx1, 1.0 - wy1

    def gather(yy, xx):
        yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        # batch-wise gather: (N, H', W') indices into (N, C, H, W)
        flat = x.reshape(n, c, h * w)
        idx = (yy * w + xx).reshape(n, 1, -1)
        out = jnp.take_along_axis(flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        return out.reshape(n, c, *gx.shape[1:])

    def inb(yy, xx):
        ok = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
        return ok.astype(x.dtype)[:, None]

    out = (gather(y0, x0) * (wy0 * wx0)[:, None] * inb(y0, x0)
           + gather(y0, x1) * (wy0 * wx1)[:, None] * inb(y0, x1)
           + gather(y1, x0) * (wy1 * wx0)[:, None] * inb(y1, x0)
           + gather(y1, x1) * (wy1 * wx1)[:, None] * inb(y1, x1))
    return out


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25):
    """reference: operators/temporal_shift_op.cc."""
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    # reference temporal_shift_op.h:60-64: channels < c1 read from t-1
    # (zero-padded), channels c1..c2 read from t+1 (zero-padded).
    prev = jnp.concatenate([jnp.zeros_like(x[:, :1, :c1]), x[:, :-1, :c1]], axis=1)
    nxt = jnp.concatenate([x[:, 1:, c1:c2], jnp.zeros_like(x[:, :1, c1:c2])], axis=1)
    keep = x[:, :, c2:]
    return jnp.concatenate([prev, nxt, keep], axis=2).reshape(nt, c, h, w)
