"""Additional NN ops — Appendix A gap-fill (reference:
paddle/fluid/operators/{pool_op.cc pool3d, pool_with_index_op.cc,
unpool_op.cc, spp_op.cc, affine_channel_op.cc, affine_grid_op.cc,
conv_transpose_op.cc conv3d/depthwise variants, data_norm_op.cc,
interpolate_op.cc bilinear/nearest, fsp_op.cc, similarity_focus_op.cc,
tree_conv (operators/tree_conv_op.cc), cvm_op.cc}).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce
from .nn import _pair, conv2d_transpose, interpolate


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def pool3d(x, kernel_size, pool_type: str = "max", stride=None, padding=0,
           global_pooling: bool = False):
    """reference: operators/pool_op.cc (3D path). x: (N, C, D, H, W)."""
    if global_pooling:
        kernel_size = x.shape[2:5]
        padding = 0
        stride = kernel_size
    k = _triple(kernel_size)
    s = _triple(stride) if stride is not None else k
    p = _triple(padding)
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0)) + tuple((pp, pp) for pp in p)
    if pool_type == "max":
        init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                else jnp.iinfo(x.dtype).min)
        return lax.reduce_window(x, init, lax.max, dims, strides, pads)
    enforce(pool_type == "avg", "pool_type must be max|avg, got %s",
            pool_type)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
    return summed / counts


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0):
    """reference: operators/pool_with_index_op.cc — max pool that also
    returns the flat (h*w) argmax index per window (consumed by unpool).
    x: (N, C, H, W) → (out, indices int32). Differentiable: the VJP
    scatters the output cotangent back to the argmax positions (the
    variadic reduce_window that computes indices has no JVP rule, so the
    gradient is supplied explicitly — exactly MaxPoolWithIndexGrad)."""
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _pair(padding)
    return _mpwi(x, k, s, p)


def _mpwi_impl(x, k, s, p):
    n, c, h, w = x.shape
    # index grid encoded as float payload alongside values
    idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    idx = jnp.broadcast_to(idx, x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    init = (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1.0, jnp.float32))
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    out, out_idx = lax.reduce_window((x, idx), init, reducer, dims, strides,
                                     pads)
    return out, out_idx.astype(jnp.int32)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _mpwi(x, k, s, p):
    return _mpwi_impl(x, k, s, p)


def _mpwi_fwd(x, k, s, p):
    out, idx = _mpwi_impl(x, k, s, p)
    return (out, idx), (idx, x)


def _mpwi_bwd(k, s, p, res, g):
    idx, x = res
    g_out, _ = g  # index cotangent is meaningless (integer output)
    gx = unpool(g_out.astype(x.dtype), idx, (x.shape[2], x.shape[3]))
    return (gx,)


_mpwi.defvjp(_mpwi_fwd, _mpwi_bwd)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0):
    """reference: pool_with_index_op.cc 3D variant. x: (N, C, D, H, W)."""
    k = _triple(kernel_size)
    s = _triple(stride) if stride is not None else k
    p = _triple(padding)
    n, c, d, h, w = x.shape
    idx = jnp.arange(d * h * w, dtype=jnp.float32).reshape(1, 1, d, h, w)
    idx = jnp.broadcast_to(idx, x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    init = (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1.0, jnp.float32))
    out, out_idx = lax.reduce_window(
        (x, idx), init, reducer, (1, 1) + k, (1, 1) + s,
        ((0, 0), (0, 0)) + tuple((pp, pp) for pp in p))
    return out, out_idx.astype(jnp.int32)


def unpool(x, indices, output_size: Tuple[int, int]):
    """reference: operators/unpool_op.cc — scatter pooled values back to
    their argmax positions. x, indices: (N, C, ph, pw); indices flat over
    output h*w."""
    n, c, ph, pw = x.shape
    oh, ow = output_size
    flat_out = jnp.zeros((n, c, oh * ow), x.dtype)
    flat_idx = indices.reshape(n, c, ph * pw)
    flat_val = x.reshape(n, c, ph * pw)
    out = jax.vmap(jax.vmap(
        lambda o, i, v: o.at[i].add(v)))(flat_out, flat_idx, flat_val)
    return out.reshape(n, c, oh, ow)


def spp(x, pyramid_height: int = 3, pool_type: str = "max"):
    """Spatial pyramid pooling (reference: operators/spp_op.cc): pool to
    1x1, 2x2, ..., concat flattened bins → (N, C * sum(4^l))."""
    n, c, h, w = x.shape
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        kh, kw = -(-h // bins), -(-w // bins)
        sh, sw = h // bins, w // bins
        if sh == 0 or sw == 0:
            enforce(False, "spp level %s too deep for input %sx%s", level,
                    h, w)
        from .nn import pool2d

        pooled = pool2d(x, (kh, kw), pool_type, stride=(sh, sw),
                        padding=0, ceil_mode=True)
        pooled = pooled[:, :, :bins, :bins]
        outs.append(pooled.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


def affine_channel(x, scale, bias, data_layout: str = "NCHW"):
    """reference: operators/affine_channel_op.cc — per-channel y=x*s+b
    (BN-fold inference form)."""
    axis = 1 if data_layout == "NCHW" else x.ndim - 1
    shape = tuple(x.shape[axis] if i == axis else 1 for i in range(x.ndim))
    return x * scale.reshape(shape) + bias.reshape(shape)


def affine_grid(theta, out_shape: Sequence[int]):
    """reference: operators/affine_grid_op.cc — sampling grid from 2x3
    affine matrices (pairs with grid_sampler). theta: (N, 2, 3);
    out_shape: (N, C, H, W) → grid (N, H, W, 2) in [-1, 1] coords."""
    n, _, h, w = out_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    base = jnp.broadcast_to(base, (n, h * w, 3))
    grid = jnp.einsum("nhk,nck->nhc", base, theta)  # (N, H*W, 2)
    return grid.reshape(n, h, w, 2)


def conv3d_transpose(x, weight, stride=1, padding=0, bias=None):
    """reference: operators/conv_transpose_op.cc 3D. x: (N, Cin, D, H, W);
    weight: (Cin, Cout, kd, kh, kw). out = (in-1)*s + k - 2p (the
    reference formula; lax explicit pads are shifted by k-1)."""
    s = _triple(stride)
    p = _triple(padding)
    k = weight.shape[2:]
    lax_pad = tuple((kk - 1 - pp, kk - 1 - pp) for kk, pp in zip(k, p))
    out = lax.conv_transpose(
        x, weight, strides=s, padding=lax_pad,
        dimension_numbers=("NCDHW", "IODHW", "NCDHW"))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    return out


def depthwise_conv2d_transpose(x, weight, stride=1, padding=0, bias=None):
    """reference: conv_transpose_op.cc depthwise variant. weight:
    (C, 1, kh, kw) — per-channel transpose conv."""
    s = _pair(stride)
    p = _pair(padding)
    out = _dw_transpose(x, weight, s, p)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _dw_transpose(x, weight, s, p):
    # grouped transpose conv: run each channel independently via vmap over
    # channel groups (C small convs fuse fine under XLA)
    n, c, h, w = x.shape
    k = weight.shape[2:]
    lax_pad = tuple((kk - 1 - pp, kk - 1 - pp) for kk, pp in zip(k, p))

    def one(chan_x, chan_w):
        return lax.conv_transpose(
            chan_x[:, None], chan_w[None, None],
            strides=s, padding=lax_pad,
            dimension_numbers=("NCHW", "IOHW", "NCHW"))[:, 0]

    out = jax.vmap(one, in_axes=(1, 0), out_axes=1)(x, weight[:, 0])
    return out


def data_norm(x, batch_size, batch_sum, batch_square_sum,
              epsilon: float = 1e-4):
    """reference: operators/data_norm_op.cc — CTR feature normalization
    from accumulated (count, sum, sum-of-squares) statistics; unlike BN
    there is no scale/bias and stats accumulate over the whole history."""
    mean = batch_sum / batch_size
    var = batch_square_sum / batch_size - mean * mean
    return (x - mean) / jnp.sqrt(var + epsilon)


def bilinear_interp(x, out_size: Sequence[int]):
    """reference: operators/interpolate_op.cc bilinear_interp."""
    return interpolate(x, tuple(out_size), method="bilinear")


def nearest_interp(x, out_size: Sequence[int]):
    """reference: operators/interpolate_op.cc nearest_interp."""
    return interpolate(x, tuple(out_size), method="nearest")


def fsp_matrix(x, y):
    """reference: operators/fsp_op.cc — flow-of-solution-procedure matrix
    for distillation: x (N, C1, H, W), y (N, C2, H, W) →
    (N, C1, C2) = x·y^T / (H*W)."""
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    xf = x.reshape(n, c1, h * w)
    yf = y.reshape(n, c2, h * w)
    return jnp.einsum("nch,ndh->ncd", xf, yf) / (h * w)


def similarity_focus(x, axis: int, indexes: Sequence[int]):
    """reference: operators/similarity_focus_op.cc — build a focus mask:
    for each selected slice along ``axis``, mark the (h, w) argmax positions
    per remaining dim, union over indexes. x: (N, C, H, W) → same-shape
    0/1 mask."""
    enforce(axis in (1, 2, 3), "axis must be 1|2|3, got %s", axis)
    n = x.shape[0]
    mask = jnp.zeros_like(x, dtype=jnp.bool_)
    for index in indexes:
        sl = jnp.take(x, index, axis=axis)  # (N, d1, d2)
        m1 = sl == jnp.max(sl, axis=1, keepdims=True)
        m2 = sl == jnp.max(sl, axis=2, keepdims=True)
        sel = (m1 | m2)
        sel = jnp.expand_dims(sel, axis)
        mask = mask | jnp.broadcast_to(sel, mask.shape)
    return mask.astype(x.dtype)


def cvm(x, use_cvm: bool = True):
    """reference: operators/cvm_op.cc — CTR show/click feature: input
    (N, D) whose first two columns are (show, click); with use_cvm the
    columns become (log(show+1), log(click+1) - log(show+1)), else they are
    dropped."""
    show = jnp.log(x[:, 0:1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - show
    if use_cvm:
        return jnp.concatenate([show, click, x[:, 2:]], axis=1)
    return x[:, 2:]


def tree_conv(nodes, edges, weight, max_depth: int = 2):
    """reference: operators/tree_conv_op.cc — tree-based convolution over a
    node-feature matrix with an adjacency (children) structure.

    nodes: (N, F); edges: (N, N) row-normalized adjacency (dense — the
    XLA-friendly form of the reference's edge list); weight: (max_depth+1,
    F, Fout). out[i] = Σ_d W_d · (A^d · nodes)[i]."""
    out = nodes @ weight[0]
    prop = nodes
    for d in range(1, max_depth + 1):
        prop = edges @ prop
        out = out + prop @ weight[d]
    return out


def adaptive_pool3d(x, output_size, pool_type: str = "avg"):
    """reference: operators/pool_op.cc adaptive path, 3D variant.
    x (N, C, D, H, W) -> (N, C, od, oh, ow); sizes must divide."""
    od, oh, ow = ((output_size,) * 3 if isinstance(output_size, int)
                  else tuple(output_size))
    n, c, d, h, w = x.shape
    enforce(d % od == 0 and h % oh == 0 and w % ow == 0,
            "adaptive pool needs divisible sizes (%s,%s,%s)->(%s,%s,%s)",
            d, h, w, od, oh, ow)
    x = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
    return x.mean(axis=(3, 5, 7)) if pool_type == "avg" \
        else x.max(axis=(3, 5, 7))


def spectral_norm(weight, u, v, *, dim: int = 0, power_iters: int = 1,
                  eps: float = 1e-12):
    """Functional spectral normalization (reference:
    operators/spectral_norm_op.cc). Returns (w / sigma, new_u, new_v);
    the nn.SpectralNorm layer owns the u/v buffers."""
    h = weight.shape[dim]
    wmat = jnp.moveaxis(weight, dim, 0).reshape(h, -1)
    for _ in range(power_iters):
        v = wmat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wmat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wmat @ v
    return weight / sigma, u, v


def image_resize_short(x, out_short_len: int, method: str = "bilinear"):
    """Resize so the SHORT edge equals out_short_len, keeping aspect
    (reference: layers/nn.py image_resize_short)."""
    h, w = x.shape[-2], x.shape[-1]
    short, long_ = (h, w) if h < w else (w, h)
    scale = out_short_len / float(short)
    nh, nw = int(round(h * scale)), int(round(w * scale))
    return interpolate(x, (nh, nw), method=method)
