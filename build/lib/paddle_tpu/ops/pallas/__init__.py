"""Pallas custom-kernel registry — the TPU-native answer to the reference's
hand-written/JIT kernel layer (reference: paddle/fluid/operators/jit/ xbyak
codegen, operators/math/ hand kernels). XLA fuses the common graph; these
kernels cover what fusion alone cannot: online-softmax attention streaming
over HBM, ring collectives overlapping compute with ICI RDMA, etc.

Kernels degrade gracefully: on CPU they run in Pallas interpret mode (tests),
on TPU they compile via Mosaic.
"""

from .flash_attention import flash_attention
from .quant_matmul import quant_matmul, quantize_tensor

__all__ = ["flash_attention", "quant_matmul", "quantize_tensor"]
