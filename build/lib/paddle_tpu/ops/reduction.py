"""Reduction ops (reference: paddle/fluid/operators/reduce_ops/ — sum, mean,
max, min, prod, all, any) plus `sum` over a var list and `mean`.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp

Axes = Optional[Union[int, Sequence[int]]]


def _norm_axes(axes: Axes):
    if axes is None:
        return None
    if isinstance(axes, int):
        return (axes,)
    return tuple(axes)


def reduce_sum(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.sum(x, axis=_norm_axes(dim), keepdims=keep_dim)


def reduce_mean(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.mean(x, axis=_norm_axes(dim), keepdims=keep_dim)


def reduce_max(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.max(x, axis=_norm_axes(dim), keepdims=keep_dim)


def reduce_min(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.min(x, axis=_norm_axes(dim), keepdims=keep_dim)


def reduce_prod(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.prod(x, axis=_norm_axes(dim), keepdims=keep_dim)


def reduce_all(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.all(x, axis=_norm_axes(dim), keepdims=keep_dim)


def reduce_any(x, dim: Axes = None, keep_dim: bool = False):
    return jnp.any(x, axis=_norm_axes(dim), keepdims=keep_dim)


def mean(x):
    """reference: operators/mean_op.cc — scalar mean of everything."""
    return jnp.mean(x)


def sum(xs):  # noqa: A001
    """reference: operators/sum_op.cc — sum a list of same-shape tensors."""
    if not isinstance(xs, (list, tuple)):
        return jnp.sum(xs)
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out
