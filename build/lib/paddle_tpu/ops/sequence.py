"""Ragged-sequence ops — the TPU-native replacement for LoDTensor.

The reference packs variable-length sequences without padding via LoD offsets
(reference: paddle/fluid/framework/lod_tensor.h:110,229) and operates on them
with 46 sequence ops (reference: paddle/fluid/operators/sequence_ops/).
That representation is shape-dynamic and XLA-hostile (SURVEY §5.7, §7).

TPU-native canonicalization: a batch of sequences is a dense padded array
``(B, T_max, ...)`` plus an integer ``lengths (B,)`` vector. All sequence ops
are masked dense ops — static shapes, MXU/VPU friendly, recompile-free across
batches once T_max is bucketed (see paddle_tpu.data.bucketing).

Each function below names the reference op it replaces.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce


def sequence_mask(lengths, maxlen: int, dtype=jnp.float32):
    """reference: operators/sequence_mask_op.cc → (B, maxlen) 0/1 mask."""
    pos = jnp.arange(maxlen)
    return (pos[None, :] < lengths[:, None]).astype(dtype)


def _lowest(dtype):
    """Most-negative representable value for float or int dtypes."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).min
    return jnp.iinfo(dtype).min


def sequence_pad(flat, lengths, maxlen: int, pad_value: float = 0.0):
    """reference: sequence_pad_op.cc — packed (sum(L), D) + lengths → (B, maxlen, D).

    Eager-path helper (the packed layout only appears at ingestion; dynamic
    slicing below is fine on host, and jit-safe when lengths are concrete).
    """
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lengths.astype(jnp.int32))])
    b = lengths.shape[0]
    d = flat.shape[1:]
    idx = offsets[:-1, None] + jnp.arange(maxlen)[None, :]  # (B, maxlen)
    idx = jnp.minimum(idx, flat.shape[0] - 1)
    out = flat[idx]  # (B, maxlen, *D)
    mask = sequence_mask(lengths, maxlen, jnp.bool_)
    mask = mask.reshape(b, maxlen, *([1] * len(d)))
    return jnp.where(mask, out, jnp.asarray(pad_value, out.dtype))


def sequence_unpad(x, lengths):
    """reference: sequence_unpad_op.cc — inverse of pad. Eager only (dynamic
    output size); inside jit keep the padded form and mask."""
    pieces = [x[i, :int(l)] for i, l in enumerate(lengths)]
    return jnp.concatenate(pieces, axis=0)


def sequence_pool(x, lengths, pool_type: str = "sum"):
    """reference: sequence_pool_op.cc — pool over time with masking.
    x: (B, T, D); returns (B, D)."""
    mask = sequence_mask(lengths, x.shape[1], x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    # pooled results have shape (B, *feature); broadcast per-row scalars to that
    row = lambda v: v.reshape((-1,) + (1,) * (x.ndim - 2))
    if pool_type == "sum":
        return jnp.sum(x * mask, axis=1)
    if pool_type == "average":
        denom = row(jnp.maximum(lengths.astype(x.dtype), 1.0))
        return jnp.sum(x * mask, axis=1) / denom
    if pool_type == "sqrt":
        denom = row(jnp.sqrt(jnp.maximum(lengths.astype(x.dtype), 1.0)))
        return jnp.sum(x * mask, axis=1) / denom
    if pool_type == "max":
        masked = jnp.where(mask > 0, x, _lowest(x.dtype))
        out = jnp.max(masked, axis=1)
        return jnp.where(row(lengths) > 0, out, 0.0)
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0).astype(jnp.int32)
        return x[jnp.arange(x.shape[0]), idx]
    if pool_type == "first":
        return x[:, 0]
    enforce(False, "unknown pool_type %s", pool_type)


def sequence_softmax(x, lengths):
    """reference: sequence_softmax_op.cc — masked softmax over time (B, T)."""
    mask = sequence_mask(lengths, x.shape[1], jnp.bool_)
    masked = jnp.where(mask, x, _lowest(x.dtype))
    out = jax.nn.softmax(masked, axis=1)
    return out * mask.astype(x.dtype)


def sequence_reverse(x, lengths):
    """reference: sequence_reverse_op.cc — reverse each row's valid prefix."""
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    ln = lengths[:, None]
    src = jnp.where(pos < ln, ln - 1 - pos, pos)  # (B, T)
    return jnp.take_along_axis(
        x, src.astype(jnp.int32).reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_expand(x, ref_lengths, rmax: Optional[int] = None):
    """reference: sequence_expand_op.cc — repeat each row i ref_lengths[i] times
    along a new ragged axis; dense analog: (B, D) → (B, R_max, D) masked.

    Pass static ``rmax`` when calling under jit (like sequence_mask's maxlen);
    without it the bound is taken from concrete ref_lengths (eager only).
    """
    if rmax is None:
        rmax = int(jnp.max(ref_lengths)) if not isinstance(ref_lengths, (list, tuple)) \
            else max(ref_lengths)
    out = jnp.repeat(x[:, None], rmax, axis=1)
    mask = sequence_mask(jnp.asarray(ref_lengths), rmax, out.dtype)
    return out * mask.reshape(mask.shape + (1,) * (out.ndim - 2))


def sequence_concat(xs, lengths_list):
    """reference: sequence_concat_op.cc — concat along time, per row."""
    b = xs[0].shape[0]
    total = sum(x.shape[1] for x in xs)
    d = xs[0].shape[2:]
    out = jnp.zeros((b, total) + d, xs[0].dtype)
    new_lengths = sum(jnp.asarray(l) for l in lengths_list)
    # Shift each segment into place with scatter via take: build gather index.
    # Row i of output = concat of valid prefixes. Compute source map eagerly.
    t_out = jnp.arange(total)[None, :]  # (1, total)
    starts = []
    acc = jnp.zeros(b, jnp.int32)
    for l in lengths_list:
        starts.append(acc)
        acc = acc + jnp.asarray(l, jnp.int32)
    result = out
    offset_in = 0
    for x, l, st in zip(xs, lengths_list, starts):
        l = jnp.asarray(l, jnp.int32)
        tmax = x.shape[1]
        src_pos = t_out - st[:, None]  # position within this segment
        valid = (src_pos >= 0) & (src_pos < l[:, None])
        src_pos_c = jnp.clip(src_pos, 0, tmax - 1).astype(jnp.int32)
        gathered = jnp.take_along_axis(
            x, src_pos_c.reshape(b, total, *([1] * len(d))), axis=1)
        result = jnp.where(valid.reshape(b, total, *([1] * len(d))),
                           gathered, result)
    return result, new_lengths


def sequence_slice(x, lengths, offset, length):
    """reference: sequence_slice_op.cc — per-row window [offset, offset+length)."""
    b, t = x.shape[:2]
    pos = jnp.arange(t)[None, :]
    src = pos + offset[:, None]
    valid = pos < length[:, None]
    src_c = jnp.clip(src, 0, t - 1).astype(jnp.int32)
    out = jnp.take_along_axis(
        x, src_c.reshape(b, t, *([1] * (x.ndim - 2))), axis=1)
    mask = valid.reshape(b, t, *([1] * (x.ndim - 2)))
    return out * mask.astype(x.dtype), length


def sequence_enumerate(x, lengths, win_size: int, pad_value: int = 0):
    """reference: sequence_enumerate_op.cc — sliding windows of ids (B, T) →
    (B, T, win_size)."""
    b, t = x.shape
    idx = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]  # (T, W)
    valid_in_row = idx < lengths[:, None, None]
    idx_c = jnp.minimum(idx, t - 1)
    out = x[:, idx_c]  # (B, T, W)
    return jnp.where(valid_in_row, out, pad_value)


def sequence_erase(x, lengths, tokens):
    """reference: sequence_erase_op.cc — remove listed tokens; dense analog
    compacts each row to the left. Eager-only (per-row python loop)."""
    outs, new_lens = [], []
    t = x.shape[1]
    for i in range(x.shape[0]):
        row = [v for v in list(x[i, :int(lengths[i])]) if int(v) not in tokens]
        new_lens.append(len(row))
        row = row + [0] * (t - len(row))
        outs.append(jnp.array(row, x.dtype))
    return jnp.stack(outs), jnp.array(new_lens, jnp.int32)


def sequence_expand_as(x, ref_lengths, rmax: Optional[int] = None):
    """reference: sequence_expand_as_op.cc."""
    return sequence_expand(x, ref_lengths, rmax=rmax)


def im2sequence(x, kernel, stride, padding=(0, 0)):
    """reference: operators/im2sequence_op.cc — image patches to sequence."""
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=kernel, window_strides=stride,
        padding=[(padding[0], padding[0]), (padding[1], padding[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, cKK, oh, ow = patches.shape
    return patches.reshape(n, cKK, oh * ow).transpose(0, 2, 1)


def position_encoding(x, alpha: float = 1.0, beta: float = 1.0):
    """reference: operators/add_position_encoding_op.cc — sinusoidal PE added.
    Handles odd feature dims: sin part gets ceil(d/2) columns, cos floor(d/2)."""
    b, t, d = x.shape
    sin_d = (d + 1) // 2
    cos_d = d // 2
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    half = max(sin_d, 1)
    div_sin = jnp.power(10000.0, jnp.arange(sin_d, dtype=jnp.float32) / half)
    div_cos = jnp.power(10000.0, jnp.arange(cos_d, dtype=jnp.float32) / half)
    pe = jnp.concatenate([jnp.sin(pos / div_sin), jnp.cos(pos / div_cos)], axis=1)
    return alpha * x + beta * pe[None]


def hash_embedding_ids(ids, num_buckets: int, num_hash: int = 1):
    """reference: operators/hash_op.cc — multi-hash ids into buckets."""
    outs = []
    x = ids.astype(jnp.uint32)
    for i in range(num_hash):
        h = (x * jnp.uint32(2654435761) + jnp.uint32(i * 0x9E3779B9))
        outs.append((h % jnp.uint32(num_buckets)).astype(jnp.int32))
    return jnp.stack(outs, axis=-1)


def sequence_reshape(x, lengths, new_dim: int):
    """reference: sequence_ops/sequence_reshape_op.cc — re-chunk each
    sequence's flattened payload into rows of ``new_dim``. On the padded
    (B, T, D) layout this is a reshape of the time/feature axes; lengths
    scale by D/new_dim. Requires T*D % new_dim == 0."""
    b, t, d = x.shape
    enforce((t * d) % new_dim == 0,
            "sequence_reshape: T*D=%s not divisible by new_dim=%s", t * d,
            new_dim)
    new_t = t * d // new_dim
    out = x.reshape(b, new_t, new_dim)
    new_lengths = (lengths * d) // new_dim
    return out, new_lengths


def sequence_scatter(x, index, updates, lengths=None):
    """reference: sequence_ops/sequence_scatter_op.cc — add per-sequence
    updates into x at per-sequence positions. x: (B, D); index: (B, T)
    positions into D; updates: (B, T); padded steps (>= lengths) ignored."""
    b, t = index.shape
    if lengths is not None:
        mask = (jnp.arange(t)[None, :] < lengths[:, None])
        updates = updates * mask.astype(updates.dtype)
    import jax

    def one(row, idx, upd):
        return row.at[idx].add(upd)

    return jax.vmap(one)(x, index, updates)


def add_position_encoding(x, alpha: float = 1.0, beta: float = 1.0):
    """reference: operators/add_position_encoding_op.cc — y = alpha*x +
    beta*sinusoid(pos) with the transformer sin/cos interleave."""
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    half = d // 2
    div = jnp.exp(jnp.arange(half, dtype=x.dtype) *
                  -(jnp.log(10000.0) / jnp.maximum(half - 1, 1)))
    ang = pos * div[None, :]
    enc = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    if enc.shape[-1] < d:  # odd d
        enc = jnp.pad(enc, ((0, 0), (0, d - enc.shape[-1])))
    return alpha * x + beta * enc[None]


# ---------------------------------------------------------------------------
# chunk evaluation (sequence tagging F1)
# ---------------------------------------------------------------------------

_CHUNK_SCHEMES = {
    # (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_flags(prev_tag, prev_type, tag, typ, other, scheme):
    """Vectorized ChunkBegin/ChunkEnd predicates (reference:
    operators/chunk_eval_op.h ChunkBegin:95 / ChunkEnd:83 — the ordered
    early-return chain becomes a jnp.select priority list)."""
    _, t_begin, t_inside, t_end, t_single = scheme
    f = jnp.full_like(tag, False, dtype=bool)
    t = jnp.full_like(tag, True, dtype=bool)
    end = jnp.select(
        [prev_type == other,
         typ == other,
         typ != prev_type,
         prev_tag == t_begin,
         prev_tag == t_inside,
         prev_tag == t_end,
         prev_tag == t_single],
        [f, t, t,
         (tag == t_begin) | (tag == t_single),
         (tag == t_begin) | (tag == t_single),
         t, t],
        default=f)
    begin = jnp.select(
        [prev_type == other,
         typ == other,
         typ != prev_type,
         tag == t_begin,
         tag == t_inside,
         tag == t_end,
         tag == t_single],
        [typ != other, f, t, t,
         (prev_tag == t_end) | (prev_tag == t_single),
         (prev_tag == t_end) | (prev_tag == t_single),
         t],
        default=f)
    return begin, end


def _chunk_segments(labels, lengths, num_chunk_types, scheme):
    """Per-position segment-close encoding of GetSegments (reference:
    chunk_eval_op.h:41): returns (close (B, T+1), start (B, T+1),
    typ (B, T+1)) where close[b, i] marks a segment [start[b, i], i-1]
    of type typ[b, i]. One extra virtual 'other' step closes any chunk
    still open at the sequence end."""
    num_tag = scheme[0]
    other = num_chunk_types
    B, T = labels.shape
    pos = jnp.arange(T)[None, :]
    valid = pos < lengths[:, None]
    # pad positions (and one virtual trailing step) become 'other' type:
    # they never begin a chunk and close any open one
    lab = jnp.where(valid, labels, other * num_tag)
    lab = jnp.concatenate(
        [lab, jnp.full((B, 1), other * num_tag, lab.dtype)], axis=1)
    tag = lab % num_tag
    typ = lab // num_tag
    prev_tag = jnp.concatenate([jnp.full((B, 1), -1, tag.dtype),
                                tag[:, :-1]], axis=1)
    prev_typ = jnp.concatenate([jnp.full((B, 1), other, typ.dtype),
                                typ[:, :-1]], axis=1)
    begin, end = _chunk_flags(prev_tag, prev_typ, tag, typ, other,
                              scheme)

    def step(carry, xs):
        in_chunk, start = carry
        b_i, e_i, i = xs
        close = in_chunk & e_i
        new_in = b_i | (in_chunk & ~e_i)
        new_start = jnp.where(b_i, i, start)
        return (new_in, new_start), (close, start)

    (_, _), (close, start) = jax.lax.scan(
        step,
        (jnp.zeros(B, bool), jnp.zeros(B, jnp.int32)),
        (begin.T, end.T, jnp.arange(T + 1, dtype=jnp.int32)))
    return close.T, start.T, prev_typ


def chunk_eval(inference, label, lengths, num_chunk_types: int,
               chunk_scheme: str = "IOB", excluded_chunk_types=()):
    """Chunking precision/recall/F1 (reference:
    operators/chunk_eval_op.h ChunkEvalKernel::Compute:110 — IOB/IOE/
    IOBES/plain schemes over label = type * num_tag_types + tag).

    Device-native: the reference walks each sequence's segment lists on
    CPU; here segments are encoded per-position (a chunk is identified by
    its close position + start + type, unique per side), so counting and
    matching are elementwise over the padded (B, T) batch — one lax.scan
    over time, everything else vectorized.

    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks) as jax scalars.
    """
    from ..core.enforce import enforce

    enforce(chunk_scheme in _CHUNK_SCHEMES,
            "unknown chunk scheme %r (IOB/IOE/IOBES/plain)", chunk_scheme)
    scheme = _CHUNK_SCHEMES[chunk_scheme]
    inference = jnp.asarray(inference)
    label = jnp.asarray(label)
    if inference.ndim == 1:
        inference = inference[None]
        label = label[None]
    lengths = jnp.asarray(lengths, jnp.int32).reshape(-1)

    i_close, i_start, i_typ = _chunk_segments(
        inference, lengths, num_chunk_types, scheme)
    l_close, l_start, l_typ = _chunk_segments(
        label, lengths, num_chunk_types, scheme)

    def not_excluded(typ):
        keep = jnp.ones_like(typ, dtype=bool)
        for t in excluded_chunk_types:
            keep &= typ != t
        return keep

    num_infer = jnp.sum(i_close & not_excluded(i_typ))
    num_label = jnp.sum(l_close & not_excluded(l_typ))
    correct = jnp.sum(i_close & l_close & (i_start == l_start) &
                      (i_typ == l_typ) & not_excluded(i_typ))
    num_infer = num_infer.astype(jnp.int32)
    num_label = num_label.astype(jnp.int32)
    correct = correct.astype(jnp.int32)
    precision = jnp.where(num_infer > 0, correct / jnp.maximum(num_infer, 1),
                          0.0).astype(jnp.float32)
    recall = jnp.where(num_label > 0, correct / jnp.maximum(num_label, 1),
                       0.0).astype(jnp.float32)
    f1 = jnp.where(correct > 0,
                   2 * precision * recall /
                   jnp.maximum(precision + recall, 1e-38),
                   0.0).astype(jnp.float32)
    return precision, recall, f1, num_infer, num_label, correct
