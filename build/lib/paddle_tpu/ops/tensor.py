"""Tensor manipulation ops: shape, indexing, creation, search.

Capability parity with reference ops: concat, split, reshape2, transpose2,
squeeze/unsqueeze, stack/unstack, expand, slice, gather, scatter, pad,
top_k, argsort, arg_max/min, where, shape, fill_constant, one_hot, diag,
linspace, range, reverse, flatten, multiplex, crop, random_crop, uniform/
gaussian_random (reference: paddle/fluid/operators/<name>_op.cc).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..core.enforce import enforce

# --- creation --------------------------------------------------------------

def fill_constant(shape, value, dtype=jnp.float32):
    return jnp.full(shape, value, dtype=dtype)


def fill_constant_batch_size_like(ref, shape, value, dtype=jnp.float32,
                                  input_dim_idx: int = 0, output_dim_idx: int = 0):
    shape = list(shape)
    shape[output_dim_idx] = ref.shape[input_dim_idx]
    return jnp.full(tuple(shape), value, dtype=dtype)


def fill_zeros_like(x):
    return jnp.zeros_like(x)


def ones(shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def zeros(shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def eye(n, m=None, dtype=jnp.float32):
    return jnp.eye(n, m, dtype=dtype)


def diag(v):
    return jnp.diag(v)


def linspace(start, stop, num, dtype=jnp.float32):
    return jnp.linspace(start, stop, int(num), dtype=dtype)


def arange(start, end=None, step=1, dtype=None):
    return jnp.arange(start, end, step, dtype=dtype)


def uniform_random(shape, key, min: float = -1.0, max: float = 1.0,  # noqa: A002
                   dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, min, max)


def gaussian_random(shape, key, mean: float = 0.0, std: float = 1.0,
                    dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * std + mean


def truncated_gaussian_random(shape, key, mean: float = 0.0, std: float = 1.0,
                              dtype=jnp.float32):
    return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * std + mean


def assign(x):
    return jnp.asarray(x)


# --- shape ops -------------------------------------------------------------

def reshape(x, shape):
    """reference: reshape2 — supports one -1 and 0 (= copy input dim)."""
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return jnp.reshape(x, shape)


def transpose(x, perm):
    return jnp.transpose(x, perm)


def flatten(x, axis: int = 1):
    """reference: flatten2 — collapse to 2D at `axis`."""
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return x.reshape(lead, -1)


def squeeze(x, axes: Optional[Sequence[int]] = None):
    return jnp.squeeze(x, tuple(axes) if axes else None)


def unsqueeze(x, axes: Union[int, Sequence[int]]):
    if isinstance(axes, int):
        axes = [axes]
    for a in sorted(axes):
        x = jnp.expand_dims(x, a)
    return x


def expand(x, expand_times: Sequence[int]):
    """reference: expand_op.cc — tile each dim."""
    return jnp.tile(x, expand_times)


def expand_as(x, target):
    return jnp.broadcast_to(x, target.shape)


def stack(xs, axis: int = 0):
    return jnp.stack(xs, axis)


def unstack(x, axis: int = 0):
    return [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]


def concat(xs, axis: int = 0):
    return jnp.concatenate(xs, axis)


def split(x, num_or_sections, axis: int = 0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis)
    # sections list; -1 means "rest"
    sections = list(num_or_sections)
    if -1 in sections:
        total = x.shape[axis]
        rest = total - sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = rest
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return jnp.split(x, idx, axis)


def slice(x, axes, starts, ends):  # noqa: A001
    """reference: slice_op.cc."""
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = jnp.s_[st:en]
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = jnp.s_[st:en:sd]
    return x[tuple(idx)]


def crop(x, shape, offsets):
    """reference: crop_op.cc."""
    idx = tuple(jnp.s_[o:o + s] for o, s in zip(offsets, shape))
    return x[idx]


def reverse(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    for a in axis:
        x = jnp.flip(x, a)
    return x


def pad(x, paddings, pad_value: float = 0.0):
    """reference: pad_op.cc — paddings is flat [before0, after0, before1, ...]."""
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return jnp.pad(x, cfg, constant_values=pad_value)


def pad_constant_like(x, y, pad_value: float = 0.0):
    """reference: pad_constant_like_op.cc — pad y up to x's shape."""
    cfg = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, cfg, constant_values=pad_value)


def shape(x):
    return jnp.array(x.shape, dtype=jnp.int32)


def cast(x, dtype):
    from ..core.dtypes import to_dtype

    return x.astype(to_dtype(dtype))


# --- indexing / search -----------------------------------------------------

def gather(x, index, axis: int = 0):
    """reference: gather_op.cc — index rows along axis."""
    return jnp.take(x, index.astype(jnp.int32), axis=axis)


def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(x, index, updates, overwrite: bool = True):
    """reference: scatter_op.cc — rows of x at `index` set/added to updates."""
    index = index.astype(jnp.int32)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def top_k(x, k: int):
    """reference: top_k_op.cc — returns (values, indices) over last dim."""
    return jax.lax.top_k(x, k)


def argsort(x, axis: int = -1, descending: bool = False):
    # Sort ascending then flip: negation wraps for unsigned ints / breaks bool.
    order = jnp.argsort(x, axis=axis)
    if descending:
        order = jnp.flip(order, axis=axis)
    values = jnp.take_along_axis(x, order, axis=axis)
    return values, order


def arg_max(x, axis: int = -1):
    return jnp.argmax(x, axis=axis)


def arg_min(x, axis: int = -1):
    return jnp.argmin(x, axis=axis)


def where_index(cond):
    """reference: where_op.cc — indices of nonzero. NOTE: dynamic output shape
    is jit-hostile; use only eagerly or with size= bound."""
    return jnp.stack(jnp.nonzero(cond), axis=-1)


def where(cond, x, y):
    return jnp.where(cond, x, y)


def multiplex(index, inputs):
    """reference: multiplex_op.cc — per-row select among inputs."""
    stacked = jnp.stack(inputs, axis=0)  # (K, N, D)
    idx = index.reshape(-1).astype(jnp.int32)  # (N,)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def is_empty(x):
    return jnp.array(x.size == 0)


def random_crop(x, shape, key):
    """reference: random_crop_op.cc — random offset crop of trailing dims."""
    offsets = []
    for i, (xs, s) in enumerate(zip(x.shape[-len(shape):], shape)):
        key, sub = jax.random.split(key)
        offsets.append(jax.random.randint(sub, (), 0, xs - s + 1))
    start = [0] * (x.ndim - len(shape)) + [int(o) for o in offsets]
    sizes = list(x.shape[:x.ndim - len(shape)]) + list(shape)
    return jax.lax.dynamic_slice(x, start, sizes)


def unique_with_counts(x):
    """reference: unique_with_counts_op — eager only (dynamic shape)."""
    vals, counts = jnp.unique(x, return_counts=True)
    return vals, counts


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis)


def tril(x, k: int = 0):
    return jnp.tril(x, k)


def triu(x, k: int = 0):
    return jnp.triu(x, k)
