"""Optimizers — capability parity with the reference optimizer set
(reference: python/paddle/fluid/optimizer.py:49 base + 12 concrete classes
:508-1874; C++ kernels in paddle/fluid/operators/optimizers/).

Design: functional update rules over parameter pytrees (the reference's
"append update ops to the program" becomes "pure update function jitted into
the train step"). The Optimizer object carries hyperparameters + LR schedule;
``init(params)`` builds the state pytree; ``apply(params, grads, state)``
returns (new_params, new_state). ``minimize`` composes value_and_grad +
clip + regularization + apply — the Optimizer.minimize analog.
"""

from .lr_scheduler import (CosineDecay, ExponentialDecay, InverseTimeDecay,
                           LinearWarmup, NaturalExpDecay, NoamDecay,
                           PiecewiseDecay, PolynomialDecay)
from .optimizers import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW,
                         DecayedAdagrad, ExponentialMovingAverage, Ftrl,
                         Lamb, LarsMomentum, Momentum, Optimizer,
                         ProximalAdagrad, ProximalGD, RMSProp)
from .loss_scaler import DynamicLossScaler
from .sparse import apply_rows, merge_rows, sparse_minimize_fn

__all__ = [
    "apply_rows", "merge_rows", "sparse_minimize_fn",
    "SGD", "Adadelta", "Adagrad", "Adam", "Adamax", "AdamW", "DecayedAdagrad",
    "Ftrl", "Lamb", "LarsMomentum", "Momentum", "Optimizer", "RMSProp",
    "ProximalGD", "ProximalAdagrad", "ExponentialMovingAverage",
    "CosineDecay", "ExponentialDecay", "InverseTimeDecay", "LinearWarmup",
    "NaturalExpDecay", "NoamDecay", "PiecewiseDecay", "PolynomialDecay",
    "DynamicLossScaler",
]
