"""Dynamic loss scaling — fp16-compat mixed precision.

Capability parity with the reference's mixed-precision decorator
(reference: python/paddle/fluid/contrib/mixed_precision/decorator.py:26,190 —
master weights + static/dynamic loss scaling). On TPU bf16 needs no scaling
(same exponent range as fp32), so this exists for fp16-compat parity and for
users porting fp16 recipes.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


class DynamicLossScaler:
    """Functional dynamic loss scaler.

    state = {"scale", "good_steps"}; usage inside a train step:
        scaled_loss = scale_loss(loss, state)
        grads = grad(scaled_loss_fn)  # scaled grads
        grads, state, is_finite = unscale_and_update(grads, state)
        # skip the optimizer apply when not is_finite (lax.cond)
    """

    def __init__(self, init_scale: float = 2.0 ** 15,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 1,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5):
        self.init_scale = init_scale
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio

    def init(self):
        return {"scale": jnp.asarray(self.init_scale, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32),
                "bad_steps": jnp.zeros((), jnp.int32)}

    def scale_loss(self, loss, state):
        return loss * state["scale"].astype(loss.dtype)

    def unscale_and_update(self, grads: Any, state) -> Tuple[Any, dict, Any]:
        scale = state["scale"]
        inv = (1.0 / scale)
        unscaled = jax.tree_util.tree_map(
            lambda g: g * inv.astype(g.dtype), grads)
        finite_tree = jax.tree_util.tree_map(
            lambda g: jnp.all(jnp.isfinite(g)), unscaled)
        is_finite = jax.tree_util.tree_reduce(
            jnp.logical_and, finite_tree, jnp.asarray(True))
        good = jnp.where(is_finite, state["good_steps"] + 1, 0)
        bad = jnp.where(is_finite, 0, state["bad_steps"] + 1)
        grow = good >= self.incr_every_n_steps
        shrink = bad >= self.decr_every_n_nan_or_inf
        new_scale = jnp.where(
            is_finite,
            jnp.where(grow, scale * self.incr_ratio, scale),
            jnp.where(shrink, scale * self.decr_ratio, scale))
        new_scale = jnp.clip(new_scale, 1.0, 2.0 ** 24)
        new_state = {"scale": new_scale,
                     "good_steps": jnp.where(grow, 0, good),
                     "bad_steps": jnp.where(shrink, 0, bad)}
        return unscaled, new_state, is_finite
