"""Learning-rate schedules.

Capability parity with the reference schedules (reference:
python/paddle/fluid/layers/learning_rate_scheduler.py — noam_decay,
exponential_decay, natural_exp_decay, inverse_time_decay, polynomial_decay,
piecewise_decay, cosine_decay, linear_lr_warmup). The reference emits schedule
*ops* into the program; here a schedule is a pure function ``step -> lr``
traced into the jitted train step (step is a traced scalar).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp
from jax import lax


class LRSchedule:
    def __call__(self, step):
        raise NotImplementedError


class Constant(LRSchedule):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, step):
        return jnp.asarray(self.value, jnp.float32)


class NoamDecay(LRSchedule):
    """reference: learning_rate_scheduler.py noam_decay."""

    def __init__(self, d_model: int, warmup_steps: int, scale: float = 1.0):
        self.d_model, self.warmup_steps, self.scale = d_model, warmup_steps, scale

    def __call__(self, step):
        step = jnp.maximum(step.astype(jnp.float32), 1.0)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.scale * (self.d_model ** -0.5) * jnp.minimum(a, b)


class ExponentialDecay(LRSchedule):
    def __init__(self, learning_rate: float, decay_steps: int,
                 decay_rate: float, staircase: bool = False):
        self.lr, self.steps, self.rate, self.staircase = (
            learning_rate, decay_steps, decay_rate, staircase)

    def __call__(self, step):
        exp = step.astype(jnp.float32) / self.steps
        if self.staircase:
            exp = jnp.floor(exp)
        return self.lr * (self.rate ** exp)


class NaturalExpDecay(LRSchedule):
    def __init__(self, learning_rate: float, decay_steps: int,
                 decay_rate: float, staircase: bool = False):
        self.lr, self.steps, self.rate, self.staircase = (
            learning_rate, decay_steps, decay_rate, staircase)

    def __call__(self, step):
        exp = step.astype(jnp.float32) / self.steps
        if self.staircase:
            exp = jnp.floor(exp)
        return self.lr * jnp.exp(-self.rate * exp)


class InverseTimeDecay(LRSchedule):
    def __init__(self, learning_rate: float, decay_steps: int,
                 decay_rate: float, staircase: bool = False):
        self.lr, self.steps, self.rate, self.staircase = (
            learning_rate, decay_steps, decay_rate, staircase)

    def __call__(self, step):
        t = step.astype(jnp.float32) / self.steps
        if self.staircase:
            t = jnp.floor(t)
        return self.lr / (1.0 + self.rate * t)


class PolynomialDecay(LRSchedule):
    def __init__(self, learning_rate: float, decay_steps: int,
                 end_learning_rate: float = 1e-4, power: float = 1.0,
                 cycle: bool = False):
        self.lr, self.steps = learning_rate, decay_steps
        self.end_lr, self.power, self.cycle = end_learning_rate, power, cycle

    def __call__(self, step):
        s = step.astype(jnp.float32)
        if self.cycle:
            mult = jnp.ceil(jnp.maximum(s, 1.0) / self.steps)
            steps = self.steps * jnp.maximum(mult, 1.0)
        else:
            steps = self.steps
            s = jnp.minimum(s, steps)
        frac = (1.0 - s / steps) ** self.power
        return (self.lr - self.end_lr) * frac + self.end_lr


class PiecewiseDecay(LRSchedule):
    """reference: piecewise_decay(boundaries, values)."""

    def __init__(self, boundaries: Sequence[int], values: Sequence[float]):
        assert len(values) == len(boundaries) + 1
        self.boundaries = list(boundaries)
        self.values = list(values)

    def __call__(self, step):
        b = jnp.asarray(self.boundaries)
        v = jnp.asarray(self.values, jnp.float32)
        idx = jnp.sum(step >= b)
        return v[idx]


class CosineDecay(LRSchedule):
    """reference: cosine_decay(lr, step_each_epoch, epochs)."""

    def __init__(self, learning_rate: float, step_each_epoch: int, epochs: int):
        self.lr, self.step_each_epoch, self.epochs = (
            learning_rate, step_each_epoch, epochs)

    def __call__(self, step):
        epoch = jnp.floor(step.astype(jnp.float32) / self.step_each_epoch)
        return self.lr * 0.5 * (jnp.cos(epoch * math.pi / self.epochs) + 1.0)


class LinearWarmup(LRSchedule):
    """reference: linear_lr_warmup — wraps another schedule (or constant)."""

    def __init__(self, learning_rate, warmup_steps: int, start_lr: float,
                 end_lr: float):
        self.base = (learning_rate if isinstance(learning_rate, LRSchedule)
                     else Constant(learning_rate))
        self.warmup_steps, self.start_lr, self.end_lr = (
            warmup_steps, start_lr, end_lr)

    def __call__(self, step):
        s = step.astype(jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * (
            s / self.warmup_steps)
        return jnp.where(s < self.warmup_steps, warm, self.base(step))


def make_schedule(lr) -> LRSchedule:
    if isinstance(lr, LRSchedule):
        return lr
    return Constant(float(lr))
