"""Optimizer implementations.

Each mirrors a reference C++ optimizer op (reference:
paddle/fluid/operators/optimizers/{sgd,momentum,lars_momentum,adam,adamax,
adagrad,decayed_adagrad,adadelta,rmsprop,ftrl}_op.cc) as a pure per-leaf
update rule lifted over the parameter pytree. Lamb/AdamW are additions the
modern model zoo needs.

The step counter lives in state["step"]; LR schedules read it (traced-safe).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.enforce import enforce
from .lr_scheduler import make_schedule

PyTree = Any


def tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class Optimizer:
    """Base — reference Optimizer (optimizer.py:49): minimize = backward +
    clip/regularize + apply_gradients, with LR schedule + accumulators."""

    def __init__(self, learning_rate=0.01, grad_clip=None, regularization=None):
        self.schedule = make_schedule(learning_rate)
        self.grad_clip = grad_clip
        self.regularization = regularization

    # --- per-leaf rule (override these two) --------------------------------

    def init_leaf(self, p) -> Dict[str, Any]:
        return {}

    def update_leaf(self, p, g, s: Dict[str, Any], lr, step):
        raise NotImplementedError

    # --- pytree lifting -----------------------------------------------------

    def init(self, params: PyTree) -> Dict[str, Any]:
        leaves, _ = jax.tree_util.tree_flatten(params)
        return {"step": jnp.zeros((), jnp.int32),
                "leaf": [self.init_leaf(p) for p in leaves]}

    def apply(self, params: PyTree, grads: PyTree,
              state: Dict[str, Any]) -> Tuple[PyTree, Dict[str, Any]]:
        step = state["step"]
        lr = self.schedule(step)
        # reference order (optimizer.py apply_gradients): clip the raw grads
        # first, then add the regularization term.
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        if self.regularization is not None:
            grads = self.regularization.apply_to_grads(params, grads)
        leaves_p, treedef = jax.tree_util.tree_flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaf_states = state["leaf"]
        enforce(len(leaf_states) == len(leaves_p),
                "optimizer state has %s leaves, params have %s — "
                "init() with the same structure", len(leaf_states), len(leaves_p))
        results = [self.update_leaf(p, g, s, lr, step)
                   for p, g, s in zip(leaves_p, leaves_g, leaf_states)]
        new_params = jax.tree_util.tree_unflatten(
            treedef, [r[0] for r in results])
        return new_params, {"step": step + 1, "leaf": [r[1] for r in results]}

    # --- high-level UX ------------------------------------------------------

    def minimize_fn(self, loss_fn: Callable) -> Callable:
        """Build a jittable ``train_step(params, state, *args) ->
        (loss, new_params, new_state)`` (Optimizer.minimize analog)."""

        def step_fn(params, state, *args, **kwargs):
            loss, grads = jax.value_and_grad(loss_fn)(params, *args, **kwargs)
            new_params, new_state = self.apply(params, grads, state)
            return loss, new_params, new_state

        return step_fn

    def current_lr(self, state) -> jnp.ndarray:
        return self.schedule(state["step"])

    # --- static-graph (fluid) entry points ---------------------------------
    # reference optimizer.py: minimize = backward + apply_gradients over a
    # Program. The SAME per-leaf rule (init_leaf/update_leaf) lowers to
    # recorded update ops, so every functional optimizer works in static
    # mode without a parallel implementation.

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        """reference: optimizer.py Optimizer.backward → append_backward."""
        from ..static.program import append_backward

        return append_backward(loss, parameter_list)

    def apply_gradients(self, params_grads):
        """Record update ops (+accumulator vars) for (param, grad) Vars.

        Mirrors the eager apply() ordering: clip the WHOLE grad set first
        (global-norm clips see all grads in one recorded op), then add the
        regularization term, then per-param updates."""
        params = [p for p, _ in params_grads]
        grads = [g for _, g in params_grads]
        if params and self.grad_clip is not None:
            prog = params[0].program
            clip = self.grad_clip
            if len(grads) == 1:
                out = prog.apply(lambda g: clip([g])[0], grads,
                                 name="grad_clip")
                grads = [out]
            else:
                out = prog.apply(lambda *gs: tuple(clip(list(gs))), grads,
                                 name="grad_clip")
                grads = list(out)
        for param, grad in zip(params, grads):
            self._append_static_update(param.program, param, grad)
        return list(zip(params, grads))

    def apply_optimize(self, loss, startup_program=None, params_grads=None):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        pairs = self.backward(loss, parameter_list=parameter_list)
        self.apply_gradients(pairs)
        return None, pairs

    def get_opti_var_name_list(self):
        """Accumulator var names created by static apply_gradients
        (reference: optimizer.py get_opti_var_name_list)."""
        return list(getattr(self, "_opti_var_names", []))

    def _append_static_update(self, prog, param, grad):
        from .. import initializer as _I

        tpl = self.init_leaf(jnp.zeros(param.shape, param.dtype))
        keys = sorted(tpl)
        names = []
        svars = []
        for k in keys:
            name = prog.unique_name(f"{param.name}_{k}")
            # accumulators start at init_leaf's ACTUAL value (e.g. Adagrad's
            # initial_accumulator_value), matching the eager init() path
            import numpy as _np

            svars.append(prog.create_parameter(
                name, jnp.shape(tpl[k]), jnp.asarray(tpl[k]).dtype,
                initializer=_I.NumpyArray(_np.asarray(tpl[k])),
                trainable=False))
            names.append(name)
        tname = prog.unique_name(f"{param.name}_step")
        tvar = prog.create_parameter(tname, (), jnp.int32,
                                     initializer=_I.Constant(0.0),
                                     trainable=False)
        names.append(tname)
        self._opti_var_names = getattr(self, "_opti_var_names", []) + names

        def fn(p, g, t, *svals):
            s = dict(zip(keys, svals))
            if self.regularization is not None:
                g = self.regularization.apply_to_grads(p, g)
            lr = self.schedule(t)
            p_new, s_new = self.update_leaf(p, g, s, lr, t)
            return (p_new, t + 1) + tuple(s_new[k] for k in keys)

        outs = prog.apply(fn, [param, grad, tvar] + svars,
                          name=f"{type(self).__name__.lower()}_{param.name}")
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        prog.assign(param, outs[0])
        prog.assign(tvar, outs[1])
        for var, k in zip(svars, keys):
            prog.assign(var, outs[2 + keys.index(k)])


class SGD(Optimizer):
    """reference: optimizers/sgd_op.cc."""

    def update_leaf(self, p, g, s, lr, step):
        return p - lr.astype(p.dtype) * g.astype(p.dtype), s


class Momentum(Optimizer):
    """reference: optimizers/momentum_op.cc (incl. use_nesterov attr)."""

    def __init__(self, learning_rate=0.01, momentum: float = 0.9,
                 use_nesterov: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def init_leaf(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        lr = lr.astype(p.dtype)
        v = self.momentum * s["velocity"] + g
        if self.use_nesterov:
            new_p = p - (g + self.momentum * v) * lr
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class LarsMomentum(Optimizer):
    """reference: optimizers/lars_momentum_op.cc — layer-adaptive LR."""

    def __init__(self, learning_rate=0.01, momentum: float = 0.9,
                 lars_coeff: float = 1e-3, lars_weight_decay: float = 5e-4, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay

    def init_leaf(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        lr = lr.astype(p.dtype)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = lr * self.lars_coeff * p_norm / (
            g_norm + self.lars_weight_decay * p_norm + 1e-12)
        local_lr = jnp.where(p_norm > 0, local_lr, lr)
        v = self.momentum * s["velocity"] + local_lr * (
            g + self.lars_weight_decay * p)
        return p - v, {"velocity": v}


class Adam(Optimizer):
    """reference: optimizers/adam_op.cc."""

    def __init__(self, learning_rate=0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8,
                 lazy_mode: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_leaf(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        t = (step + 1).astype(jnp.float32)
        m = self.beta1 * s["m"] + (1 - self.beta1) * g
        v = self.beta2 * s["v"] + (1 - self.beta2) * jnp.square(g)
        mhat = m / (1 - self.beta1 ** t).astype(p.dtype)
        vhat = v / (1 - self.beta2 ** t).astype(p.dtype)
        new_p = p - lr.astype(p.dtype) * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return new_p, {"m": m, "v": v}


class AdamW(Adam):
    """Decoupled weight decay (modern addition; the reference couples L2 into
    grads via regularizer.py)."""

    def __init__(self, learning_rate=0.001, weight_decay: float = 0.01, **kw):
        super().__init__(learning_rate, **kw)
        self.weight_decay = weight_decay

    def update_leaf(self, p, g, s, lr, step):
        new_p, new_s = super().update_leaf(p, g, s, lr, step)
        return new_p - lr.astype(p.dtype) * self.weight_decay * p, new_s


class Adamax(Optimizer):
    """reference: optimizers/adamax_op.cc."""

    def __init__(self, learning_rate=0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_leaf(self, p):
        return {"m": jnp.zeros_like(p), "inf": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        t = (step + 1).astype(jnp.float32)
        m = self.beta1 * s["m"] + (1 - self.beta1) * g
        inf = jnp.maximum(self.beta2 * s["inf"], jnp.abs(g))
        lr_t = (lr / (1 - self.beta1 ** t)).astype(p.dtype)
        new_p = p - lr_t * m / (inf + self.epsilon)
        return new_p, {"m": m, "inf": inf}


class Adagrad(Optimizer):
    """reference: optimizers/adagrad_op.cc."""

    def __init__(self, learning_rate=0.01, epsilon: float = 1e-6,
                 initial_accumulator_value: float = 0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def init_leaf(self, p):
        return {"moment": jnp.full_like(p, self.init_acc)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        moment = s["moment"] + jnp.square(g)
        new_p = p - lr.astype(p.dtype) * g / (jnp.sqrt(moment) + self.epsilon)
        return new_p, {"moment": moment}


class DecayedAdagrad(Optimizer):
    """reference: optimizers/decayed_adagrad_op.cc."""

    def __init__(self, learning_rate=0.01, decay: float = 0.95,
                 epsilon: float = 1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def init_leaf(self, p):
        return {"moment": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        moment = self.decay * s["moment"] + (1 - self.decay) * jnp.square(g)
        new_p = p - lr.astype(p.dtype) * g / (jnp.sqrt(moment) + self.epsilon)
        return new_p, {"moment": moment}


class Adadelta(Optimizer):
    """reference: optimizers/adadelta_op.cc."""

    def __init__(self, learning_rate=1.0, rho: float = 0.95,
                 epsilon: float = 1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def init_leaf(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p),
                "avg_sq_update": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        asg = self.rho * s["avg_sq_grad"] + (1 - self.rho) * jnp.square(g)
        update = g * jnp.sqrt(s["avg_sq_update"] + self.epsilon) / jnp.sqrt(
            asg + self.epsilon)
        asu = self.rho * s["avg_sq_update"] + (1 - self.rho) * jnp.square(update)
        return p - lr.astype(p.dtype) * update, \
            {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    """reference: optimizers/rmsprop_op.cc (incl. centered variant)."""

    def __init__(self, learning_rate=0.01, rho: float = 0.95,
                 epsilon: float = 1e-6, momentum: float = 0.0,
                 centered: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def init_leaf(self, p):
        s = {"mean_square": jnp.zeros_like(p), "moment": jnp.zeros_like(p)}
        if self.centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        ms = self.rho * s["mean_square"] + (1 - self.rho) * jnp.square(g)
        out = {"mean_square": ms}
        if self.centered:
            mg = self.rho * s["mean_grad"] + (1 - self.rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum * s["moment"] + lr.astype(p.dtype) * g / denom
        out["moment"] = mom
        return p - mom, out


class Ftrl(Optimizer):
    """reference: optimizers/ftrl_op.cc."""

    def __init__(self, learning_rate=0.01, l1: float = 0.0, l2: float = 0.0,
                 lr_power: float = -0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def init_leaf(self, p):
        return {"squared": jnp.zeros_like(p), "linear": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        lr = lr.astype(p.dtype)
        new_sq = s["squared"] + jnp.square(g)
        if self.lr_power == -0.5:
            sigma = (jnp.sqrt(new_sq) - jnp.sqrt(s["squared"])) / lr
        else:
            sigma = (new_sq ** -self.lr_power - s["squared"] ** -self.lr_power) / lr
        linear = s["linear"] + g - sigma * p
        if self.lr_power == -0.5:
            denom = jnp.sqrt(new_sq) / lr + 2 * self.l2
        else:
            denom = new_sq ** -self.lr_power / lr + 2 * self.l2
        pre = (jnp.sign(linear) * self.l1 - linear) / denom
        new_p = jnp.where(jnp.abs(linear) > self.l1, pre, jnp.zeros_like(p))
        return new_p, {"squared": new_sq, "linear": linear}


class Lamb(Optimizer):
    """LAMB (large-batch training; reference-era fleet used LARS, Lamb is the
    transformer analog)."""

    def __init__(self, learning_rate=0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-6,
                 weight_decay: float = 0.01, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon, self.weight_decay = epsilon, weight_decay

    def init_leaf(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        t = (step + 1).astype(jnp.float32)
        m = self.beta1 * s["m"] + (1 - self.beta1) * g
        v = self.beta2 * s["v"] + (1 - self.beta2) * jnp.square(g)
        mhat = m / (1 - self.beta1 ** t).astype(p.dtype)
        vhat = v / (1 - self.beta2 ** t).astype(p.dtype)
        update = mhat / (jnp.sqrt(vhat) + self.epsilon) + self.weight_decay * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p - lr.astype(p.dtype) * ratio * update, {"m": m, "v": v}


class ProximalGD(Optimizer):
    """reference: optimizers/proximal_gd_op.cc — SGD with L1/L2 proximal
    projection: w = prox(w - lr*g)."""

    def __init__(self, learning_rate, l1: float = 0.0, l2: float = 0.0,
                 **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2 = l1, l2

    def update_leaf(self, p, g, s, lr, step):
        prox = p - lr * g
        if self.l1 > 0:
            prox = (jnp.sign(prox) *
                    jnp.maximum(jnp.abs(prox) - lr * self.l1, 0.0))
        new_p = prox / (1.0 + lr * self.l2)
        return new_p, s


class ProximalAdagrad(Optimizer):
    """reference: optimizers/proximal_adagrad_op.cc — Adagrad step with the
    same proximal projection using the adaptive lr."""

    def __init__(self, learning_rate, l1: float = 0.0, l2: float = 0.0,
                 epsilon: float = 1e-10, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.epsilon = l1, l2, epsilon

    def init_leaf(self, p):
        return {"moment": jnp.zeros_like(p)}

    def update_leaf(self, p, g, s, lr, step):
        moment = s["moment"] + g * g
        alr = lr / (jnp.sqrt(moment) + self.epsilon)
        prox = p - alr * g
        if self.l1 > 0:
            prox = (jnp.sign(prox) *
                    jnp.maximum(jnp.abs(prox) - alr * self.l1, 0.0))
        new_p = prox / (1.0 + alr * self.l2)
        return new_p, {"moment": moment}


class ExponentialMovingAverage:
    """Parameter EMA (reference: operators/average_accumulates_op.cc +
    optimizer.py ModelAverage/EMA capability): shadow = decay*shadow +
    (1-decay)*param, with bias correction. Functional: state in, state out."""

    def __init__(self, decay: float = 0.999):
        self.decay = decay

    def init(self, params):
        return {"shadow": tree_map(jnp.zeros_like, params),
                "count": jnp.zeros((), jnp.int32)}

    def update(self, params, state):
        count = state["count"] + 1
        shadow = tree_map(
            lambda s, p: self.decay * s + (1.0 - self.decay) * p,
            state["shadow"], params)
        return {"shadow": shadow, "count": count}

    def average(self, state):
        """Bias-corrected EMA params."""
        corr = 1.0 - self.decay ** state["count"].astype(jnp.float32)
        return tree_map(lambda s: s / jnp.maximum(corr, 1e-12),
                        state["shadow"])
