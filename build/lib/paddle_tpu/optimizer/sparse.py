"""Row-sparse optimizer updates — the SelectedRows update path.

Capability lineage: the reference's sparse gradients are SelectedRows
(reference: framework/selected_rows.h:32) emitted by
lookup_table_op.cc (is_sparse=True); duplicate rows are merged by
operators/math/selected_rows_functor.cc (MergeAdd) and the optimizer ops
carry dedicated sparse branches that update only the touched rows
(reference: operators/optimizers/adam_op.h SelectedRows branch with
lazy_mode, sgd_op.cc / adagrad_op.cc sparse kernels).

TPU-native form: ids are merged with a static-size ``jnp.unique`` +
``segment_sum`` (MergeAdd), the per-row optimizer state leaves are
gathered for the unique rows, the optimizer's ordinary ``update_leaf``
rule runs on the (U, D) slice, and parameters/state scatter back with
out-of-bounds drop semantics — O(batch x seq x D) per step, flat in
vocab size. Untouched rows keep stale accumulators: the reference's
lazy_mode semantics (momentum/Adam moments decay only when a row is
touched).

``sparse_minimize_fn`` builds the full train step around the
capture/inject contexts of ``nn.sparse`` (see that module's docstring
for the two-phase design).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.enforce import enforce

PyTree = Any


def merge_rows(ids, row_grads, vocab_size: int):
    """MergeAdd (reference: selected_rows_functor.cc): flatten and merge
    duplicate ids. Returns (uids (N,), merged (N, D)) where slots past
    the number of distinct ids hold ``vocab_size`` (out-of-bounds — the
    scatter drops them)."""
    ids = ids.reshape(-1)
    row_grads = row_grads.reshape(ids.shape[0], -1)
    n = ids.shape[0]
    uids, inv = jnp.unique(ids, size=n, fill_value=vocab_size,
                           return_inverse=True)
    merged = jax.ops.segment_sum(row_grads, inv.reshape(-1), num_segments=n)
    return uids, merged


def apply_rows(optimizer, table, ids, row_grads,
               leaf_state: Dict[str, Any], lr, step
               ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """One row-sparse update of ``table`` with ``optimizer``'s ordinary
    update_leaf rule applied to the touched rows only.

    ``ids``: int array (any shape); ``row_grads``: ids.shape + (D,).
    State leaves whose leading dim equals the vocab are treated as
    per-row accumulators (Adam moments, Adagrad accumulator, momentum
    velocity); anything else passes through untouched.
    """
    V = table.shape[0]
    uids, merged = merge_rows(ids, row_grads, V)
    merged = merged.astype(table.dtype)

    def rowwise(leaf):
        return (hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] == V)

    p_rows = table.at[uids].get(mode="fill", fill_value=0)
    s_rows = {k: (v.at[uids].get(mode="fill", fill_value=0)
                  if rowwise(v) else v)
              for k, v in leaf_state.items()}
    p_new, s_new = optimizer.update_leaf(p_rows, merged, s_rows, lr, step)
    # fill slots carry uid == V: out-of-bounds, dropped by the scatter
    new_table = table.at[uids].set(p_new, mode="drop")
    new_state = {k: (v.at[uids].set(s_new[k], mode="drop")
                     if rowwise(v) else s_new[k])
                 for k, v in leaf_state.items()}
    return new_table, new_state


def find_sparse_embeddings(model) -> Dict[str, Any]:
    """{param name -> layer} for every is_sparse embedding in ``model``
    (nn.Embedding and parallel.ShardedEmbedding)."""
    out = {}
    for name, sub in model.named_sublayers():
        if getattr(sub, "is_sparse", False) and hasattr(sub, "weight"):
            out[f"{name}.weight" if name else "weight"] = sub
    return out


def sparse_minimize_fn(model, forward_loss: Callable, optimizer,
                       emb_optimizer=None):
    """Build ``(init_fn, step_fn)`` where embedding tables flagged
    ``is_sparse`` get row-sparse updates and everything else follows the
    ordinary dense ``optimizer.apply``.

    - ``forward_loss(params, *args, **kwargs) -> scalar loss`` must run
      the model through ``model.functional_call`` (or ``model(...)``
      with params set) so the sparse layers see the capture/inject
      contexts.
    - ``emb_optimizer`` optionally uses a different rule for the tables
      (reference: PS deployments pair sparse Adagrad tables with dense
      Adam); defaults to ``optimizer``.

    Returned contract::

        state = init_fn(params)
        loss, new_params, new_state = jax.jit(step_fn)(params, state, *a)
    """
    from ..nn.sparse import Capture, Inject

    embs = find_sparse_embeddings(model)
    enforce(embs, "sparse_minimize_fn: model has no is_sparse embeddings "
            "— use optimizer.minimize_fn instead")
    emb_names = set(embs)
    eopt = emb_optimizer or optimizer
    layer_ids = {id(l) for l in embs.values()}
    by_layer = {id(l): n for n, l in embs.items()}

    def init_fn(params: Dict[str, Any]) -> Dict[str, Any]:
        dense = {k: v for k, v in params.items() if k not in emb_names}
        return {
            "dense": optimizer.init(dense),
            "sparse": {n: eopt.init_leaf(params[n]) for n in emb_names},
        }

    def step_fn(params, state, *args, **kwargs):
        tables = {n: params[n] for n in emb_names}
        dense = {k: v for k, v in params.items() if k not in emb_names}

        # phase 1: capture the ids each sparse layer consumes (everything
        # else in this pass is dead code — XLA DCE removes it)
        cap = Capture(layer_ids)
        with cap:
            forward_loss(params, *args, **kwargs)
        # phase 2: gather rows OUTSIDE the differentiated function
        rows = {slot: jnp.take(tables[by_layer[owner]], cap.ids[slot],
                               axis=0)
                for slot, owner in cap.owner.items()}

        def inner(dense_p, rows_map):
            inj = Inject(layer_ids, rows_map)
            with inj:
                return forward_loss({**dense_p, **tables}, *args, **kwargs)

        loss, (g_dense, g_rows) = jax.value_and_grad(
            inner, argnums=(0, 1))(dense, rows)

        step = state["dense"]["step"]
        new_dense, new_dense_state = optimizer.apply(
            dense, g_dense, state["dense"])

        lr = eopt.schedule(step)
        new_sparse_state = {}
        new_tables = dict(tables)
        for name in emb_names:
            slots = [s for s, o in cap.owner.items()
                     if by_layer[o] == name]
            tbl, st = new_tables[name], state["sparse"][name]
            for slot in slots:
                tbl, st = apply_rows(eopt, tbl, cap.ids[slot],
                                     g_rows[slot], st, lr, step)
            new_tables[name] = tbl
            new_sparse_state[name] = st

        new_params = {**new_dense, **new_tables}
        return loss, new_params, {"dense": new_dense_state,
                                  "sparse": new_sparse_state}

    return init_fn, step_fn
