"""Collective ops over mesh axes.

The TPU-native replacement for the reference's NCCL op-handles and RPC
collective server (reference: framework/details/all_reduce_op_handle.cc:91,
operators/distributed/collective_client.h, layers/collective.py:19): these are
`lax` collectives bound to named mesh axes, emitted inside `shard_map`/`pjit`
regions; XLA lowers them onto ICI/DCN rings — there is no hand-written
transport.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
from jax import lax

Axis = Union[str, Sequence[str]]


def allreduce(x, axis: Axis = "dp", op: str = "sum"):
    """reference: allreduce op (distributed_ops/allreduce_op.cc) → lax.p*."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unknown allreduce op {op}")


def allgather(x, axis: Axis = "dp", tiled: bool = True):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: Axis = "dp", scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                            tiled=True)


def all_to_all(x, axis: Axis, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(x, axis: Axis, perm):
    return lax.ppermute(x, axis, perm)


def broadcast(x, axis: Axis = "dp", src: int = 0):
    """Broadcast src's shard to all — BCastParamsToDevices analog
    (reference: parallel_executor.cc:434)."""
    idx = lax.axis_index(axis)
    masked = jax.tree_util.tree_map(
        lambda a: jax.numpy.where(idx == src, a, jax.numpy.zeros_like(a)), x)
    return jax.tree_util.tree_map(lambda a: lax.psum(a, axis), masked)


def axis_index(axis: Axis = "dp"):
    return lax.axis_index(axis)
