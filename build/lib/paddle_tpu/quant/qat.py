"""Quantization-aware training + post-training quantization — capability
parity with the reference's slim quantization framework (reference:
python/paddle/fluid/contrib/slim/quantization/quantization_pass.py —
QuantizationTransformPass inserts fake_quant ops before quantizable ops and
QuantizationFreezePass rewrites for int8 inference; post-training calibration:
paddle/fluid/inference/api/mkldnn_quantizer.cc).

TPU-native design: instead of a protobuf-graph rewrite pass, quantization is
a *layer rewrite*: ``quantize_model`` walks the Layer tree and wraps each
quantizable module (Linear/Conv2D) in a ``QuantedLayer`` that fake-quants its
input activation (moving-average abs-max, tracked in buffers so the state
threads through ``functional_call`` pytrees) and its weight (channel-wise
abs-max). The same wrapper serves QAT (train with STE gradients) and PTQ
(run calibration batches, then freeze). ``freeze`` exports real int8 weights
+ scales, the QuantizationFreezePass analog.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple

import jax.numpy as jnp

from ..core.enforce import enforce
from ..nn.layer import Layer
from . import ops as Q


@dataclass
class QuantConfig:
    weight_bits: int = 8
    activation_bits: int = 8
    moving_rate: float = 0.9
    # which layer classes get wrapped; names match paddle_tpu.nn types
    quantizable: Tuple[str, ...] = ("Linear", "Conv2D")
    # per-channel weight axis by layer type (Linear weight is (in, out) →
    # channel axis 1; Conv2D weight is (cout, cin, kh, kw) → axis 0)
    channel_axis: Dict[str, int] = field(
        default_factory=lambda: {"Linear": 1, "Conv2D": 0})


class QuantedLayer(Layer):
    """Wraps one quantizable layer with activation+weight fake quantization
    (the QuantizationTransformPass insertion point, per layer instead of
    per graph node)."""

    def __init__(self, inner: Layer, config: QuantConfig):
        super().__init__()
        tname = type(inner).__name__
        enforce("weight" in inner._params,
                "QuantedLayer needs an inner layer with a 'weight' param, "
                "got %s", tname)
        self.inner = inner
        self.config = config
        self.channel_axis = config.channel_axis.get(tname, 0)
        # moving-average activation scale state lives in buffers so it is
        # part of the functional state pytree
        self.register_buffer("act_scale", jnp.asarray(0.0, jnp.float32))
        self.register_buffer("act_accum", jnp.asarray(0.0, jnp.float32))
        self.register_buffer("act_state", jnp.asarray(0.0, jnp.float32))

    def forward(self, x, *args, **kwargs):
        cfg = self.config
        st = Q.MovingAverageState(self.act_scale, self.act_accum,
                                  self.act_state)
        xq, new_st = Q.fake_quantize_moving_average_abs_max(
            x, st, cfg.activation_bits, cfg.moving_rate,
            is_test=not self.training)
        if self.training:
            self.update_buffer("act_scale", new_st.scale)
            self.update_buffer("act_accum", new_st.accum)
            self.update_buffer("act_state", new_st.state)
        w = self.inner._params["weight"]
        wq, _ = Q.fake_channel_wise_quantize_abs_max(
            w, cfg.weight_bits, self.channel_axis)
        saved = self.inner._params["weight"]
        self.inner._params["weight"] = wq
        try:
            out = self.inner.forward(xq, *args, **kwargs)
        finally:
            self.inner._params["weight"] = saved
        return out

    def weight_scales(self):
        return Q.abs_max_scale(self.inner._params["weight"],
                               axis=self.channel_axis)


def quantize_model(model: Layer, config: Optional[QuantConfig] = None,
                   ) -> Layer:
    """Rewrite ``model`` in place, wrapping every quantizable sublayer.
    Returns the model (param paths gain an ``.inner`` segment under each
    wrapped layer — do this BEFORE snapshotting params)."""
    config = config or QuantConfig()

    def rewrite(layer: Layer):
        for name, sub in list(layer._sublayers.items()):
            if type(sub).__name__ in config.quantizable:
                wrapper = QuantedLayer(sub, config)
                layer._sublayers[name] = wrapper
                object.__setattr__(layer, name, wrapper)
            else:
                rewrite(sub)

    enforce(type(model).__name__ not in config.quantizable,
            "quantize_model wraps sublayers; wrap the root %s yourself with "
            "QuantedLayer", type(model).__name__)
    rewrite(model)
    return model


def calibrate(model: Layer, batches: Iterable, forward=None) -> Layer:
    """Post-training calibration (mkldnn_quantizer.cc analog): run
    representative batches in training mode so the moving-average activation
    scales settle, then switch to eval (frozen scales)."""
    model.train()
    for batch in batches:
        if forward is not None:
            forward(model, batch)
        elif isinstance(batch, tuple):
            model(*batch)
        else:
            model(batch)
    model.eval()
    return model


def freeze(model: Layer) -> Dict[str, Dict[str, jnp.ndarray]]:
    """QuantizationFreezePass analog: export real int8 weights + scales for
    every quantized layer. Returns {layer_path: {"weight_int8", "weight_scale",
    "act_scale", "bits"}}."""
    out = {}
    for path, sub in model.named_sublayers():
        if isinstance(sub, QuantedLayer):
            w = sub.inner._params["weight"]
            wscale = sub.weight_scales()
            shape = [1] * w.ndim
            shape[sub.channel_axis] = w.shape[sub.channel_axis]
            out[path] = {
                "weight_int8": Q.quantize_to_int(
                    w, wscale.reshape(shape), sub.config.weight_bits),
                "weight_scale": wscale,
                "act_scale": sub.act_scale,
                "bits": sub.config.weight_bits,
            }
    return out
