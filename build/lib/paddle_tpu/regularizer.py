"""Weight regularization — parity with the reference regularizers
(reference: python/paddle/fluid/regularizer.py — L1Decay/L2Decay appended as
grad-modifying ops). Here: pure functions adding the decay term to grads,
pluggable into ``Optimizer(regularization=...)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class L2Decay:
    def __init__(self, coeff: float):
        self.coeff = coeff

    def apply_to_grads(self, params, grads):
        return jax.tree_util.tree_map(
            lambda p, g: g + self.coeff * p, params, grads)

    def loss_term(self, params):
        return 0.5 * self.coeff * sum(
            jnp.sum(jnp.square(p)) for p in jax.tree_util.tree_leaves(params))


class L1Decay:
    def __init__(self, coeff: float):
        self.coeff = coeff

    def apply_to_grads(self, params, grads):
        return jax.tree_util.tree_map(
            lambda p, g: g + self.coeff * jnp.sign(p), params, grads)

    def loss_term(self, params):
        return self.coeff * sum(
            jnp.sum(jnp.abs(p)) for p in jax.tree_util.tree_leaves(params))


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
