"""Model compression — the contrib/slim capability set (reference:
python/paddle/fluid/contrib/slim/): distillation (distill.py), pruning
with sensitivity analysis + structural shrink (prune.py), and the
epoch-driven Compressor/Strategy/Config driver (core.py). Quantization
lives in ``paddle_tpu.quant`` (slim/quantization's role)."""

from .core import (Compressor, Context, DistillationStrategy,
                   SensitivePruneStrategy, Strategy, UniformPruneStrategy,
                   build_strategies)
from .distill import (Distiller, fsp_loss, l2_feature_loss,
                      soft_label_loss)
from .prune import (Pruner, channel_keep_indices, compute_sensitivities,
                    greedy_ratios_for_target, magnitude_mask, shrink_params,
                    structured_channel_mask, uniform_ratio_search)

__all__ = [
    "Compressor", "Context", "Strategy", "UniformPruneStrategy",
    "SensitivePruneStrategy", "DistillationStrategy", "build_strategies",
    "Distiller", "soft_label_loss", "fsp_loss", "l2_feature_loss",
    "Pruner", "magnitude_mask", "structured_channel_mask",
    "compute_sensitivities", "greedy_ratios_for_target",
    "uniform_ratio_search", "channel_keep_indices", "shrink_params",
]
