"""Distillation losses + composition (reference:
python/paddle/fluid/contrib/slim/distillation/ — soft-label loss, fsp
loss, l2 feature loss between teacher/student var pairs;
distillation_strategy.py merges teacher and student programs — here the
teacher is just a second params tree + apply_fn, composed functionally).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.enforce import enforce
from ..ops.loss import softmax_with_cross_entropy
from ..ops.nn_extra import fsp_matrix

def soft_label_loss(student_logits, teacher_logits,
                    temperature: float = 1.0):
    """KL-style soft-label distillation loss (reference:
    distillation_strategy soft_label_loss): CE(student/T, softmax(teacher/T))
    scaled by T^2 so gradients keep magnitude."""
    t = temperature
    teacher_probs = jax.nn.softmax(teacher_logits / t, axis=-1)
    ce = softmax_with_cross_entropy(student_logits / t, teacher_probs,
                                    soft_label=True)
    return jnp.mean(ce) * (t * t)


def fsp_loss(student_pair: Tuple, teacher_pair: Tuple):
    """FSP distillation loss (reference: fsp_op.cc + distillation usage):
    L2 between the student's and teacher's flow matrices."""
    s = fsp_matrix(*student_pair)
    te = fsp_matrix(*teacher_pair)
    return jnp.mean((s - te) ** 2)


def l2_feature_loss(student_feat, teacher_feat):
    """reference: distillation l2-loss between matched feature maps."""
    return jnp.mean((student_feat - teacher_feat) ** 2)


class Distiller:
    """Compose distillation terms with the task loss (the
    DistillationStrategy role, config-driven weighting)."""

    def __init__(self, temperature: float = 4.0, soft_weight: float = 0.7,
                 hard_weight: float = 0.3, feature_weight: float = 0.0):
        self.temperature = temperature
        self.soft_weight = soft_weight
        self.hard_weight = hard_weight
        self.feature_weight = feature_weight

    def loss(self, student_logits, teacher_logits, label=None,
             feature_pairs: Sequence[Tuple] = ()):
        total = self.soft_weight * soft_label_loss(
            student_logits, teacher_logits, self.temperature)
        if label is not None and self.hard_weight:
            total = total + self.hard_weight * jnp.mean(
                softmax_with_cross_entropy(student_logits, label))
        for s, t in feature_pairs:
            total = total + self.feature_weight * l2_feature_loss(s, t)
        return total


