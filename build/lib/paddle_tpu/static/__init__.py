"""Static-graph mode — the fluid Program/Executor capability surface
(reference: python/paddle/fluid/framework.py, executor.py) on an XLA
compile-the-whole-slice design. See program.py for the architecture note.

Usage (mirrors the reference's train loop):

    import paddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = prog.data("x", (-1, 784))
        label = prog.data("label", (-1,), "int32")
        h = static.layers.fc(x, 128, act="relu")
        logits = static.layers.fc(h, 10)
        loss = static.layers.mean(
            static.layers.softmax_with_cross_entropy(logits, label))
        static.Adam(1e-3).minimize(loss)

    exe = static.Executor()
    out, = exe.run(prog, feed={"x": xs, "label": ys}, fetch_list=[loss])
"""

from . import layers
from .control_flow import DynamicRNN, IfElse, StaticRNN, While
from .executor import Executor, Scope, global_scope
from .io import (InferencePredictor, TrainStepRunner, load_inference_model,
                 load_persistables, save_inference_model, save_persistables,
                 save_train_program)
from .optimizer import SGD, Adam, Momentum, Optimizer
from .program import (GRAD_SUFFIX, Program, Var, append_backward,
                      default_main_program, program_guard)

__all__ = [
    "layers", "DynamicRNN", "IfElse", "StaticRNN", "While",
    "Executor", "Scope", "global_scope",
    "InferencePredictor", "TrainStepRunner", "load_inference_model",
    "load_persistables", "save_inference_model", "save_persistables",
    "save_train_program",
    "SGD", "Adam", "Momentum", "Optimizer",
    "GRAD_SUFFIX", "Program", "Var", "append_backward",
    "default_main_program", "program_guard",
]
