"""Block-DSL control flow for static Programs — While / IfElse /
StaticRNN / DynamicRNN as RECORDING CONTEXTS.

Capability equivalent of the reference's sub-block control-flow layers
(reference: python/paddle/fluid/layers/control_flow.py — While.block:635,
IfElse:1489, DynamicRNN:1619, StaticRNN:268; C++ interpreters
paddle/fluid/operators/controlflow/while_op.cc:59,
conditional_block_op.cc, recurrent_op.cc). The reference records body ops
into a nested BlockDesc that a sub-executor interprets per iteration;
here the ``with`` block records ordinary op nodes into the (single-block)
Program, and on exit they are POPPED and re-recorded as ONE op node whose
fn replays them inside ``lax.while_loop`` / ``lax.scan`` — XLA-compiled
structured control flow instead of an op-by-op sub-interpreter.

Write-back convention: the loop state of a While is exactly the set of
pre-existing vars the body writes (via ``assign``-style in-place layers:
``increment(x, in_place=True)``, ``less_than(..., cond=...)``,
``logical_and(..., out=...)``, ``layers.assign(x, output=...)``) plus the
loop condition var — mirroring the reference's requirement that the body
mutate its condition.

Sequence semantics: DynamicRNN consumes the framework's LoD replacement —
padded ``(B, T, ...)`` arrays whose companion lengths var rides on
``Var.lod_src`` (SURVEY §7 ragged canonicalization). Finished rows freeze
their memories and emit zeros, numerically matching the reference's
shrink-batch-by-length execution for pooled/masked consumers.

IfElse keeps the reference's row-routing API (input/output per branch)
but lowers to compute-both-and-mask — the XLA-native form of
split_lod_tensor/merge_lod_tensor (reference: layers/control_flow.py
split_lod_tensor) — valid whenever branch ops are row-independent, which
is what the reference API supports anyway.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import enforce
from .program import (TRACE_BATCH, Program, Var, _OpNode,
                      default_main_program)


def _exec_nodes(nodes, env: Dict[str, Any]) -> Dict[str, Any]:
    for node in nodes:
        args = [env[n] for n in node.inputs]
        out = node.fn(*args)
        if len(node.outputs) == 1:
            env[node.outputs[0]] = out
        else:
            for oname, oval in zip(node.outputs, out):
                env[oname] = oval
    return env


def _analyze(body: Sequence[_OpNode], pre_names, bound: Sequence[str]):
    """Split the body's dataflow: ``writes`` = pre-existing vars the body
    assigns (loop state), ``external`` = names read from outside (params,
    consts, captured activations), ``internal`` = produced inside."""
    internal, writes = set(), []
    for node in body:
        enforce(isinstance(node, _OpNode),
                "append_backward cannot appear inside a control-flow "
                "block — call it on the outer program")
        for o in node.outputs:
            if o in pre_names and o not in writes:
                writes.append(o)
            internal.add(o)
    external = []
    for node in body:
        for n in node.inputs:
            if n not in internal and n not in bound and n not in external:
                external.append(n)
    # a var both read and written must resolve to the carried value, so
    # drop carried names from the external (invariant) set
    external = [n for n in external if n not in writes]
    return writes, external


class While:
    """reference: layers/control_flow.py:593 While, :635 block().

    ::

        cond = layers.less_than(i, n)
        w = While(cond)
        with w.block():
            ...            # body layers; must re-assign `cond`
            layers.increment(i, in_place=True)
            layers.less_than(i, n, cond=cond)
    """

    def __init__(self, cond: Var, is_test: bool = False,
                 name: Optional[str] = None):
        enforce(isinstance(cond, Var),
                "While(cond) needs a static Program Var (build inside "
                "program_guard); eager code uses ops.while_loop")
        self.cond = cond
        self.prog: Program = cond.program

    @contextlib.contextmanager
    def block(self):
        prog = self.prog
        start = len(prog.nodes)
        pre_names = set(prog.vars)
        yield
        body = prog.nodes[start:]
        del prog.nodes[start:]
        for node in body:
            # a TensorArray first written inside the loop is not loop
            # state (its buffer var doesn't pre-exist), so its writes
            # would silently reset every iteration
            enforce(not (node.name == "array_write"
                         and node.inputs
                         and node.inputs[0] not in pre_names),
                    "TensorArray written inside a While block must be "
                    "seeded with an array_write BEFORE the loop so its "
                    "buffer becomes loop-carried state (reference decode "
                    "seeds index 0 pre-loop)")
        writes, external = _analyze(body, pre_names, bound=())
        carry = list(dict.fromkeys([self.cond.name] + writes))
        enforce(self.cond.name in [o for n in body for o in n.outputs],
                "While body never re-assigns its condition %r (use "
                "less_than(..., cond=cond) / logical_and(..., out=cond)) "
                "— the loop would never terminate", self.cond.name)
        n_carry = len(carry)

        def while_fn(*vals, _body=tuple(body), _carry=tuple(carry),
                     _ext=tuple(external), _n=n_carry):
            init = tuple(vals[:_n])
            inv = dict(zip(_ext, vals[_n:]))

            def cond_fn(state):
                c = state[0]
                return jnp.reshape(c, ()).astype(bool)

            def body_fn(state):
                env = dict(inv)
                env.update(zip(_carry, state))
                env = _exec_nodes(_body, env)
                return tuple(env[nm] for nm in _carry)

            out = lax.while_loop(cond_fn, body_fn, init)
            # _OpNode's one-output convention stores fn's return directly;
            # unwrap the 1-tuple so the var keeps its shape
            return out[0] if _n == 1 else out

        # record with explicit output names = the carried vars (write-back)
        node = _OpNode(while_fn, carry + external, list(carry), "while")
        prog.nodes.append(node)
        prog.version += 1


class IfElse:
    """reference: layers/control_flow.py:1489 IfElse. ``cond`` is a
    (N, 1) bool tensor; both branches compute on the full rows and the
    outputs merge by mask (the XLA form of split/merge_lod_tensor)."""

    def __init__(self, cond: Var, name: Optional[str] = None):
        enforce(isinstance(cond, Var), "IfElse(cond) needs a Program Var")
        self.cond = cond
        self.prog: Program = cond.program
        self._branches: Dict[bool, Tuple[List[_OpNode], List[str],
                                         List[str]]] = {}
        self._cur: Optional[bool] = None
        self._outputs: Dict[bool, List[str]] = {True: [], False: []}
        self._external: Dict[bool, List[str]] = {True: [], False: []}
        self._nodes: Dict[bool, List[_OpNode]] = {True: [], False: []}

    def input(self, x: Var) -> Var:
        enforce(self._cur is not None,
                "IfElse.input() must be called inside a branch block")
        return x  # row routing is by mask at merge time

    def output(self, *outs: Var) -> None:
        enforce(self._cur is not None,
                "IfElse.output() must be called inside a branch block")
        # -1 batch placeholders trace as TRACE_BATCH (program.py apply);
        # normalize both sides so batch-polymorphic programs compare
        # consistently
        def _rows(d):
            return TRACE_BATCH if d == -1 else d

        rows = _rows(self.cond.shape[0])
        for v in outs:
            # compute-both-and-mask merges row-wise, so every output must
            # keep the cond's row dimension; a cross-row reduction inside
            # a branch (shape change) would merge garbage
            enforce(v.shape and _rows(v.shape[0]) == rows,
                    "IfElse output %r has shape %s but cond has %s rows: "
                    "branch ops must be row-independent (no cross-row "
                    "reductions) — IfElse lowers to compute-both-and-mask",
                    v.name, tuple(v.shape), rows)
        self._outputs[self._cur].extend(v.name for v in outs)

    @contextlib.contextmanager
    def _branch(self, which: bool):
        enforce(self._cur is None, "IfElse blocks cannot nest")
        prog = self.prog
        self._cur = which
        start = len(prog.nodes)
        pre = set(prog.vars)
        yield
        body = prog.nodes[start:]
        del prog.nodes[start:]
        writes, external = _analyze(body, pre, bound=())
        enforce(not writes, "IfElse branches produce values via "
                ".output(...), not in-place assigns (got %s)", writes)
        self._nodes[which] = list(body)
        self._external[which] = external
        self._cur = None

    def true_block(self):
        return self._branch(True)

    def false_block(self):
        return self._branch(False)

    def __call__(self) -> List[Var]:
        prog = self.prog
        t_out, f_out = self._outputs[True], self._outputs[False]
        enforce(len(t_out) == len(f_out) and t_out,
                "IfElse needs the same number of output() calls in both "
                "blocks (got %s true, %s false)", len(t_out), len(f_out))
        ext = list(dict.fromkeys(self._external[True] +
                                 self._external[False]))

        def ifelse_fn(cond, *vals, _t=tuple(self._nodes[True]),
                      _f=tuple(self._nodes[False]), _ext=tuple(ext),
                      _to=tuple(t_out), _fo=tuple(f_out)):
            env = dict(zip(_ext, vals))
            t_env = _exec_nodes(_t, dict(env))
            f_env = _exec_nodes(_f, dict(env))
            def merge(tv, fv):
                mask = jnp.reshape(cond, (cond.shape[0],) +
                                   (1,) * (tv.ndim - 1))
                return jnp.where(mask.astype(bool), tv, fv)

            outs = tuple(merge(t_env[tn], f_env[fn])
                         for tn, fn in zip(_to, _fo))
            # single output unwraps (the _OpNode one-output convention)
            return outs[0] if len(outs) == 1 else outs

        outs = prog.apply(ifelse_fn, [self.cond] +
                          [prog.vars[n] for n in ext], name="ifelse")
        return list(outs) if isinstance(outs, tuple) else [outs]


class StaticRNN:
    """reference: layers/control_flow.py:268 StaticRNN — fixed-length RNN
    over a (B, T, D) input; ``with rnn.step():`` records one timestep."""

    def __init__(self, name: Optional[str] = None):
        self.prog = default_main_program()
        self._steps: List[Tuple[str, str]] = []   # (placeholder, outer x)
        self._mems: List[Tuple[str, Optional[str], Tuple, float]] = []
        self._updates: Dict[str, str] = {}
        self._outs: List[str] = []
        self._body: List[_OpNode] = []
        self._external: List[str] = []
        self._result: Optional[List[Var]] = None
        self._in_block = False
        self._seq_len: Optional[int] = None

    # -- inside-block API ---------------------------------------------------
    def step_input(self, x: Var) -> Var:
        enforce(self._in_block, "step_input() belongs inside rnn.step()")
        enforce(len(x.shape) >= 2, "step input must be (B, T, ...)")
        if self._seq_len is None:
            self._seq_len = x.shape[1]
        ph = Var(self.prog, self.prog.unique_name("rnn_step_in"),
                 (x.shape[0],) + tuple(x.shape[2:]), x.dtype)
        self.prog.vars[ph.name] = ph
        self._steps.append((ph.name, x.name))
        return ph

    def memory(self, init: Optional[Var] = None,
               shape: Optional[Sequence[int]] = None,
               batch_ref: Optional[Var] = None, init_value: float = 0.0,
               init_batch_dim_idx: int = 0, ref_batch_dim_idx: int = 0,
               value: Optional[float] = None, dtype=None) -> Var:
        enforce(self._in_block, "memory() belongs inside the block")
        if value is not None:
            init_value = value
        if init is not None:
            mshape = tuple(init.shape)
            init_name = init.name
            mdtype = init.dtype
        else:
            enforce(shape is not None, "memory() needs init= or shape=")
            if batch_ref is not None:
                bsz = batch_ref.shape[0]
            else:
                enforce(self._steps,
                        "memory(shape=...) without batch_ref needs a prior "
                        "step_input to infer the batch dim")
                bsz = self.prog.vars[self._steps[0][1]].shape[0]
            mshape = (bsz,) + tuple(shape)
            init_name = None
            mdtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        ph = Var(self.prog, self.prog.unique_name("rnn_mem"), mshape, mdtype)
        self.prog.vars[ph.name] = ph
        self._mems.append((ph.name, init_name, mshape, init_value,
                           jnp.dtype(mdtype)))
        return ph

    def update_memory(self, mem: Var, new: Var) -> None:
        enforce(self._in_block, "update_memory() belongs inside the block")
        self._updates[mem.name] = new.name

    def step_output(self, o: Var) -> None:
        enforce(self._in_block, "step_output() belongs inside the block")
        self._outs.append(o.name)

    output = step_output

    # -- block lifecycle ----------------------------------------------------
    @contextlib.contextmanager
    def step(self):
        prog = self.prog
        self._in_block = True
        start = len(prog.nodes)
        pre = set(prog.vars)
        yield
        body = prog.nodes[start:]
        del prog.nodes[start:]
        bound = {ph for ph, _ in self._steps} | \
                {m[0] for m in self._mems}
        writes, external = _analyze(body, pre, bound=bound)
        enforce(not writes, "StaticRNN/DynamicRNN blocks communicate via "
                "update_memory/output, not in-place assigns (got %s)",
                writes)
        self._body, self._external = list(body), external
        self._in_block = False
        self._record()

    block = step  # DynamicRNN spells it block(); share the machinery

    def _lengths_for(self, prog: Program) -> Optional[str]:
        return None  # StaticRNN: full length

    def _record(self) -> None:
        prog = self.prog
        enforce(self._outs, "rnn block defined no output()")
        enforce(self._steps or self._seq_len is not None,
                "rnn block needs at least one step_input")
        step_phs = [ph for ph, _ in self._steps]
        step_xs = [x for _, x in self._steps]
        mem_phs = [m[0] for m in self._mems]
        mem_inits = [m[1] for m in self._mems]
        init_vars = [n for n in mem_inits if n is not None]
        lens_name = self._lengths_for(prog)
        n_step, n_mem, n_init = len(step_phs), len(mem_phs), len(init_vars)

        def rnn_fn(*vals, _body=tuple(self._body), _phs=tuple(step_phs),
                   _mems=tuple(self._mems), _upd=dict(self._updates),
                   _outs=tuple(self._outs), _ext=tuple(self._external),
                   _masked=lens_name is not None):
            xs = vals[:n_step]
            k = n_step
            lens = None
            if _masked:
                lens = vals[k]
                k += 1
            inits = {n: v for n, v in zip(init_vars, vals[k:k + n_init])}
            k += n_init
            inv = dict(zip(_ext, vals[k:]))
            B = xs[0].shape[0] if xs else 1
            T = xs[0].shape[1] if xs else 1

            mem0 = []
            for (ph, init_name, shape, init_value, mdtype) in _mems:
                if init_name is not None:
                    mem0.append(inits[init_name])
                else:
                    mem0.append(jnp.full((B,) + tuple(shape[1:]),
                                         init_value, mdtype))

            def one(carry, t):
                mems = carry
                env = dict(inv)
                for ph, x in zip(_phs, xs):
                    env[ph] = lax.dynamic_index_in_dim(x, t, 1,
                                                       keepdims=False)
                env.update(zip([m[0] for m in _mems], mems))
                env = _exec_nodes(_body, env)
                new = []
                for (ph, *_rest), old in zip(_mems, mems):
                    cand = env[_upd[ph]] if ph in _upd else old
                    if lens is not None:
                        act = (t < lens).reshape(
                            (-1,) + (1,) * (cand.ndim - 1))
                        cand = jnp.where(act, cand, old)
                    new.append(cand)
                outs = []
                for o in _outs:
                    val = env[o]
                    if lens is not None:
                        act = (t < lens).reshape(
                            (-1,) + (1,) * (val.ndim - 1))
                        val = val * act.astype(val.dtype)
                    outs.append(val)
                return tuple(new), tuple(outs)

            _, stacked = lax.scan(one, tuple(mem0), jnp.arange(T))
            # (T, B, ...) -> (B, T, ...); single output unwraps (the
            # _OpNode one-output convention stores fn's return directly)
            outs_bt = tuple(jnp.moveaxis(s, 0, 1) for s in stacked)
            return outs_bt[0] if len(outs_bt) == 1 else outs_bt

        inputs = (step_xs + ([lens_name] if lens_name else []) +
                  init_vars + self._external)
        out_vars = []
        for o in self._outs:
            inner = prog.vars[o]
            name = prog.unique_name("rnn_out")
            B = self.prog.vars[step_xs[0]].shape[0] if step_xs else -1
            ov = Var(prog, name, (B, self._seq_len) + tuple(inner.shape[1:]),
                     inner.dtype)
            ov.lod_src = (getattr(prog.vars[step_xs[0]], "lod_src", None)
                          if step_xs else None)
            prog.vars[name] = ov
            out_vars.append(ov)
        prog.nodes.append(_OpNode(rnn_fn, list(inputs),
                                  [v.name for v in out_vars], "rnn"))
        prog.version += 1
        self._result = out_vars

    def __call__(self) -> Any:
        enforce(self._result is not None,
                "call the rnn after its block closes")
        return (self._result[0] if len(self._result) == 1
                else tuple(self._result))


class DynamicRNN(StaticRNN):
    """reference: layers/control_flow.py:1619 DynamicRNN — variable-length
    RNN over the padded+lengths LoD replacement. ``step_input`` takes a
    lod-carrying (B, T, ...) var; finished rows freeze memories and emit
    zeros (numerically equal to the reference's length-sorted shrinking
    batch for masked/pooled consumers)."""

    def __init__(self, lod_level: int = 1, name: Optional[str] = None):
        super().__init__(name=name)
        self._lens: Optional[str] = None

    def step_input(self, x: Var, level: int = 0) -> Var:
        ph = super().step_input(x)
        lens = getattr(x, "lod_src", None)
        if lens is not None and self._lens is None:
            self._lens = lens
        return ph

    def static_input(self, x: Var) -> Var:
        # per-sequence invariant input: visible to every step as-is
        return x

    def _lengths_for(self, prog: Program) -> Optional[str]:
        return self._lens


class Switch:
    """reference: layers/control_flow.py Switch — first-match-wins case
    chain, used by piecewise LR schedules::

        with Switch() as switch:
            with switch.case(step < b1):
                assign(lr1, output=lr)
            with switch.default():
                assign(lr2, output=lr)

    Lowering: every case body records unconditionally (compute-all), and
    each outer var written by any body selects its final value by the
    FIRST true condition (jnp.where chain) — the XLA form of the
    reference's conditional_block dispatch. Bodies communicate only via
    in-place writes to pre-existing vars (assign(output=)/increment),
    matching the reference's usage."""

    def __init__(self, name: Optional[str] = None):
        self.prog: Program = default_main_program()
        # (cond_name or None, body nodes, writes, external reads)
        self._cases: List[Tuple[Optional[str], List[_OpNode], List[str],
                                List[str]]] = []
        self._entered = False

    def __enter__(self) -> "Switch":
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self._lower()
        return False

    @contextlib.contextmanager
    def _capture(self, cond: Optional[Var]):
        enforce(self._entered,
                "use Switch inside a `with Switch() as switch:` block")
        enforce(cond is None or isinstance(cond, Var),
                "switch.case(cond) needs a Program Var condition")
        enforce(not (self._cases and self._cases[-1][0] is None),
                "default() must be the last Switch block")
        prog = self.prog
        start = len(prog.nodes)
        pre = set(prog.vars)
        yield
        body = prog.nodes[start:]
        del prog.nodes[start:]
        writes, external = _analyze(body, pre, bound=())
        enforce(writes, "a Switch block must write at least one outer "
                "var (assign(..., output=var))")
        self._cases.append((cond.name if cond is not None else None,
                            list(body), writes, external))

    def case(self, cond: Var):
        return self._capture(cond)

    def default(self):
        return self._capture(None)

    def _lower(self) -> None:
        enforce(self._cases, "Switch recorded no case blocks")
        prog = self.prog
        all_writes: List[str] = []
        for _c, _b, writes, _e in self._cases:
            for w in writes:
                if w not in all_writes:
                    all_writes.append(w)
        cond_names = [c for c, *_ in self._cases if c is not None]
        externals: List[str] = []
        for _c, _b, _w, ext in self._cases:
            for e in ext:
                if e not in externals and e not in all_writes:
                    externals.append(e)
        n_w, n_c = len(all_writes), len(cond_names)
        cases = [(c, tuple(b), tuple(w))
                 for c, b, w, _e in self._cases]

        def switch_fn(*vals):
            init = dict(zip(all_writes, vals[:n_w]))
            conds = dict(zip(cond_names, vals[n_w:n_w + n_c]))
            env0 = dict(zip(externals, vals[n_w + n_c:]))
            env0.update(init)
            # evaluate every body from the same pre-switch env
            outs = []
            for cname, body, writes in cases:
                env = dict(env0)
                env = _exec_nodes(body, env)
                outs.append({w: env[w] for w in writes})
            # first-match-wins: fold the chain from the last case up.
            # A true case owns ALL outer vars, not just the ones it
            # writes — untouched vars keep their pre-switch value, as the
            # reference runs only the first true block.
            final = dict(init)
            for (cname, _b, writes), got in zip(reversed(cases),
                                                reversed(outs)):
                if cname is None:
                    for w in writes:
                        final[w] = got[w]
                    continue
                c = jnp.reshape(conds[cname], ()).astype(bool)
                for w in all_writes:
                    final[w] = jnp.where(c, got.get(w, init[w]), final[w])
            # single write unwraps (the _OpNode one-output convention
            # stores fn's return directly)
            return (final[all_writes[0]] if n_w == 1
                    else tuple(final[w] for w in all_writes))

        node = _OpNode(switch_fn,
                       all_writes + cond_names + externals,
                       list(all_writes), "switch")
        prog.nodes.append(node)
        prog.version += 1
