"""Static-graph layer functions — fluid `layers.*` capability surface
(reference: python/paddle/fluid/layers/nn.py, 184 functions; fc:210) as
thin recorders over the functional op library: each call creates params on
the current Program and records one traced op node.

Param creation mirrors LayerHelper (reference: layer_helper.py:29).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import initializer as I
from ..ops import loss as OL
from ..core.enforce import enforce
from ..ops import math as OM
from ..ops import nn as ON
from .program import Program, Var, default_main_program


def _prog(*vars_) -> Program:
    for v in vars_:
        if isinstance(v, Var):
            return v.program
    return default_main_program()


def shared_param(prog: Program, pname: str, shape, init) -> Var:
    """Get-or-create a named, shareable parameter — the one sharing
    protocol for param_attr layers (fc, embedding): an existing var must
    be a real parameter of the matching shape (a silent collision with a
    feed/op-output var would train nothing)."""
    if pname in prog.vars:
        v = prog.vars[pname]
        enforce(v.is_param,
                "param_attr %r collides with a non-parameter var — "
                "pick a different name", pname)
        enforce(tuple(v.shape) == tuple(shape),
                "shared param %s has shape %s, this layer needs %s",
                pname, tuple(v.shape), tuple(shape))
        return v
    return prog.create_parameter(pname, tuple(shape), initializer=init)


def fc(input, size: int, act: Optional[str] = None,
       bias_attr: bool = True, name: str = "fc",
       param_attr=None) -> Var:
    """reference: layers/nn.py fc:210. A LIST input gets one weight per
    entry and the projections sum (the reference's multi-input mul+sum).

    ``param_attr`` with a name pins EXACT weight names, enabling the
    reference's cross-program weight sharing — the book pattern where
    decoder_decode reuses decoder_train's weights through the scope
    (reference: tests/book/test_machine_translation.py). A single
    (non-list) input uses ``<name>`` verbatim; a LIST input appends
    ``_0``, ``_1``, ... per entry; the bias gets ``<name>.b``. Keep the
    input STRUCTURE identical across sharing programs — mixing the bare
    and suffixed forms for one name in the same program is rejected."""
    is_list = isinstance(input, (list, tuple))
    inputs = list(input) if is_list else [input]
    prog = _prog(*inputs)
    attr_name = getattr(param_attr, "name", None) or (
        param_attr if isinstance(param_attr, str) else None)
    if attr_name is not None:
        # input-structure registry: two fc calls sharing one name must
        # agree on structure (bare weight for a single input, _0.._k-1
        # for a k-list), or their weight names fork silently. Cross-
        # PROGRAM mixing cannot be detected at build time — keep the
        # input structure identical across sharing programs.
        arity = len(inputs) if is_list else 0  # 0 = single non-list
        registry = getattr(prog, "_fc_shared_arity", None)
        if registry is None:
            registry = prog._fc_shared_arity = {}
        prev = registry.get(attr_name)
        enforce(prev is None or prev == arity,
                "param_attr %r was used by an fc with %s input(s); this "
                "fc has %s — weight names differ by input structure, so "
                "these calls would NOT share", attr_name,
                "a single non-list" if prev == 0 else prev,
                "a single non-list" if arity == 0 else arity)
        registry[attr_name] = arity

    def wname(i):
        if attr_name is None:
            return prog.unique_name(f"{name}_w")
        return f"{attr_name}_{i}" if is_list else attr_name

    ws = [shared_param(prog, wname(i), (x.shape[-1], size),
                       I.XavierUniform())
          for i, x in enumerate(inputs)]
    args = inputs + ws
    if bias_attr:
        bname = (f"{attr_name}.b" if attr_name is not None
                 else prog.unique_name(f"{name}_b"))
        args.append(shared_param(prog, bname, (size,), I.Constant(0.0)))
    k = len(inputs)

    def fn(*vals):
        xs, rest = vals[:k], vals[k:]
        ws_, b = rest[:k], (rest[k] if bias_attr else None)
        y = sum(x @ w for x, w in zip(xs, ws_))
        if b is not None:
            y = y + b
        if act is not None:
            y = getattr(jax.nn, act, getattr(OM, act, None))(y)
        return y

    return prog.apply(fn, args, name=name)


def conv2d(input: Var, num_filters: int, filter_size: int, stride: int = 1,
           padding: int = 0, groups: int = 1, act: Optional[str] = None,
           bias_attr: bool = True, name: str = "conv2d") -> Var:
    prog = _prog(input)
    c_in = input.shape[1]
    w = prog.create_parameter(
        prog.unique_name(f"{name}_w"),
        (num_filters, c_in // groups, filter_size, filter_size),
        initializer=I.MSRA(uniform=False))
    args = [input, w]
    if bias_attr:
        b = prog.create_parameter(prog.unique_name(f"{name}_b"),
                                  (num_filters,), initializer=I.Constant(0.0))
        args.append(b)

    def fn(x, w, b=None):
        y = ON.conv2d(x, w, stride, padding, 1, groups)
        if b is not None:
            y = y + b.reshape(1, -1, 1, 1)
        if act is not None:
            y = getattr(jax.nn, act)(y)
        return y

    return prog.apply(fn, args, name=name)


def embedding(input: Var, size: Sequence[int], padding_idx=None,
              is_sparse: bool = False, is_distributed: bool = False,
              param_attr=None, dtype=None, name: str = "embedding") -> Var:
    """``param_attr`` with a name enables the reference's cross-layer
    param sharing (e.g. the MT book model's shared 'vemb' table);
    ``is_sparse`` is advisory — gradients are dense under XLA and giant
    tables shard via parallel.ShardedEmbedding (OP_COVERAGE.md)."""
    prog = _prog(input)
    attr_name = getattr(param_attr, "name", None) or (
        param_attr if isinstance(param_attr, str) else None)
    w = shared_param(prog, attr_name or prog.unique_name(f"{name}_w"),
                     tuple(size), I.XavierNormal())
    return prog.apply(lambda ids, t: ON.embedding(ids, t, padding_idx),
                      [input, w], name=name)


def _unary(fnname, jfn):
    def layer(x: Var, name: Optional[str] = None) -> Var:
        return _prog(x).apply(jfn, [x], name=name or fnname)

    layer.__name__ = fnname
    return layer


relu = _unary("relu", jax.nn.relu)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
softmax = _unary("softmax", lambda x: jax.nn.softmax(x, axis=-1))
exp = _unary("exp", jnp.exp)
log = _unary("log", jnp.log)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)


def mean(x: Var, name: str = "mean") -> Var:
    """LoD-aware: a padded sequence tensor averages over REAL tokens only
    (the reference's mean over a LoDTensor counts actual rows)."""
    prog = _prog(x)
    lens = getattr(x, "lod_src", None)
    if lens is not None and lens in prog.vars:
        def fn(a, ln):
            t = a.shape[1]
            m = (jnp.arange(t)[None, :] < ln[:, None]).astype(a.dtype)
            m = m.reshape(m.shape + (1,) * (a.ndim - 2))
            return jnp.sum(a * m) / jnp.maximum(
                jnp.sum(m) * float(np.prod(a.shape[2:], dtype=np.int64)
                                   or 1), 1.0)

        out = prog.apply(fn, [x, prog.vars[lens]], name=name)
        out.lod_src = None
        return out
    return prog.apply(jnp.mean, [x], name=name)


def reduce_sum(x: Var, dim=None, keep_dim: bool = False) -> Var:
    return _prog(x).apply(
        lambda a: jnp.sum(a, axis=dim, keepdims=keep_dim), [x],
        name="reduce_sum")


def reshape(x: Var, shape: Sequence[int]) -> Var:
    return _prog(x).apply(lambda a: jnp.reshape(a, shape), [x],
                          name="reshape")


def transpose(x: Var, perm: Sequence[int]) -> Var:
    return _prog(x).apply(lambda a: jnp.transpose(a, perm), [x],
                          name="transpose")


def concat(xs: Sequence[Var], axis: int = 0) -> Var:
    prog = _prog(*xs)
    return prog.apply(lambda *a: jnp.concatenate(a, axis=axis), list(xs),
                      name="concat")


def dropout(x: Var, dropout_prob: float = 0.5, seed: int = 0,
            is_test: bool = False) -> Var:
    """Static dropout uses a fixed fold-in key per recorded op (the dygraph
    path owns stateful RNG; reference: operators/dropout_op.cc)."""
    if is_test or dropout_prob == 0.0:
        return x
    prog = _prog(x)
    opid = prog._name_counter + 1
    key = jax.random.fold_in(jax.random.key(seed), opid)

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - dropout_prob, a.shape)
        return jnp.where(keep, a / (1.0 - dropout_prob), 0.0)

    return prog.apply(fn, [x], name="dropout", eval_fn=lambda a: a)


def cross_entropy(input: Var, label: Var, soft_label: bool = False) -> Var:
    return _prog(input).apply(
        lambda p, l: OL.cross_entropy(p, l, soft_label=soft_label),
        [input, label], name="cross_entropy")


def softmax_with_cross_entropy(logits: Var, label: Var) -> Var:
    return _prog(logits).apply(OL.softmax_with_cross_entropy,
                               [logits, label],
                               name="softmax_with_cross_entropy")


def accuracy(input: Var, label: Var) -> Var:
    from ..metrics import accuracy as acc_fn

    return _prog(input).apply(acc_fn, [input, label], name="accuracy")


def batch_norm(input: Var, act: Optional[str] = None, is_test: bool = False,
               momentum: float = 0.9, epsilon: float = 1e-5,
               name: str = "batch_norm") -> Var:
    """Static BN: scale/bias trainable; running stats are persistable
    non-trainable vars updated through the step (mirrors the reference's
    batch_norm_op in-place MeanOut/VarianceOut)."""
    prog = _prog(input)
    c = input.shape[1]
    scale = prog.create_parameter(prog.unique_name(f"{name}_scale"), (c,),
                                  initializer=I.Constant(1.0))
    bias = prog.create_parameter(prog.unique_name(f"{name}_bias"), (c,),
                                 initializer=I.Constant(0.0))
    rmean = prog.create_parameter(prog.unique_name(f"{name}_mean"), (c,),
                                  initializer=I.Constant(0.0),
                                  trainable=False)
    rvar = prog.create_parameter(prog.unique_name(f"{name}_var"), (c,),
                                 initializer=I.Constant(1.0),
                                 trainable=False)

    def make_fn(training):
        def fn(x, s, b, m, v):
            y, nm, nv = ON.batch_norm(x, s, b, m, v, training=training,
                                      momentum=momentum, epsilon=epsilon)
            if act is not None:
                y = getattr(jax.nn, act)(y)
            return y, nm, nv

        return fn

    y, nm, nv = prog.apply(make_fn(not is_test),
                           [input, scale, bias, rmean, rvar],
                           name=name, eval_fn=make_fn(False))
    prog.assign(rmean, nm)
    prog.assign(rvar, nv)
    return y


# ---------------------------------------------------------------------------
# in-place write layers (block-DSL state plumbing)
# ---------------------------------------------------------------------------
# The reference's While/optimizer bodies mutate vars through op outputs
# (reference: layers/control_flow.py increment in_place, layers/ops
# less_than(cond=...), logical_and(out=...)); here a write to an existing
# var records Program.assign, which the block-DSL lowering turns into loop
# carry state (static/control_flow.py).


def assign(input: Var, output: Optional[Var] = None) -> Var:
    prog = _prog(input, output)
    out = prog.apply(lambda a: a, [input], name="assign_value")
    if output is not None:
        prog.assign(output, out)
        return output
    return out


def increment(x: Var, value: float = 1.0, in_place: bool = True) -> Var:
    prog = _prog(x)
    out = prog.apply(lambda a: a + jnp.asarray(value, a.dtype), [x],
                     name="increment")
    if in_place:
        prog.assign(x, out)
        return x
    return out


def _compare(name, jfn):
    def layer(x: Var, y, force_cpu: Optional[bool] = None,
              cond: Optional[Var] = None) -> Var:
        prog = _prog(x, y, cond)
        out = prog.apply(jfn, [x, y], name=name)
        if cond is not None:
            prog.assign(cond, out)
            return cond
        return out

    layer.__name__ = name
    return layer


less_than = _compare("less_than", jnp.less)
less_equal = _compare("less_equal", jnp.less_equal)
greater_than = _compare("greater_than", jnp.greater)
greater_equal = _compare("greater_equal", jnp.greater_equal)
equal = _compare("equal", jnp.equal)
not_equal = _compare("not_equal", jnp.not_equal)


def _logical(name, jfn, unary=False):
    if unary:
        def layer(x: Var, out: Optional[Var] = None,
                  name_: Optional[str] = None) -> Var:
            prog = _prog(x, out)
            o = prog.apply(jfn, [x], name=name)
            if out is not None:
                prog.assign(out, o)
                return out
            return o
    else:
        def layer(x: Var, y: Var, out: Optional[Var] = None,
                  name_: Optional[str] = None) -> Var:
            prog = _prog(x, y, out)
            o = prog.apply(jfn, [x, y], name=name)
            if out is not None:
                prog.assign(out, o)
                return out
            return o

    layer.__name__ = name
    return layer


logical_and = _logical("logical_and", jnp.logical_and)
logical_or = _logical("logical_or", jnp.logical_or)
logical_xor = _logical("logical_xor", jnp.logical_xor)
logical_not = _logical("logical_not", jnp.logical_not, unary=True)


def fill_constant(shape, dtype, value, force_cpu: bool = False,
                  out: Optional[Var] = None) -> Var:
    from ..core.dtypes import to_dtype

    prog = _prog(out)
    o = prog.apply(
        lambda: jnp.full(tuple(shape), value, to_dtype(dtype)),
        [], name="fill_constant")
    if out is not None:
        prog.assign(out, o)
        return out
    return o


def zeros(shape, dtype="float32", force_cpu: bool = False) -> Var:
    return fill_constant(shape, dtype, 0.0)


# ---------------------------------------------------------------------------
# sequence layers over the padded+lengths LoD replacement
# ---------------------------------------------------------------------------


def _lens_var(prog: Program, x: Var, what: str) -> Var:
    lens = getattr(x, "lod_src", None)
    from ..core.enforce import enforce as _enf

    _enf(lens is not None and lens in prog.vars,
         "%s needs sequence (lod_level>=1) input; %s carries no lengths "
         "companion", what, x.name)
    return prog.vars[lens]


def dynamic_lstm(input: Var, size: int, use_peepholes: bool = True,
                 is_reverse: bool = False, gate_activation: str = "sigmoid",
                 cell_activation: str = "tanh",
                 candidate_activation: str = "tanh",
                 name: str = "dynamic_lstm"):
    """reference: layers/nn.py dynamic_lstm — ``input`` is the already
    x-projected (B, T, 4H) sequence; this layer owns the recurrent weight
    (H, 4H) and gate bias. Peepholes are subsumed by the gate bias on the
    masked-scan design (reference peephole weights extend the bias vector;
    documented deviation). Returns (hidden (B,T,H), cell-final)."""
    prog = _prog(input)
    H = size // 4
    w_hh = prog.create_parameter(prog.unique_name(f"{name}_w"), (H, 4 * H),
                                 initializer=I.XavierUniform())
    b = prog.create_parameter(prog.unique_name(f"{name}_b"), (4 * H,),
                              initializer=I.Constant(0.0))
    lens = _lens_var(prog, input, "dynamic_lstm")

    def fn(x, w, bias, ln):
        from ..ops import rnn as RN

        eye = jnp.eye(x.shape[-1], dtype=x.dtype)  # input already projected
        outs, (h_t, c_t) = RN.lstm(
            x, eye, w, bias=bias, lengths=ln, is_reverse=is_reverse,
            gate_activation=gate_activation, cell_activation=cell_activation,
            candidate_activation=candidate_activation)
        return outs, c_t

    hidden, cell = prog.apply(fn, [input, w_hh, b, lens], name=name)
    hidden.lod_src = input.lod_src
    return hidden, cell


def sequence_last_step(input: Var, name: str = "sequence_last_step") -> Var:
    prog = _prog(input)
    lens = _lens_var(prog, input, "sequence_last_step")

    def fn(x, ln):
        idx = jnp.maximum(ln - 1, 0)
        return jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)

    out = prog.apply(fn, [input, lens], name=name)
    out.lod_src = None
    return out


def sequence_first_step(input: Var, name: str = "sequence_first_step") -> Var:
    out = _prog(input).apply(lambda x: x[:, 0], [input], name=name)
    out.lod_src = None
    return out


def sequence_pool(input: Var, pool_type: str = "sum",
                  name: str = "sequence_pool") -> Var:
    from ..ops import sequence as SQ

    prog = _prog(input)
    lens = _lens_var(prog, input, "sequence_pool")
    out = prog.apply(lambda x, ln: SQ.sequence_pool(x, ln, pool_type),
                     [input, lens], name=name)
    out.lod_src = None
    return out


# ---------------------------------------------------------------------------
# static TensorArray (block-DSL state buffers)
# ---------------------------------------------------------------------------
# reference: layers/control_flow.py create_array / tensor_array ops +
# operators/controlflow/tensor_array_read_write_op.cc. The reference grows
# LoDTensorArrays dynamically; XLA needs static shapes, so the array is a
# fixed-capacity (cap, ...) buffer var written by dynamic index — writes
# inside While blocks become loop carry state automatically.


class StaticArray:
    """Handle pairing a Program with a lazily-created buffer var plus a
    live element count (the buffer itself is capacity-padded — XLA needs
    static shapes — while ``size`` tracks the highest written index)."""

    def __init__(self, prog: Program, dtype, capacity: int):
        self.prog = prog
        self.dtype = dtype
        self.capacity = capacity
        self.buffer: Optional[Var] = None
        self.size: Optional[Var] = None

    def _ensure(self, x: Var) -> Var:
        if self.buffer is None:
            cap = self.capacity
            # shape comes from the seed value AT TRACE TIME so the buffer
            # stays batch-polymorphic (recorded Var shapes resolve -1
            # to a placeholder and must not be baked into the zeros)
            buf = self.prog.apply(
                lambda v: jnp.zeros((cap,) + v.shape, v.dtype),
                [x], name="tensor_array")
            self.buffer = buf
            self.size = self.prog.apply(
                lambda: jnp.zeros((), jnp.int32), [], name="array_size")
        return self.buffer


def create_array(dtype="float32", capacity: int = 64) -> StaticArray:
    from .program import default_main_program

    return StaticArray(default_main_program(), dtype, capacity)


def array_write(x: Var, i: Var, array: Optional[StaticArray] = None,
                capacity: int = 64) -> StaticArray:
    prog = _prog(x, i)
    if array is None:
        array = StaticArray(prog, x.dtype, capacity)
    buf = array._ensure(x)

    def fn(b, v, idx):
        return b.at[jnp.reshape(idx, ()).astype(jnp.int32)].set(
            v.astype(b.dtype))

    out = prog.apply(fn, [buf, x, i], name="array_write")
    prog.assign(buf, out)
    new_size = prog.apply(
        lambda s, idx: jnp.maximum(s, jnp.reshape(idx, ())
                                   .astype(jnp.int32) + 1),
        [array.size, i], name="array_size_update")
    prog.assign(array.size, new_size)
    return array


def array_read(array: StaticArray, i: Var) -> Var:
    from ..core.enforce import enforce as _enf

    _enf(array.buffer is not None,
         "array_read before any array_write — the buffer has no shape yet")
    prog = array.prog

    def fn(b, idx):
        return jax.lax.dynamic_index_in_dim(
            b, jnp.reshape(idx, ()).astype(jnp.int32), 0, keepdims=False)

    return prog.apply(fn, [array.buffer, i], name="array_read")


def array_length(array: StaticArray) -> Var:
    """True element count (highest written index + 1), NOT the static
    capacity — matches the eager array's length semantics."""
    from ..core.enforce import enforce as _enf

    _enf(array.size is not None,
         "array_length before any array_write — the array is empty")
    return array.size


def tensor_array_to_tensor(array: StaticArray, axis: int = 0):
    """Stacked buffer + true element count. The stacked tensor is
    capacity-padded with zeros past ``n`` (XLA static shapes); slice with
    ``n`` on the host or mask downstream."""
    prog = array.prog
    out = prog.apply(lambda b: jnp.moveaxis(b, 0, axis), [array.buffer],
                     name="tensor_array_to_tensor")
    return out, array.size
