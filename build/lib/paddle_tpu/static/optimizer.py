"""Static-graph optimizers: ``minimize`` = append_backward + recorded
update ops (reference: python/paddle/fluid/optimizer.py:49 —
minimize = append_backward + _create_optimization_pass; sgd_op.cc,
adam_op.cc, momentum_op.cc). Accumulators are persistable non-trainable
vars in the Program, exactly the reference's accumulator mechanism
(optimizer.py _add_accumulator)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp

from .. import initializer as I
from .program import Program, Var, append_backward


class Optimizer:
    def __init__(self, learning_rate: float):
        self.lr = learning_rate

    def minimize(self, loss: Var,
                 parameter_list: Optional[Sequence[str]] = None
                 ) -> List[Tuple[Var, Var]]:
        prog = loss.program
        pairs = append_backward(loss, parameter_list)
        for param, grad in pairs:
            self._append_update(prog, param, grad)
        return pairs

    def _append_update(self, prog: Program, param: Var, grad: Var) -> None:
        raise NotImplementedError


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op.cc."""

    def _append_update(self, prog, param, grad):
        new_p = prog.apply(lambda p, g: p - self.lr * g, [param, grad],
                           name=f"sgd_{param.name}")
        prog.assign(param, new_p)


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op.cc."""

    def __init__(self, learning_rate: float, momentum: float = 0.9,
                 use_nesterov: bool = False):
        super().__init__(learning_rate)
        self.mu = momentum
        self.nesterov = use_nesterov

    def _append_update(self, prog, param, grad):
        vel = prog.create_parameter(
            prog.unique_name(f"{param.name}_velocity"), param.shape,
            param.dtype, initializer=I.Constant(0.0), trainable=False)

        def fn(p, g, v):
            v_new = self.mu * v + g
            if self.nesterov:
                p_new = p - self.lr * (g + self.mu * v_new)
            else:
                p_new = p - self.lr * v_new
            return p_new, v_new

        p_new, v_new = prog.apply(fn, [param, grad, vel],
                                  name=f"momentum_{param.name}")
        prog.assign(param, p_new)
        prog.assign(vel, v_new)


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op.cc."""

    def __init__(self, learning_rate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def _append_update(self, prog, param, grad):
        m = prog.create_parameter(prog.unique_name(f"{param.name}_moment1"),
                                  param.shape, param.dtype,
                                  initializer=I.Constant(0.0),
                                  trainable=False)
        v = prog.create_parameter(prog.unique_name(f"{param.name}_moment2"),
                                  param.shape, param.dtype,
                                  initializer=I.Constant(0.0),
                                  trainable=False)
        t = prog.create_parameter(prog.unique_name(f"{param.name}_step"),
                                  (), jnp.float32,
                                  initializer=I.Constant(0.0),
                                  trainable=False)

        def fn(p, g, m, v, t):
            t_new = t + 1.0
            m_new = self.b1 * m + (1 - self.b1) * g
            v_new = self.b2 * v + (1 - self.b2) * g * g
            m_hat = m_new / (1 - self.b1 ** t_new)
            v_hat = v_new / (1 - self.b2 ** t_new)
            p_new = p - self.lr * m_hat / (jnp.sqrt(v_hat) + self.eps)
            return p_new, m_new, v_new, t_new

        p_new, m_new, v_new, t_new = prog.apply(
            fn, [param, grad, m, v, t], name=f"adam_{param.name}")
        prog.assign(param, p_new)
        prog.assign(m, m_new)
        prog.assign(v, v_new)
        prog.assign(t, t_new)
