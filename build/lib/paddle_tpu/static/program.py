"""Static-graph front end: Program / Var / program_guard.

Capability equivalent of fluid's graph-building core (reference:
python/paddle/fluid/framework.py — Variable:366, Operator:924,
Block:1369 append_op:1665, Program:2704, program_guard:3681), re-designed
for XLA: instead of a protobuf ProgramDesc interpreted op-by-op
(reference: framework/executor.cc:149), a Program records a DAG of
**Python-traceable op nodes**; the Executor JIT-compiles any
(feed, fetch) slice of it into one XLA executable and caches it — the
per-op interpreter hot loop (reference: framework/operator.cc:881
RunImpl) becomes a single compiled program.

Autodiff parity: ``append_backward`` (reference: backward.py:394) records
a grad node that differentiates the traced prefix with ``jax.grad`` —
the VJP-rule registry plays the role of ``GradOpDescMaker``
(reference: framework/grad_op_desc_maker.h:36).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.enforce import enforce
from ..core.dtypes import default_dtype

GRAD_SUFFIX = "@GRAD"

# substitute for -1 batch placeholders when abstract-evaluating recorded
# ops; shape checks that compare placeholder dims must use the same value
TRACE_BATCH = 8


class Var:
    """Symbolic handle inside a Program (reference: framework.py:366
    Variable) with math-op patching (reference: layers/math_op_patch.py)."""

    def __init__(self, program: "Program", name: str, shape: Tuple[int, ...],
                 dtype, *, is_param: bool = False, is_feed: bool = False,
                 trainable: bool = True):
        self.program = program
        self.name = name
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.is_param = is_param
        self.is_feed = is_feed
        self.trainable = trainable
        # LoD replacement metadata: name of the companion lengths var for
        # padded (B, T, ...) sequence data (SURVEY §7 ragged
        # canonicalization); propagated through recorded ops
        self.lod_src: Optional[str] = None
        # level-2 nested LoD: companion (B, N) per-sub-sequence lengths
        self.lod_src2: Optional[str] = None

    # -- math-op patching ---------------------------------------------------
    def _binop(self, other, fn, opname):
        # non-Var operands are captured as constants by Program.apply
        return self.program.apply(fn, [self, other], name=opname)

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b, "elementwise_sub")

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: b - a, "elementwise_sub")

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b, "elementwise_div")

    def __matmul__(self, o):
        return self._binop(o, lambda a, b: a @ b, "matmul")

    def __neg__(self):
        return self.program.apply(lambda a: -a, [self], name="scale")

    def __pow__(self, o):
        return self._binop(o, lambda a, b: a ** b, "elementwise_pow")

    def __repr__(self):
        kind = "param" if self.is_param else ("feed" if self.is_feed else "var")
        return f"Var({self.name!r}, {kind}, shape={self.shape}, dtype={self.dtype})"


class _OpNode:
    """One recorded op: pure fn over named inputs → named outputs."""

    __slots__ = ("fn", "inputs", "outputs", "name", "attrs")

    def __init__(self, fn: Callable, inputs: List[str], outputs: List[str],
                 name: str, attrs: Optional[dict] = None):
        self.fn = fn
        self.inputs = inputs
        self.outputs = outputs
        self.name = name
        self.attrs = attrs or {}


class _GradNode:
    """Backward marker (append_backward): differentiates the prefix program
    ending at `loss_name` w.r.t. `param_names`, emitting `<p>@GRAD` vars."""

    __slots__ = ("prefix_len", "loss_name", "param_names", "outputs", "name")

    def __init__(self, prefix_len: int, loss_name: str,
                 param_names: List[str]):
        self.prefix_len = prefix_len
        self.loss_name = loss_name
        self.param_names = param_names
        self.outputs = [p + GRAD_SUFFIX for p in param_names]
        self.name = "grad"


class Program:
    """Recorded op DAG (reference: framework.py:2704 Program). ``version``
    bumps on every mutation — part of the Executor's compile-cache key."""

    def __init__(self):
        self.nodes: List[Any] = []
        self.vars: Dict[str, Var] = {}
        self.param_inits: Dict[str, Tuple[Callable, Tuple[int, ...], Any]] = {}
        self.version = 0
        self._name_counter = 0

    # -- fluid block API (reference framework.py Program.block:2704ff).
    # This Program is single-block by design: nesting lives inside traced
    # functions (lax.cond/scan sub-traces), not desc sub-blocks — so the
    # Program IS its global block.
    def global_block(self):
        return self

    def current_block(self):
        return self

    def block(self, index: int = 0):
        return self

    def var(self, name: str) -> Var:
        """reference: framework.py Block.var — name lookup with a typed
        error."""
        enforce(name in self.vars, "program has no var %s", name)
        return self.vars[name]

    def list_vars(self):
        return list(self.vars.values())

    def to_string(self, throw_on_error: bool = False, with_details=False):
        return repr(self)

    @staticmethod
    def parse_from_string(binary_str):
        from ..core.enforce import EnforceError

        raise EnforceError(
            "the serialized program format is the StableHLO artifact — "
            "load with static.io.load_inference_model / the C++ predictor "
            "(SURVEY §7: ProgramDesc → serialized HLO + metadata)")

    # -- naming -------------------------------------------------------------
    def unique_name(self, stem: str) -> str:
        self._name_counter += 1
        prefix = getattr(self, "_name_prefix", "")
        return f"{prefix}{stem}_{self._name_counter}"

    # -- graph building -----------------------------------------------------
    def data(self, name: str, shape: Sequence[int], dtype=None,
             lod_level: int = 0) -> Var:
        """Feed placeholder (reference: layers/io.py data). Leading -1 means
        batch-polymorphic (resolved per-run; distinct sizes recompile).

        ``lod_level >= 1`` declares variable-length sequence data: the var
        becomes padded ``(-1, -1, *shape)`` (a trailing ``[1]`` elem shape
        collapses, matching the reference's per-token scalars) and a
        companion ``<name>@LEN`` int32 feed var carries the row lengths —
        the LoD-offsets replacement (reference: framework/lod_tensor.h:110;
        DataFeeder pads ragged batches and fills both)."""
        dtype = dtype or default_dtype()
        enforce(name not in self.vars, "var %s already exists", name)
        if lod_level >= 2:
            # nested LoD (reference: framework/lod_tensor.h:229 level-2
            # offsets — e.g. per-source candidate lists): padded
            # (B, N, T, *elem) with TWO companions — <name>@LEN (B,) =
            # sub-sequence count per sample, <name>@LEN2 (B, N) =
            # token count per sub-sequence (0-padded)
            enforce(lod_level == 2,
                    "lod_level > 2 is not supported (the reference book "
                    "models use at most level-2 results)")
            elem = tuple(d for d in shape if d != -1)
            if elem and elem[-1] == 1:
                elem = elem[:-1]
            v = Var(self, name, (-1, -1, -1) + elem, dtype, is_feed=True)
            lv = Var(self, name + "@LEN", (-1,), jnp.int32, is_feed=True)
            lv2 = Var(self, name + "@LEN2", (-1, -1), jnp.int32,
                      is_feed=True)
            self.vars[name + "@LEN"] = lv
            self.vars[name + "@LEN2"] = lv2
            v.lod_src = lv.name
            v.lod_src2 = lv2.name
        elif lod_level == 1:
            elem = tuple(d for d in shape if d != -1)  # -1 = old-style
            # batch placeholder; per-token scalars declare shape [1]
            if elem and elem[-1] == 1:
                elem = elem[:-1]
            v = Var(self, name, (-1, -1) + elem, dtype, is_feed=True)
            lv = Var(self, name + "@LEN", (-1,), jnp.int32, is_feed=True)
            self.vars[name + "@LEN"] = lv
            v.lod_src = lv.name
        else:
            v = Var(self, name, tuple(shape), dtype, is_feed=True)
        self.vars[name] = v
        self.version += 1
        return v

    def create_parameter(self, name: str, shape: Sequence[int], dtype=None,
                         initializer: Optional[Callable] = None,
                         trainable: bool = True) -> Var:
        """Trainable parameter; its initializer becomes part of the startup
        program (reference: framework.py:3476 Parameter + initializer.py
        ops emitted into the startup program). ``trainable=False`` makes a
        persistable state var (optimizer accumulators, step counters)."""
        from ..initializer import XavierUniform

        dtype = dtype or default_dtype()
        enforce(name not in self.vars, "var %s already exists", name)
        v = Var(self, name, tuple(shape), dtype, is_param=True,
                trainable=trainable)
        self.vars[name] = v
        self.param_inits[name] = (initializer or XavierUniform(),
                                  tuple(shape), dtype)
        self.version += 1
        return v

    def apply(self, fn: Callable, inputs: Sequence[Any], *,
              name: str = "op", attrs: Optional[dict] = None,
              eval_fn: Optional[Callable] = None):
        """Record `fn(*inputs)` as an op node. Non-Var inputs are captured
        as constants (their values live in ``_const_values`` and are fed to
        the executor env). Output arity/shapes/dtypes come from abstract
        eval of ``fn``. ``eval_fn``, if given, is the inference-mode variant
        (same signature and output arity) substituted by
        ``clone(for_test=True)`` — the reference's is_test attribute on ops
        like batch_norm/dropout (reference: framework.py clone semantics)."""
        if eval_fn is not None:
            attrs = dict(attrs or {}, eval_fn=eval_fn)
        in_names, consts = [], {}
        for x in inputs:
            if isinstance(x, Var):
                enforce(x.program is self,
                        "input %s belongs to another Program", x.name)
                in_names.append(x.name)
            else:
                cname = self.unique_name(f"const_{name}")
                consts[cname] = x
                in_names.append(cname)

        # abstract-eval output specs
        import jax

        in_specs = []
        for n in in_names:
            if n in consts:
                arr = jnp.asarray(consts[n])
                self.vars[n] = Var(self, n, arr.shape, arr.dtype)
                self._const_values = getattr(self, "_const_values", {})
                self._const_values[n] = arr
                in_specs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
            else:
                v = self.vars[n]
                shape = tuple(TRACE_BATCH if d == -1 else d
                              for d in v.shape)
                in_specs.append(jax.ShapeDtypeStruct(shape, v.dtype))
        try:
            out_specs = jax.eval_shape(fn, *in_specs)
        except Exception as e:  # pragma: no cover - surfacing build errors
            raise type(e)(f"while recording op {name!r}: {e}") from e
        flat = out_specs if isinstance(out_specs, tuple) else (out_specs,)

        # sequence metadata rides along: outputs inherit the first
        # lod-carrying input's lengths companion (row-preserving ops keep
        # ragged structure; consumers that reduce it clear lod_src)
        lod_carrier = next((self.vars[n] for n in in_names
                            if n in self.vars and
                            getattr(self.vars[n], "lod_src", None)), None)
        lod_src = lod_carrier.lod_src if lod_carrier is not None else None
        lod_src2 = (getattr(lod_carrier, "lod_src2", None)
                    if lod_carrier is not None else None)
        out_vars = []
        for spec in flat:
            oname = self.unique_name(name)
            shape = tuple(spec.shape)
            # keep batch polymorphism: if any feed had -1 leading, outputs
            # keep their traced shape (informational only)
            ov = Var(self, oname, shape, spec.dtype)
            ov.lod_src = lod_src
            ov.lod_src2 = lod_src2
            self.vars[oname] = ov
            out_vars.append(ov)
        self.nodes.append(_OpNode(fn, in_names, [v.name for v in out_vars],
                                  name, attrs))
        self.version += 1
        return out_vars[0] if len(out_vars) == 1 else tuple(out_vars)

    def assign(self, target: Var, value: Var) -> None:
        """Record an in-place update of `target` (optimizer writes —
        reference: optimizer ops mutating their Param input). The executor
        threads the new value to subsequent reads and back to the scope."""
        self.nodes.append(_OpNode(lambda v: v, [value.name], [target.name],
                                  "assign"))
        self.version += 1

    def param_names(self) -> List[str]:
        """Trainable params only (grad targets)."""
        return [n for n, v in self.vars.items()
                if v.is_param and v.trainable]

    def persistable_names(self) -> List[str]:
        """Everything scope-backed: params + optimizer state (reference:
        io.py save_persistables semantics)."""
        return [n for n, v in self.vars.items() if v.is_param]

    def clone(self, for_test: bool = False) -> "Program":
        """Snapshot (reference: Program.clone framework.py) — shares no
        mutable state with the original. ``for_test=True`` drops the
        backward marker and everything after it (grad + optimizer ops),
        the reference's inference-clone semantics."""
        p = Program()
        nodes = self.nodes
        if for_test:
            cut = next((i for i, n in enumerate(nodes)
                        if isinstance(n, _GradNode)), len(nodes))
            # swap train-mode ops for their inference variants (batch_norm
            # uses running stats, dropout becomes identity)
            nodes = [
                _OpNode(n.attrs["eval_fn"], n.inputs, n.outputs, n.name,
                        n.attrs)
                if isinstance(n, _OpNode) and "eval_fn" in n.attrs else n
                for n in nodes[:cut]
            ]
        p.nodes = list(nodes)
        p.vars = {}
        for k, v in self.vars.items():
            nv = Var(p, v.name, v.shape, v.dtype, is_param=v.is_param,
                     is_feed=v.is_feed, trainable=v.trainable)
            nv.lod_src = v.lod_src
            nv.lod_src2 = v.lod_src2
            p.vars[k] = nv
        p.param_inits = dict(self.param_inits)
        p._const_values = dict(getattr(self, "_const_values", {}))
        p.version = self.version
        p._name_counter = self._name_counter
        return p

    def __repr__(self):
        ops = ", ".join(n.name for n in self.nodes[:8])
        return (f"Program({len(self.nodes)} ops [{ops}...], "
                f"{len(self.param_inits)} params)")


# ---------------------------------------------------------------------------
# default program + guard (reference: framework.py:3681 program_guard)
# ---------------------------------------------------------------------------

_tls = threading.local()


def default_main_program() -> Program:
    if not hasattr(_tls, "main"):
        _tls.main = Program()
    return _tls.main


def is_building() -> bool:
    """True inside ``program_guard`` — layers with no Var inputs (e.g.
    fill_constant) use this to record onto the Program instead of
    returning an eager array."""
    return getattr(_tls, "building", 0) > 0


@contextlib.contextmanager
def program_guard(main: Program):
    prev = getattr(_tls, "main", None)
    _tls.main = main
    _tls.building = getattr(_tls, "building", 0) + 1
    try:
        yield main
    finally:
        _tls.building -= 1
        if prev is None:
            del _tls.main
        else:
            _tls.main = prev


def append_backward(loss: Var, parameter_list: Optional[Sequence[str]] = None
                    ) -> List[Tuple[Var, Var]]:
    """reference: backward.py:394 — record grad vars for every trainable
    param reachable in the prefix; returns [(param, grad)] pairs."""
    prog = loss.program
    params = list(parameter_list or prog.param_names())
    enforce(params, "append_backward: program has no parameters")
    node = _GradNode(len(prog.nodes), loss.name, params)
    prog.nodes.append(node)
    pairs = []
    for p in params:
        gv = Var(prog, p + GRAD_SUFFIX, prog.vars[p].shape,
                 prog.vars[p].dtype)
        prog.vars[gv.name] = gv
        pairs.append((prog.vars[p], gv))
    prog.version += 1
    return pairs
