"""DLPack interop (reference: paddle/fluid/framework/dlpack_tensor.cc —
zero-copy tensor exchange with other frameworks).

JAX speaks DLPack natively; these helpers mirror the reference's surface
and cover the torch round-trip used by data pipelines."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.enforce import enforce


def to_dlpack(x):
    """Array → DLPack capsule (reference: DLPackTensor ctor)."""
    return jax.dlpack.to_dlpack(jnp.asarray(x))


def from_dlpack(capsule_or_tensor):
    """DLPack capsule or any __dlpack__ object (torch tensor, numpy array,
    cupy...) → jax Array (reference: framework dlpack→Tensor path)."""
    return jax.dlpack.from_dlpack(capsule_or_tensor)


def from_torch(t):
    """torch.Tensor → jax Array without a host copy when devices allow."""
    enforce(hasattr(t, "__dlpack__"), "expected a torch tensor, got %s",
            type(t).__name__)
    return jax.dlpack.from_dlpack(t)


def to_torch(x):
    """jax Array → torch.Tensor via DLPack."""
    import torch

    return torch.from_dlpack(jnp.asarray(x))
