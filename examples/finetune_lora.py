"""LoRA fine-tuning: freeze a pretrained GPT, train only low-rank
adapters on the attention projections, then merge them back into plain
weights for serving (byte-identical forward, adapters gone).

  JAX_PLATFORMS=cpu python examples/finetune_lora.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (or: pip install -e .)

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.models import gpt as G
from paddle_tpu.utils.flops import enable_compile_cache

enable_compile_cache()


def main():
    pt.seed(0)
    model = G.GPTForCausalLM(G.GPTConfig.tiny()).eval()

    # adapt only q/v projections (the classic recipe); base weights
    # move to buffers — OUT of the trainable dict
    paths = nn.apply_lora(model, r=8, alpha=16,
                          targets=("q_proj", "v_proj"))
    lora = nn.lora_parameters(model)
    n_total = sum(np.size(v) for v in model.named_buffers().values())
    n_lora = sum(np.size(v) for v in lora.values())
    print(f"adapting {len(paths)} projections: {n_lora} trainable "
          f"adapter values vs {n_total} frozen")

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, (8, 32)).astype(np.int32)
    opt = optimizer.Adam(5e-3)
    state = opt.init(lora)

    @jax.jit
    def step(lora, state):
        def loss(p):
            out, _ = model.functional_call(p, ids, training=True,
                                           method="forward_loss")
            return out

        l, g = jax.value_and_grad(loss)(lora)
        lora, state = opt.apply(lora, g, state)
        return l, lora, state

    for i in range(10):
        l, lora, state = step(lora, state)
        if i % 3 == 0:
            print(f"step {i}: loss {float(l):.4f}")

    # fold the adapters into the weights for serving
    model.set_parameters(lora)
    merged = nn.merge_lora(model)
    print(f"merged {len(merged)} adapters; generating:")
    out = model.generate(ids[:1, :4], 16, temperature=0.0)
    print("  ", np.asarray(out)[0].tolist())


if __name__ == "__main__":
    main()
