"""The LM serving stack end-to-end: a GPT quantized to weight-only
int8 (W8A16 — half the weight HBM stream of the bandwidth-bound decode
loop), served with continuous batching (slot arena, per-request
sampling/eos), plus a speculative-decoding pass that provably preserves
the target model's distribution.

  python examples/serve_gpt.py            # real chip
  JAX_PLATFORMS=cpu python examples/serve_gpt.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (or: pip install -e .)

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
from paddle_tpu import quant
from paddle_tpu.models import gpt as G
from paddle_tpu.models.speculative import speculative_generate
from paddle_tpu.serving import BatchedDecoder
from paddle_tpu.utils.flops import enable_compile_cache

enable_compile_cache()


def main():
    pt.seed(0)
    # tiny config so the example runs anywhere; swap for
    # GPTConfig.small() + real weights in production
    target = G.GPTForCausalLM(G.GPTConfig.tiny()).eval()
    pt.seed(1)
    draft = G.GPTForCausalLM(G.GPTConfig(
        vocab_size=512, hidden_size=64, num_layers=1, num_heads=2,
        num_kv_heads=2, intermediate_size=128, max_position=128)).eval()

    # --- weight-only int8: a pure post-training transform ------------
    wrapped = quant.apply_weight_only_int8(target)
    print(f"W8A16: {len(wrapped)} projections quantized")

    # --- continuous batching over a PAGED KV cache: 6 requests over 3
    # slots sharing a page pool (memory scales with live tokens) ------
    dec = BatchedDecoder(target, slots=3, capacity=128, pages=8,
                         page_size=64, prefix_cache=True,
                         key=jax.random.key(0), temperature=0.8,
                         top_p=0.9, eos_id=7)
    rng = np.random.default_rng(0)
    rids = [dec.submit(rng.integers(1, 512, (n,)), max_new=16)
            for n in (4, 9, 5, 7, 3, 6)]
    outs = dec.run()
    for rid in rids:
        print(f"request {rid}: {len(outs[rid])} tokens ->",
              outs[rid][:8].tolist(), "...")

    # --- speculative decoding: same distribution, fewer target passes -
    prompt = rng.integers(1, 512, (2, 6)).astype(np.int32)
    out, stats = speculative_generate(
        target, draft, prompt, 30, gamma=3,
        key=jax.random.key(2), temperature=0.8, return_stats=True)
    acc = np.asarray(stats["accepted_drafts"], np.float64)
    rounds = np.asarray(stats["rounds"], np.float64)
    print("speculative: tokens/target-pass =",
          np.round(1 + acc / np.maximum(rounds, 1), 2).tolist())

    # --- the serving-arena composition: SPECULATIVE rounds over the
    # paged pool + CHUNKED PREFILL (long prompts prefill 64 tokens per
    # tick so live slots keep their decode cadence) ------------------
    # sampled mode: rejection-sampling acceptance (u*q < p) is
    # meaningful even for this untrained pair — greedy acceptance
    # would be argmax agreement, ~0 across two random models.
    # (For a dispatch-bound link WITHOUT a draft model, the sibling
    # lever is BatchedDecoder(decode_steps=k): k tokens per dispatch,
    # token-identical to k=1.)
    sdec = BatchedDecoder(target, slots=2, capacity=128, pages=8,
                          page_size=64, draft=draft, gamma=3,
                          prefill_chunk=64, temperature=0.8,
                          key=jax.random.key(3))
    for n in (40, 5, 9):
        sdec.submit(rng.integers(1, 512, (n,)), max_new=12)
    souts = sdec.run()
    rate = sdec.spec_accepted / max(1, sdec.spec_row_rounds)
    print(f"arena speculative: {len(souts)} requests done, "
          f"accept/round = {rate:.2f}")


if __name__ == "__main__":
    main()
