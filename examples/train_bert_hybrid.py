"""Hybrid-parallel BERT pretraining: ONE jitted step composes dp x tp x
pp over a device mesh — XLA inserts the gradient all-reduce (dp),
activation all-reduces (tp), and neighbour collective-permutes (pp);
the attention rides the Pallas flash kernel on TPU and the MLM head is
the fused chunked linear-CE. Run on the 8-device CPU simulation or any
real slice:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    python examples/train_bert_hybrid.py
"""

import jax

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (or: pip install -e .)

# this environment's sitecustomize may pre-register a remote TPU
# backend; examples honor JAX_PLATFORMS=cpu even then
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    n = os.environ.get("XLA_FLAGS", "")
    if "device_count=8" in n:
        jax.config.update("jax_num_cpu_devices", 8)

import paddle_tpu as pt
from paddle_tpu import checkpoint
from paddle_tpu.parallel.hybrid import build_bert_hybrid_step
from paddle_tpu.utils.flops import enable_compile_cache

enable_compile_cache()


def main():
    devs = jax.devices()
    if len(devs) >= 8:
        mesh = pt.build_mesh(dp=2, tp=2, pp=2, devices=devs[:8])
    else:
        mesh = pt.build_mesh(dp=1, tp=1, pp=1, devices=devs[:1])
    pt.set_mesh(mesh)

    # the flagship composed step over the REAL BertForPretraining stack;
    # returns the pipelined step, its numerically-identical sequential
    # reference, initialized (sharded) params, and a matching feed
    step, _ref, params, feed = build_bert_hybrid_step(
        mesh, num_microbatches=2)
    jstep = jax.jit(step, donate_argnums=(0,))
    for i in range(4):
        loss, params = jstep(params, *feed)
        print(f"step {i}: loss {float(loss):.4f}")

    checkpoint.save(params, "/tmp/bert_hybrid_ckpt")
    print("sharded checkpoint saved to /tmp/bert_hybrid_ckpt")


if __name__ == "__main__":
    main()
