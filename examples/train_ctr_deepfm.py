"""Sparse CTR training (DeepFM): expert-parallel sharded embedding
tables over the 'ep' mesh axis plus ROW-SPARSE optimizer updates — each
step touches O(batch x fields) table rows instead of O(vocab) (the
SelectedRows capability, redesigned).

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    python examples/train_ctr_deepfm.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (or: pip install -e .)

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    if "device_count=8" in os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_num_cpu_devices", 8)

import numpy as np

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.models import deepfm as DF
from paddle_tpu.optimizer.sparse import sparse_minimize_fn
from paddle_tpu.utils.flops import enable_compile_cache

enable_compile_cache()


def main():
    devs = jax.devices()
    ep = min(4, len(devs))
    mesh = pt.build_mesh(ep=ep, devices=devs[:ep])
    pt.set_mesh(mesh)
    pt.seed(0)

    cfg = DF.DeepFMConfig(total_vocab=100_000, num_fields=26,
                          dense_dim=13, embed_dim=16,
                          embedding_axis="ep" if ep > 1 else None,
                          sparse_grads=True)
    model = DF.DeepFM(cfg)

    def forward_loss(params, ids, dense, labels):
        logits, _ = model.functional_call(params, ids, dense)
        return DF.loss_fn(logits, labels)

    init_fn, step_fn = sparse_minimize_fn(model, forward_loss,
                                          optimizer.Adam(1e-2))
    params = model.named_parameters()
    state = init_fn(params)
    step_fn = jax.jit(step_fn)

    rng = np.random.default_rng(0)
    B = 1024
    for i in range(8):
        ids = rng.integers(0, cfg.total_vocab, (B, cfg.num_fields))
        dense = rng.normal(size=(B, cfg.dense_dim)).astype(np.float32)
        labels = (ids[:, 0] % 2 == 0).astype(np.float32)
        loss, params, state = step_fn(params, state, ids, dense, labels)
        print(f"step {i}: loss {float(loss):.4f}")
    print(f"tables sharded over ep={ep}; per-step row updates: "
          f"{B * cfg.num_fields} of {cfg.total_vocab}")


if __name__ == "__main__":
    main()
