"""paddle_tpu — a TPU-native deep-learning framework.

Capability-equivalent rebuild of PaddlePaddle Fluid (~1.4) designed TPU-first:
JAX/XLA for the compute path (traced, compiled, SPMD over device meshes),
Pallas for custom kernels, and native host-side components for the runtime.
See SURVEY.md at the repo root for the reference blueprint.
"""

__version__ = "0.1.0"

from . import core, ops
from .core import (CPUPlace, FLAGS, Place, TPUPlace, build_mesh, default_place,
                   device_count, get_mesh, is_compiled_with_tpu, seed,
                   set_device, set_mesh)

# Subpackages imported lazily to keep `import paddle_tpu` fast.
# name -> module path relative to this package.
_LAZY = {
    "nn": ".nn",
    "optimizer": ".optimizer",
    "parallel": ".parallel",
    "static": ".static",
    "data": ".data",
    "models": ".models",
    "metrics": ".metrics",
    "profiler": ".core.profiler",
    "telemetry": ".telemetry",
    "analysis": ".analysis",
    "initializer": ".initializer",
    "regularizer": ".regularizer",
    "clip": ".clip",
    "native": ".native",
    "checkpoint": ".checkpoint",
    "quant": ".quant",
    "amp": ".amp",
    "fleet": ".fleet",
    "debug": ".debug",
    "install_check": ".install_check",
    "resilience": ".resilience",
    "train_loop": ".train_loop",
    "slim": ".slim",
    "utils": ".utils",
    "jit": ".jit",
    "nets": ".nets",
    "layers": ".layers",
    "fluid": ".fluid",
    "dataset": ".dataset",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        try:
            mod = importlib.import_module(_LAZY[name], __name__)
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"paddle_tpu.{name} is not available: {e}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
