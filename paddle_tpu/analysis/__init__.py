"""Static verification plane — ahead-of-execution analyzers.

The reference Fluid verified nothing before the op loop ran (a
malformed ProgramDesc died mid-run, reference: framework/executor.cc);
this package is the opposite posture: pure static passes over the
program IR, buffer provenance, sharding plans, and the repo's own
source, each returning typed :class:`Diagnostic` records *before*
anything executes.

- :mod:`.verify` — Program IR verifier (use-before-write, conflicting
  writes, dead ops, unreachable fetches, shape/dtype drift, param
  mutation). Wired into ``Executor.run`` as verify-on-first-compile.
- :mod:`.donation` — donation-safety analyzer (host-owned / view /
  zero-copy-host-backed buffers donated; unused donations; alias
  escapes — the PR 6 SIGSEGV taxonomy). Wired into ``Trainer`` at
  compile time.
- :mod:`.shardcheck` — static Plan audit (would-reshard, dropped
  specs, big-leaf-replicated). Rendered by ``Plan.describe`` and
  /statusz.
- :mod:`.lint` — AST linter for repo invariants (atomic state writes,
  span clocks, thread names, device_get-into-donation, debug
  leftovers). ``tools/lint.py`` CLI + the ci.sh ``lint`` stage.
- :mod:`.concurrency` — whole-repo concurrency verifier (the
  ``PT-RACE-4xx`` family: unsynchronized shared writes from thread
  entries, lock-order inversions with witness paths, blocking calls
  under locks, non-looped condition waits, unjoined non-daemon
  threads). ``tools/lint.py --select PT-RACE`` + the ci.sh ``race
  smoke`` stage; :func:`~.concurrency.lock_order_graph` feeds the
  runtime lock-order watchdog (``telemetry/lockwatch.py``).

Opt out of the wired-in passes with ``FLAGS_static_verify=0`` (env or
``core.config.FLAGS``); the analyzers stay importable/callable either
way.
"""

from .concurrency import (RACE_CODES, analyze_file, analyze_paths,
                          analyze_source, lock_order_graph)
from .diagnostics import (Diagnostic, errors, format_diagnostics,
                          has_errors)
from .donation import (check_donation, classify_provenance,
                       note_host_backed, note_owned, note_transfer,
                       track_host_transfers)
from .lint import LINT_CODES, lint_file, lint_paths, lint_source
from .shardcheck import audit_plan, audit_summary
from .verify import fetch_diagnostic, verify_program

__all__ = [
    "Diagnostic", "errors", "format_diagnostics", "has_errors",
    "verify_program", "fetch_diagnostic",
    "check_donation", "classify_provenance", "note_owned",
    "note_host_backed", "note_transfer", "track_host_transfers",
    "audit_plan", "audit_summary",
    "lint_source", "lint_file", "lint_paths", "LINT_CODES",
    "analyze_source", "analyze_file", "analyze_paths", "RACE_CODES",
    "lock_order_graph",
]
