"""Concurrency verifier — a whole-repo AST pass over the threaded half
of the framework.

PRs 9-13 made this a genuinely concurrent system (fleet watcher
threads, async checkpoint writers, router claim lanes, per-stream SSE
pumps, prefetcher queues) and its worst historical bugs are exactly
this class: the PR 1 writer-thread use-after-free, the PR 9
survivor-wedged-in-a-dead-rank's-barrier hang, the PR 11
serial-fan-in-on-a-wedged-peer stall. This pass builds a per-module
*concurrency model* — thread entry points (``threading.Thread(target=
...)``, ``ThreadPoolExecutor.submit``), lock objects and the functions
that acquire them, attributes written from thread bodies — and emits
the ``PT-RACE-4xx`` family through the shared :class:`Diagnostic`
currency (codes in ``diagnostics.py``):

- **PT-RACE-401** — a shared attribute written from a thread entry and
  written elsewhere with no common lock (write/write race), or written
  from a thread entry under NO lock at all while read/written elsewhere
  (unsynchronized shared mutation). A thread-side write that holds a
  lock and is merely *read* lock-free elsewhere is NOT flagged — that
  is the sanctioned publication-read pattern this codebase uses for
  stats snapshots (CPython reference stores are atomic; the lock
  serializes the writers).
- **PT-RACE-402** — lock-order inversion: the per-module
  lock-acquisition graph (edge A→B = B acquired while A held, lexically
  or through a one-module call chain) has a cycle. Both witness paths
  are named — the pair of functions that acquire the same locks in
  opposite orders is tomorrow's deadlock.
- **PT-RACE-403** — a blocking call (``join()`` / ``queue.get()`` /
  ``queue.put()`` on a bounded queue / ``Event.wait()`` /
  ``Condition.wait()`` on a *different* condition) without a timeout
  while a lock is held: one wedged peer turns a lock into a system-wide
  stall (the PR 11 fan-in class). ``Condition.wait`` on the condition
  itself is the sanctioned pattern and exempt (wait releases it).
- **PT-RACE-404** — ``Condition.wait`` outside a predicate loop
  (``while``): condition waits are spec'd to wake spuriously and after
  stolen wakeups; an ``if``-guarded wait acts on stale state.
  ``wait_for`` carries its own loop and is exempt.
- **PT-RACE-405** — a non-daemon thread that is never ``join``-ed
  anywhere in its module: on interpreter shutdown it blocks process
  exit forever (or leaks, under daemonized parents).

Scope and honesty: the model is per-module and intentionally
flow-insensitive — it names every *structurally possible* hazard, not
every dynamically reachable one. False positives are suppressed like
every other analysis code: ``# pt-lint: disable=PT-RACE-401 <reason>``
on (or above) the flagged line, reason REQUIRED.

The runtime companion (``telemetry/lockwatch.py``) instruments real
lock acquisitions at test time and validates this pass's lock graph
against observed orderings — :func:`lock_order_graph` is the interface
between the two.

``tools/lint.py --select PT-RACE`` runs just this family; the ci.sh
``race smoke`` stage gates it repo-wide.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic
from .lint import _dotted, _suppressions, _terminal

RACE_CODES = {
    "PT-RACE-401": "shared attribute written in a thread entry without "
                   "a common lock",
    "PT-RACE-402": "lock-order inversion (cyclic lock-acquisition "
                   "graph)",
    "PT-RACE-403": "timeout-less blocking call while holding a lock",
    "PT-RACE-404": "Condition.wait outside a predicate loop",
    "PT-RACE-405": "non-daemon thread never joined",
}

# constructors that make a lock-like object (anything you can hold
# while blocking someone else). Condition doubles as a lock (``with
# cond:`` acquires its inner lock).
_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
_EVENT_CTORS = {"Event"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_THREAD_CTORS = {"Thread"}

# attribute kinds the model tracks (values of _Symbols maps)
_KIND_LOCK = "lock"
_KIND_COND = "condition"
_KIND_EVENT = "event"
_KIND_QUEUE = "queue"
_KIND_THREAD = "thread"

# blocking receiver kinds for PT-RACE-403, by method name
_BLOCKING_METHODS = {
    "join": (_KIND_THREAD,),
    "get": (_KIND_QUEUE,),
    "put": (_KIND_QUEUE,),
    "wait": (_KIND_EVENT, _KIND_COND),
}

# sync-primitive kinds: attributes holding these are themselves
# thread-safe (or lifecycle-managed) — rebinding one is initialization,
# not shared-state mutation, so PT-RACE-401 skips them
_SYNC_KINDS = {_KIND_LOCK, _KIND_COND, _KIND_EVENT, _KIND_QUEUE,
               _KIND_THREAD}


def _ctor_kind(call: ast.Call) -> Optional[str]:
    """The sync-primitive kind a constructor call produces, if any.
    Matched by terminal name (``threading.Lock`` / bare ``Lock`` /
    ``queue.Queue``), the same posture as the rest of the linter —
    false negatives from exotic aliasing beat false positives from
    guessing."""
    name = _terminal(call.func)
    if name in _LOCK_CTORS:
        return _KIND_LOCK
    if name in _COND_CTORS:
        return _KIND_COND
    if name in _EVENT_CTORS:
        return _KIND_EVENT
    if name in _QUEUE_CTORS:
        return _KIND_QUEUE
    if name in _THREAD_CTORS:
        return _KIND_THREAD
    if name == "WatchedLock":  # the runtime watchdog's wrapper IS a lock
        return _KIND_LOCK
    return None


def _has_timeout(call: ast.Call, method: str) -> bool:
    """True when the blocking call is bounded — positional timeout
    slots differ per primitive, so the method name matters:
    ``join``/``wait`` take timeout FIRST, ``queue.get(block,
    timeout)`` takes ``block`` first (so ``get(True)`` is still
    unbounded but ``get(False)`` never blocks), and ``queue.put(item,
    block, timeout)``'s first positional is the ITEM (a bare
    ``put(x)`` is unbounded). An explicit ``None`` timeout — keyword
    or positional — is the unbounded spelling, not a bound."""

    def bounds(node: ast.AST) -> bool:
        # a literal None is unbounded; any other expression is taken
        # as a real bound (a variable timeout can't be judged here)
        return not (isinstance(node, ast.Constant)
                    and node.value is None)

    for kw in call.keywords:
        if kw.arg == "timeout" and bounds(kw.value):
            return True
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    args = call.args
    if method in ("join", "wait"):
        return bool(args) and bounds(args[0])
    if method == "get":
        if len(args) >= 2:
            return bounds(args[1])  # get(block, timeout)
        return bool(args) and isinstance(args[0], ast.Constant) \
            and args[0].value is False  # get(False) never blocks
    if method == "put":
        if len(args) >= 3:
            return bounds(args[2])  # put(item, block, timeout)
        return len(args) == 2 and isinstance(args[1], ast.Constant) \
            and args[1].value is False  # put(item, False)
    return bool(args)


class _FnInfo:
    """Everything the checkers need to know about one function body."""

    def __init__(self, qual: str, node: ast.AST, cls: Optional[str]):
        self.qual = qual            # "Class.method" or "function"
        self.node = node
        self.cls = cls
        self.line = node.lineno
        # [(attr, line, locks_held, is_write, is_read)]
        self.attr_accesses: List[Tuple[str, int, frozenset, bool, bool]] = []
        # [(lock_id, line)] every acquisition site (with / .acquire())
        self.acquires: List[Tuple[str, int]] = []
        # [(held_lock, acquired_lock, line)] lexical nesting edges
        self.nested: List[Tuple[str, str, int]] = []
        # [(callee_qual, line, locks_held)]
        self.calls: List[Tuple[str, int, frozenset]] = []
        # [(desc, line, locks_held, receiver_kind)]
        self.blocking: List[Tuple[str, int, frozenset, str]] = []
        # [(cond_id, line, in_while)]
        self.cond_waits: List[Tuple[str, int, bool]] = []
        # [(line, daemon, binding, target_qual)] threads created here
        self.threads: List[Tuple[int, bool, Optional[str],
                                 Optional[str]]] = []
        # names of local functions defined in this body (closures)
        self.local_fns: Dict[str, ast.AST] = {}


def _queue_put_blocks(ctor: ast.Call) -> bool:
    """Can ``put()`` on a queue built by this constructor block? Only
    a BOUNDED queue's put blocks: ``Queue()`` / ``Queue(0)`` /
    ``SimpleQueue()`` never do. A non-literal maxsize is taken as
    bounded (the common reason to pass one)."""
    if _terminal(ctor.func) == "SimpleQueue":
        return False
    size = None
    if ctor.args:
        size = ctor.args[0]
    for kw in ctor.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    if size is None:
        return False  # default maxsize=0: unbounded
    if isinstance(size, ast.Constant):
        return bool(size.value)  # 0/None stay unbounded
    return True


class _ModuleModel:
    """The per-module concurrency model the checkers consume."""

    def __init__(self, modname: str, path: str):
        self.modname = modname
        self.path = path
        # symbol tables: "Class.attr" / "mod.name" -> kind
        self.symbols: Dict[str, str] = {}
        # queue symbols whose put() can actually block (maxsize > 0)
        self.bounded_queues: Set[str] = set()
        self.functions: Dict[str, _FnInfo] = {}
        # thread entry qualnames (targets of Thread()/submit())
        self.thread_entries: Set[str] = set()
        # qualnames with .join() called on their thread binding
        self.joined_bindings: Set[str] = set()


# ---------------------------------------------------------------------------
# pass 1: symbol collection (locks / conditions / events / queues /
# threads, keyed by class attribute or module-level name)
# ---------------------------------------------------------------------------


class _SymbolCollector(ast.NodeVisitor):
    def __init__(self, model: _ModuleModel):
        self.model = model
        self._cls: Optional[str] = None

    def visit_ClassDef(self, node):
        prev, self._cls = self._cls, node.name
        self.generic_visit(node)
        self._cls = prev

    def _record(self, target: ast.AST, value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        kind = _ctor_kind(value)
        if kind is None:
            return
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and self._cls):
            sym = f"{self._cls}.{target.attr}"
        elif isinstance(target, ast.Name):
            # module-level or function-local: both get recorded; the
            # analyzer resolves locals first by lexical preference
            sym = f"{self.model.modname}.{target.id}"
        else:
            return
        self.model.symbols[sym] = kind
        if kind == _KIND_QUEUE and _queue_put_blocks(value):
            self.model.bounded_queues.add(sym)

    def visit_Assign(self, node):
        for t in node.targets:
            self._record(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record(node.target, node.value)
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# pass 2: per-function analysis with lexical lock-hold tracking
# ---------------------------------------------------------------------------


class _FnAnalyzer:
    """Walk one function body tracking the lexically-held lock set."""

    def __init__(self, model: _ModuleModel, info: _FnInfo):
        self.model = model
        self.info = info

    # -- id resolution -------------------------------------------------------

    def _sym_id(self, node: ast.AST) -> Optional[str]:
        """Resolve an expression to a tracked symbol id, or None."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and self.info.cls):
            key = f"{self.info.cls}.{node.attr}"
            return key if key in self.model.symbols else None
        if isinstance(node, ast.Name):
            key = f"{self.model.modname}.{node.id}"
            return key if key in self.model.symbols else None
        return None

    def _kind_of(self, sym: Optional[str]) -> Optional[str]:
        return self.model.symbols.get(sym) if sym else None

    # -- the walk ------------------------------------------------------------

    def run(self) -> None:
        body = getattr(self.info.node, "body", [])
        # pre-scan this scope's nested defs so a Thread(target=worker)
        # lexically BEFORE `def worker` still resolves scope-qualified
        self._scan_local_defs(body)
        for stmt in body:
            self._walk(stmt, held=(), loops=0)

    def _scan_local_defs(self, body) -> None:
        work = list(body)
        while work:
            node = work.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.info.local_fns[node.name] = node
                continue  # deeper defs belong to THAT scope
            work.extend(ast.iter_child_nodes(node))

    def _walk(self, node: ast.AST, held: Tuple[str, ...],
              loops: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body runs later (possibly on a thread):
            # it gets its own _FnInfo via the module visitor; here we
            # only note its existence
            self.info.local_fns[node.name] = node
            return
        if isinstance(node, ast.With):
            acquired: List[str] = []
            for item in node.items:
                sym = self._sym_id(item.context_expr)
                kind = self._kind_of(sym)
                if kind in (_KIND_LOCK, _KIND_COND):
                    self.info.acquires.append((sym, node.lineno))
                    for h in held + tuple(acquired):
                        if h != sym:
                            self.info.nested.append((h, sym, node.lineno))
                    acquired.append(sym)
            inner = held + tuple(a for a in acquired if a not in held)
            for stmt in node.body:
                self._walk(stmt, inner, loops)
            return
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            for child in ast.iter_child_nodes(node):
                self._walk(child, held, loops + 1)
            return
        if isinstance(node, ast.Call):
            self._call(node, held, loops)
            for child in ast.iter_child_nodes(node):
                self._walk(child, held, loops)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(node, held, loops)
            return
        if isinstance(node, ast.Attribute):
            self._attr_read(node, held)
            for child in ast.iter_child_nodes(node):
                self._walk(child, held, loops)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, loops)

    # -- attribute accesses (PT-RACE-401 raw material) -----------------------

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and self.info.cls):
            return node.attr
        return None

    def _attr_read(self, node: ast.Attribute, held) -> None:
        attr = self._self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self.info.attr_accesses.append(
                (attr, node.lineno, frozenset(held), False, True))

    def _assign(self, node, held, loops) -> None:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            for sub in ast.walk(t):
                attr = self._self_attr(sub)
                if attr is not None:
                    is_aug = isinstance(node, ast.AugAssign)
                    self.info.attr_accesses.append(
                        (attr, node.lineno, frozenset(held), True,
                         is_aug))
        if getattr(node, "value", None) is not None:
            self._walk(node.value, held, loops)

    # -- calls: acquisitions, blocking calls, thread spawns, call graph ------

    def _call(self, node: ast.Call, held, loops) -> None:
        func = node.func
        term = _terminal(func)
        dotted = _dotted(func)

        # explicit .acquire() — treated as an acquisition site for the
        # order graph (hold extent approximated as the whole function;
        # this repo overwhelmingly uses `with`)
        if term == "acquire" and isinstance(func, ast.Attribute):
            sym = self._sym_id(func.value)
            if self._kind_of(sym) in (_KIND_LOCK, _KIND_COND):
                self.info.acquires.append((sym, node.lineno))
                for h in held:
                    if h != sym:
                        self.info.nested.append((h, sym, node.lineno))

        # thread creation
        if term in _THREAD_CTORS and dotted in ("threading.Thread",
                                                "Thread"):
            self._thread_ctor(node)

        # executor.submit(fn, ...) — the pool's workers are thread
        # entries too
        if term == "submit" and node.args:
            tq = self._target_qual(node.args[0])
            if tq is not None:
                self.model.thread_entries.add(tq)

        # .join() on a tracked thread binding: feeds PT-RACE-405 and,
        # timeout-less under a lock, PT-RACE-403. Blocking sites are
        # recorded with the LEXICAL held set even when it is empty —
        # the checker widens it with the caller-held entry context
        # (a private helper only ever called under a lock blocks
        # under that lock just the same).
        if term == "join" and isinstance(func, ast.Attribute):
            sym = self._sym_id(func.value)
            if self._kind_of(sym) == _KIND_THREAD:
                self.model.joined_bindings.add(sym)
                if not _has_timeout(node, "join"):
                    self.info.blocking.append(
                        (f"{sym}.join()", node.lineno, frozenset(held),
                         _KIND_THREAD))

        # blocking queue ops / event waits / condition waits. put()
        # blocks only on a BOUNDED queue (the default maxsize=0 and
        # SimpleQueue never do)
        if term in ("get", "put") and isinstance(func, ast.Attribute):
            sym = self._sym_id(func.value)
            if (self._kind_of(sym) == _KIND_QUEUE
                    and not _has_timeout(node, term)
                    and (term == "get"
                         or sym in self.model.bounded_queues)):
                self.info.blocking.append(
                    (f"{sym}.{term}()", node.lineno, frozenset(held),
                     _KIND_QUEUE))
        if term == "wait" and isinstance(func, ast.Attribute):
            sym = self._sym_id(func.value)
            kind = self._kind_of(sym)
            if kind == _KIND_COND:
                self.info.cond_waits.append((sym, node.lineno,
                                             loops > 0))
                if not _has_timeout(node, "wait"):
                    others = frozenset(h for h in held if h != sym)
                    self.info.blocking.append(
                        (f"{sym}.wait()", node.lineno, others,
                         _KIND_COND))
            elif kind == _KIND_EVENT and not _has_timeout(node,
                                                           "wait"):
                self.info.blocking.append(
                    (f"{sym}.wait()", node.lineno, frozenset(held),
                     _KIND_EVENT))

        # intra-module call graph (for 401 reachability + 402 edges
        # through one call level): self.method() and bare-name calls
        cq = self._callee_qual(func)
        if cq is not None:
            self.info.calls.append((cq, node.lineno, frozenset(held)))

    def _callee_qual(self, func: ast.AST) -> Optional[str]:
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self" and self.info.cls):
            return f"{self.info.cls}.{func.attr}"
        if isinstance(func, ast.Name):
            # a local closure shadows any module function of the same
            # name — and gets a scope-qualified name so two functions'
            # same-named `worker` closures never collide in the model
            if func.id in self.info.local_fns:
                return f"{self.info.qual}.<locals>.{func.id}"
            return func.id
        return None

    def _target_qual(self, target: ast.AST) -> Optional[str]:
        """Resolve a Thread(target=X) / submit(X) expression to a
        function qualname the model may know."""
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and self.info.cls):
            return f"{self.info.cls}.{target.attr}"
        if isinstance(target, ast.Name):
            if target.id in self.info.local_fns:
                return f"{self.info.qual}.<locals>.{target.id}"
            return target.id
        return None

    def _thread_ctor(self, node: ast.Call) -> None:
        daemon = False
        target_qual = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            if kw.arg == "target":
                target_qual = self._target_qual(kw.value)
        if target_qual is not None:
            self.model.thread_entries.add(target_qual)
        self.info.threads.append((node.lineno, daemon, None,
                                  target_qual))


# ---------------------------------------------------------------------------
# module driver
# ---------------------------------------------------------------------------


def _collect_functions(model: _ModuleModel, tree: ast.Module) -> None:
    """Register every function body: module functions by bare name,
    methods as Class.method, and nested defs (closures) by bare name
    scoped to their module — thread workers in this codebase are
    closures (`def worker(): ...; Thread(target=worker)`), and their
    self-attribute accesses belong to the enclosing class."""

    def add(node, qual: str, cls: Optional[str]):
        info = _FnInfo(qual, node, cls)
        model.functions[qual] = info
        _FnAnalyzer(model, info).run()
        # nested defs analyze with the ENCLOSING class context (a
        # closure inside a method mutates self through its cell) and a
        # scope-qualified name — two functions' same-named `worker`
        # closures must never overwrite each other in the model
        for name, sub in list(info.local_fns.items()):
            add(sub, f"{qual}.<locals>.{name}", cls)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add(node, node.name, None)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    add(sub, f"{node.name}.{sub.name}", node.name)


def _module_name(path: str) -> str:
    """Collision-safe module identity: ``<parent_dir>.<stem>`` when the
    path carries a parent (this tree has four same-named module pairs —
    static/io.py vs fluid/io.py, telemetry/metrics.py vs metrics.py,
    ... — which must not share a symbol namespace or lock_order_graph
    keys), bare stem otherwise."""
    norm = path.replace("\\", "/")
    stem = os.path.splitext(os.path.basename(norm))[0]
    parent = os.path.basename(os.path.dirname(norm))
    return f"{parent}.{stem}" if parent not in ("", ".") else stem


def _build_model(src: str, path: str) -> Optional[_ModuleModel]:
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None  # lint.py already reports unparseable files
    model = _ModuleModel(_module_name(path), path)
    _SymbolCollector(model).visit(tree)
    _collect_functions(model, tree)
    return model


def _thread_reachable(model: _ModuleModel) -> Set[str]:
    """Qualnames reachable from any thread entry through the
    intra-module call graph (cycle-safe BFS)."""
    seen: Set[str] = set()
    work = [q for q in model.thread_entries if q in model.functions]
    while work:
        q = work.pop()
        if q in seen:
            continue
        seen.add(q)
        info = model.functions.get(q)
        if info is None:
            continue
        for callee, _, _ in info.calls:
            if callee in model.functions and callee not in seen:
                work.append(callee)
    return seen


def _entry_contexts(model: _ModuleModel) -> Dict[str, frozenset]:
    """Caller-held lock context per function: the set of locks held at
    EVERY intra-module call site (a ``_tick_locked``-style private
    helper runs under its caller's lock even though it never acquires
    one itself). Applied only to private functions (one leading
    underscore): a public function is callable from other modules the
    model can't see, so it gets the empty context — assuming otherwise
    would hide real races. Thread entries always get the empty context
    (the runtime calls them with nothing held). Computed to fixpoint;
    monotone (contexts only grow), so it terminates."""
    sites: Dict[str, List[Tuple[str, frozenset]]] = {
        q: [] for q in model.functions}
    for caller, info in model.functions.items():
        for callee, _, held in info.calls:
            if callee in sites:
                sites[callee].append((caller, held))

    def is_seeded_empty(q: str) -> bool:
        name = q.rsplit(".", 1)[-1]
        return (q in model.thread_entries
                or name in model.thread_entries
                or not name.startswith("_")
                or name.startswith("__")
                or not sites[q])

    ctx: Dict[str, frozenset] = {q: frozenset()
                                 for q in model.functions}
    changed = True
    while changed:
        changed = False
        for q in model.functions:
            if is_seeded_empty(q):
                continue
            acc: Optional[frozenset] = None
            for caller, held in sites[q]:
                eff = held | ctx.get(caller, frozenset())
                acc = eff if acc is None else (acc & eff)
            new = acc or frozenset()
            if new != ctx[q]:
                ctx[q] = new
                changed = True
    return ctx


def _transitive_acquires(model: _ModuleModel
                         ) -> Dict[str, Set[Tuple[str, int]]]:
    """For each function: every lock it (or anything it calls, within
    the module) acquires — the call-chain half of the 402 edge set."""
    memo: Dict[str, Set[Tuple[str, int]]] = {}

    def visit(q: str, stack: Set[str]) -> Set[Tuple[str, int]]:
        if q in memo:
            return memo[q]
        if q in stack:
            return set()
        info = model.functions.get(q)
        if info is None:
            return set()
        stack.add(q)
        out: Set[Tuple[str, int]] = set(info.acquires)
        for callee, _, _ in info.calls:
            out |= visit(callee, stack)
        stack.discard(q)
        memo[q] = out
        return out

    for q in model.functions:
        visit(q, set())
    return memo


# ---------------------------------------------------------------------------
# checkers
# ---------------------------------------------------------------------------


def _check_401(model: _ModuleModel, reachable: Set[str],
               ctx: Dict[str, frozenset]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    # group accesses per (class, attr)
    per_attr: Dict[Tuple[str, str], Dict[str, list]] = {}
    for qual, info in model.functions.items():
        if info.cls is None:
            continue
        side = "thread" if qual in reachable else "main"
        # __init__ runs happens-before thread start: initialization
        # writes are invisible to the race model
        if qual.endswith(".__init__"):
            continue
        entry = ctx.get(qual, frozenset())
        for attr, line, lex_held, is_write, is_read in \
                info.attr_accesses:
            held = lex_held | entry
            key = (info.cls, attr)
            if model.symbols.get(f"{info.cls}.{attr}") in _SYNC_KINDS:
                continue
            if f"{info.cls}.{attr}" in model.functions:
                continue  # method/property access, not shared state
            rec = per_attr.setdefault(key, {"thread": [], "main": []})
            rec[side].append((qual, line, held, is_write, is_read))
    for (cls, attr), rec in sorted(per_attr.items()):
        t_writes = [r for r in rec["thread"] if r[3]]
        if not t_writes:
            continue
        m_writes = [r for r in rec["main"] if r[3]]
        m_reads = [r for r in rec["main"] if not r[3]]
        flagged = None
        # write/write race: no common lock between any write pair —
        # the peer write may live on the main side OR in a DIFFERENT
        # thread entry path (two worker loops racing each other is the
        # classic form; same-function pairs are skipped because a
        # single entry's multiplicity is invisible statically)
        for tq, tl, th, _, _ in t_writes:
            peers = m_writes + [r for r in t_writes if r[0] != tq]
            for mq, ml, mh, _, _ in peers:
                if not (th & mh):
                    flagged = (tq, tl, mq, ml, "written")
                    break
            if flagged:
                break
        if flagged is None:
            # unsynchronized thread-side write + ANY other access: a
            # locked thread write read lock-free elsewhere is the
            # sanctioned publication pattern and stays silent
            for tq, tl, th, _, _ in t_writes:
                if th:
                    continue
                others = m_writes + m_reads
                for mq, ml, mh, _, _ in others:
                    if not (th & mh):
                        flagged = (tq, tl, mq, ml, "accessed")
                        break
                if flagged:
                    break
        if flagged is None:
            continue
        tq, tl, mq, ml, verb = flagged
        out.append(Diagnostic(
            code="PT-RACE-401", severity="error", path=model.path,
            line=tl, var=f"{cls}.{attr}",
            message=(f"self.{attr} written from thread entry path "
                     f"{tq} (line {tl}) and {verb} in {mq} (line {ml}) "
                     f"with no common lock"),
            hint=("guard both sides with one lock, or make the "
                  "elsewhere side read-only under a locked writer "
                  "(the publication pattern); suppress with a reason "
                  "if the accesses are provably not concurrent")))
    return out


def _check_402(model: _ModuleModel,
               ctx: Dict[str, frozenset]) -> List[Diagnostic]:
    # edges: (A, B) -> witness description
    edges: Dict[Tuple[str, str], str] = {}
    trans = _transitive_acquires(model)
    for qual, info in model.functions.items():
        for a, b, line in info.nested:
            edges.setdefault((a, b), f"{qual} ({model.path}:{line}) "
                                     f"acquires {b} while holding {a}")
        # caller-held context: a private helper's acquisitions order
        # AFTER whatever its callers always hold
        for lock, line in info.acquires:
            for h in ctx.get(qual, frozenset()):
                if h != lock:
                    edges.setdefault(
                        (h, lock),
                        f"{qual} ({model.path}:{line}) acquires "
                        f"{lock} with {h} held by every caller")
        for callee, line, held in info.calls:
            if not held or callee not in model.functions:
                continue
            for lock, lline in trans.get(callee, ()):
                for h in held:
                    if h != lock:
                        edges.setdefault(
                            (h, lock),
                            f"{qual} ({model.path}:{line}) calls "
                            f"{callee} (which acquires {lock} at line "
                            f"{lline}) while holding {h}")
    # cycle detection over the small per-module graph; report each
    # 2-cycle (the overwhelmingly common inversion) once, canonically
    out: List[Diagnostic] = []
    seen: Set[frozenset] = set()
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reachable_from(start: str, goal: str) -> Optional[List[str]]:
        # BFS path start -> goal
        work = [(start, [start])]
        visited = {start}
        while work:
            cur, p = work.pop(0)
            for nxt in adj.get(cur, ()):
                if nxt == goal:
                    return p + [nxt]
                if nxt not in visited:
                    visited.add(nxt)
                    work.append((nxt, p + [nxt]))
        return None

    for (a, b), witness in sorted(edges.items()):
        key = frozenset((a, b))
        if key in seen:
            continue
        path_back = reachable_from(b, a)
        if path_back is None:
            continue
        seen.add(key)
        # witness for the return path: chain the first edge of it
        back_edges = list(zip(path_back, path_back[1:]))
        back_witness = "; ".join(edges[e] for e in back_edges
                                 if e in edges)
        line = None
        info_line = witness.rfind(":")
        if info_line != -1:
            tail = witness[info_line + 1:].split(")")[0]
            line = int(tail) if tail.isdigit() else None
        out.append(Diagnostic(
            code="PT-RACE-402", severity="error", path=model.path,
            line=line, var=" -> ".join([a, b]),
            message=(f"lock-order inversion between {a} and {b}: "
                     f"[{witness}] vs [{back_witness}]"),
            hint=("pick ONE global order for these locks and make "
                  "every path acquire in it (or collapse to a single "
                  "lock); the runtime watchdog "
                  "(telemetry.lockwatch) can confirm which orders "
                  "execute")))
    return out


def _check_403(model: _ModuleModel,
               ctx: Dict[str, frozenset]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for qual, info in sorted(model.functions.items()):
        entry = ctx.get(qual, frozenset())
        for desc, line, lex_held, kind in info.blocking:
            held = lex_held | entry
            if kind == _KIND_COND:
                # waiting on the condition itself releases it — only
                # OTHER held locks stall peers
                held = held - {desc.split(".wait")[0]}
            if not held:
                continue
            locks = ", ".join(sorted(held))
            out.append(Diagnostic(
                code="PT-RACE-403", severity="error", path=model.path,
                line=line, var=desc,
                message=(f"{qual} blocks on {desc} with no timeout "
                         f"while holding {locks}: a wedged peer turns "
                         f"the lock into a system-wide stall"),
                hint=("pass a timeout (loop on expiry) or move the "
                      "blocking call outside the lock")))
    return out


def _check_404(model: _ModuleModel) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for qual, info in sorted(model.functions.items()):
        for cond, line, in_while in info.cond_waits:
            if in_while:
                continue
            out.append(Diagnostic(
                code="PT-RACE-404", severity="error", path=model.path,
                line=line, var=cond,
                message=(f"{qual} calls {cond}.wait() outside a "
                         f"predicate loop: spurious/stolen wakeups "
                         f"make the post-wait state unchecked"),
                hint=("wrap in `while not predicate: cond.wait(...)` "
                      "or use cond.wait_for(predicate, ...)")))
    return out


def _check_405(model: _ModuleModel) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for qual, info in sorted(model.functions.items()):
        for line, daemon, _, target in info.threads:
            if daemon:
                continue
            # joined anywhere in the module (on any tracked thread
            # binding of the enclosing class, or any .join() textual
            # hit on a thread symbol)? The binding-level model: a
            # non-daemon thread is acceptable ONLY if some module code
            # joins a thread object — conservative at module scope.
            if model.joined_bindings:
                continue
            tgt = f" (target {target})" if target else ""
            out.append(Diagnostic(
                code="PT-RACE-405", severity="error", path=model.path,
                line=line, var=qual,
                message=(f"{qual} starts a non-daemon thread{tgt} that "
                         f"no code in this module ever joins: "
                         f"interpreter shutdown blocks on it forever"),
                hint=("pass daemon=True (and bound its loop on a stop "
                      "Event), or keep the Thread object and join it "
                      "on every shutdown path")))
    return out


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def analyze_source(src: str, path: str = "<string>"
                   ) -> List[Diagnostic]:
    """Run every PT-RACE checker over one module's source. Unparseable
    files return no findings here (``lint_source`` owns that
    diagnosis). Suppressions: ``# pt-lint: disable=PT-RACE-4xx
    <reason>`` on or above the flagged line (shared grammar with the
    repo linter; reason required)."""
    model = _build_model(src, path)
    if model is None:
        return []
    reachable = _thread_reachable(model)
    ctx = _entry_contexts(model)
    findings = (_check_401(model, reachable, ctx)
                + _check_402(model, ctx)
                + _check_403(model, ctx) + _check_404(model)
                + _check_405(model))
    findings.sort(key=lambda d: (d.line or 0, d.code))
    sup = _suppressions(src)
    out: List[Diagnostic] = []
    for d in findings:
        entries = [e for e in (sup.get(d.line),
                               sup.get((d.line or 0) - 1))
                   if e is not None and d.code in e[0]]
        if any(reason for _, reason in entries):
            continue
        if entries:
            d.message += (" [suppression ignored: pt-lint disable "
                          "comments require a reason]")
        out.append(d)
    return out


def analyze_file(path: str) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        return analyze_source(f.read(), path)


def _py_files(paths: Sequence[str],
              exclude: Sequence[str]) -> List[str]:
    """Deterministic ``*.py`` discovery shared by :func:`analyze_paths`
    and :func:`lock_order_graph` — ONE walk, so the watchdog's static
    graph is always built from the same file set the diagnostics pass
    covered."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in exclude)
                files.extend(os.path.join(root, n)
                             for n in sorted(names)
                             if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    return files


def analyze_paths(paths: Sequence[str],
                  exclude: Sequence[str] = ("__pycache__",)
                  ) -> List[Diagnostic]:
    """Analyze files and directory trees (``*.py`` only), deterministic
    order — the repo-wide entry ``tools/lint.py --select PT-RACE``
    drives."""
    out: List[Diagnostic] = []
    for f in _py_files(paths, exclude):
        out.extend(analyze_file(f))
    return out


def lock_order_graph(paths: Sequence[str]
                     ) -> Dict[Tuple[str, str], str]:
    """The static lock-acquisition graph over ``paths``: ``(A, B) ->
    witness`` meaning some code acquires B while holding A. Lock names
    are ``<parent_dir.stem>:<Class.attr|module.name>`` (see
    :func:`_module_name` — collision-safe across this tree's
    same-named modules) — the contract the runtime watchdog's
    :meth:`~paddle_tpu.telemetry.lockwatch.LockOrderWatchdog.
    verify_static` matches observed orderings against."""
    graph: Dict[Tuple[str, str], str] = {}
    for fpath in _py_files(paths, ("__pycache__",)):
        with open(fpath, encoding="utf-8") as f:
            src = f.read()
        model = _build_model(src, fpath)
        if model is None:
            continue
        trans = _transitive_acquires(model)
        for qual, info in model.functions.items():
            for a, b, line in info.nested:
                key = (f"{model.modname}:{a}", f"{model.modname}:{b}")
                graph.setdefault(key, f"{qual} {fpath}:{line}")
            for callee, line, held in info.calls:
                if not held or callee not in model.functions:
                    continue
                for lock, lline in trans.get(callee, ()):
                    for h in held:
                        if h != lock:
                            key = (f"{model.modname}:{h}",
                                   f"{model.modname}:{lock}")
                            graph.setdefault(
                                key, f"{qual} {fpath}:{line} via "
                                     f"{callee}")
    return graph
