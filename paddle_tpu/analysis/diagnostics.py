"""Typed diagnostic records — the one result currency every static
analyzer in this package speaks.

A :class:`Diagnostic` names WHAT is wrong (a stable ``PT-`` code + a
human message), WHERE (a Program node index / var name for the IR
verifier, a file:line for the repo linter, a state-leaf name for the
plan audit) and HOW TO FIX IT (the hint). Analyzers only *report*;
policy (raise / render / count) belongs to the caller — the Executor
raises on errors, ``Plan.describe`` embeds a summary, ``tools/lint.py``
sets the exit code.

Code registry (grep anchor — add new codes here, README carries the
user-facing table):

=============  ========================================================
PT-UBW-001     Program IR: read of an undefined or not-yet-written var
PT-DUP-002     Program IR: conflicting writes to one var
PT-DEAD-003    Program IR: dead op for the requested fetch slice
PT-FETCH-004   Program IR: fetch target undefined or unreachable
PT-SHAPE-005   Program IR: declared vs inferred shape/dtype mismatch
PT-MUT-006     Program IR: parameter written outside update ops
PT-DON-101     Donation: donated leaf is host-owned (numpy-backed)
PT-DON-102     Donation: donated leaf is a non-owning host view
PT-DON-103     Donation: donated argument unused by the step
PT-DON-104     Donation: donated buffer aliases a live/non-donated one
PT-SHARD-201   Plan audit: placed leaf would reshard at dispatch
PT-SHARD-202   Plan audit: explicit/pattern spec dropped (divisibility)
PT-SHARD-203   Plan audit: big leaf replicated under an fsdp plan
PT-SHARD-204   Plan audit: registered table not row-sharded under an
               ep plan (explicit override or indivisible vocab —
               every device pays the whole table)
PT-SHARD-205   Plan audit: table rows sharded over a batch axis
               (id-batch/table-axis mismatch — breaks lookup/exchange
               offset arithmetic)
PT-LINT-301    Repo lint: state-file write bypasses utils/atomic
PT-LINT-302    Repo lint: wall-clock time.time() inside a span body
PT-LINT-303    Repo lint: unnamed thread (Thread without name= /
               ThreadPoolExecutor without thread_name_prefix)
PT-LINT-304    Repo lint: device_get result flows into a donating call
PT-LINT-305    Repo lint: leftover debug hook (jax.debug.print, ...)
PT-LINT-306    Repo lint: HTTP hop without trace-header propagation
PT-LINT-307    Repo lint: SSE/chunked writer missing per-event flush
               or trace-header echo
PT-LINT-308    Repo lint: attend-path QuantizedPool dispatch branch
               outside ops/paged_kv.py (storage-form dispatch must
               stay at the one attend boundary; kernels take raw
               (values, scales) arrays)
PT-LINT-309    Repo lint: perf_counter()/time.time() delta around a
               jitted/compiled dispatch with no device fence before
               the stop-stamp (async dispatch: the delta times the
               enqueue, not the device — fence with
               block_until_ready / np.asarray / float(loss) first)
PT-TUNE-501    Tuning table: device-matched decode entry exists only
               under the legacy pre-int8 key — dtype-keyed entry
               missing (stale table; re-run tools/pallas_tune.py
               --decode on the chip)
PT-RACE-401    Concurrency: shared attribute written from a thread
               entry with no common lock
PT-RACE-402    Concurrency: lock-order inversion (cycle in the
               lock-acquisition graph, both witness paths named)
PT-RACE-403    Concurrency: timeout-less blocking call (join /
               queue.get / Event.wait / foreign Condition.wait)
               while holding a lock
PT-RACE-404    Concurrency: Condition.wait outside a predicate loop
PT-RACE-405    Concurrency: non-daemon thread never joined in its
               module
PT-PERF-801    Perf sentinel (warning): train-step wall time regressed
               past the rolling baseline band for this
               (program, backend) — warn-once; POST /profilez for a
               device trace, /statusz costs for the roofline; delete
               the baseline file to re-arm after an intended change
PT-PERF-802    Perf sentinel (warning): serving inter-token latency
               regressed past the rolling baseline band (same
               machinery as 801 over per-tick ITL)
PT-AOT-601     AOT serving (warning): --from-artifact boot rejected
               the serialized artifact (toolchain fingerprint drift,
               torn/unreadable artifact) and fell back to the trace
               path — the replica serves correctly but pays
               trace+compile cold start; re-export the artifact under
               the current jax/jaxlib to restore trace-free boots
=============  ========================================================
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Diagnostic:
    """One finding from a static pass. ``node``/``var`` locate inside a
    Program (or a state tree: ``var`` is the leaf name), ``path``/
    ``line`` locate inside a source file (the linter)."""

    code: str
    severity: str
    message: str
    hint: str = ""
    node: Optional[int] = None
    var: Optional[str] = None
    path: Optional[str] = None
    line: Optional[int] = None

    def __post_init__(self):
        from ..core.enforce import enforce

        enforce(self.severity in SEVERITIES,
                "diagnostic severity must be one of %s, got %r",
                SEVERITIES, self.severity)

    def location(self) -> str:
        if self.path is not None:
            return (f"{self.path}:{self.line}" if self.line is not None
                    else self.path)
        parts = []
        if self.node is not None:
            parts.append(f"op[{self.node}]")
        if self.var is not None:
            parts.append(f"var {self.var!r}")
        return " ".join(parts)

    def __str__(self) -> str:
        loc = self.location()
        s = f"{self.code} {self.severity}"
        if loc:
            s += f" at {loc}"
        s += f": {self.message}"
        if self.hint:
            s += f" (hint: {self.hint})"
        return s

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v not in (None, "")}


def errors(diags: List[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == "error"]


def has_errors(diags: List[Diagnostic]) -> bool:
    return any(d.severity == "error" for d in diags)


def format_diagnostics(diags: List[Diagnostic],
                       header: Optional[str] = None) -> str:
    """Multi-line render, errors first (stable within a severity)."""
    order = {s: i for i, s in enumerate(SEVERITIES)}
    ranked = sorted(diags, key=lambda d: order.get(d.severity, 99))
    n_err = sum(1 for d in diags if d.severity == "error")
    n_warn = sum(1 for d in diags if d.severity == "warning")
    lines = [header if header is not None else
             f"{len(diags)} finding(s): {n_err} error(s), "
             f"{n_warn} warning(s)"]
    lines += [f"  {d}" for d in ranked]
    return "\n".join(lines)
