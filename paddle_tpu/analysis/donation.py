"""Donation-safety analyzer — static provenance + aliasing checks for a
step about to be compiled with ``donate_argnums``.

The two worst bugs in this tree's history were donation bugs the
runtime only surfaced as intermittent heap corruption: the PR 6 SIGSEGV
(checkpoint-restored leaves ZERO-COPIED by the CPU PJRT client from
disk-loaded numpy temporaries, then DONATED by the next train step —
the runtime reused memory numpy still owned) and its snapshot-side twin
(``device_get`` views of live buffers saved while the step donated the
source). This module flags those classes *before the step runs*, as
typed :class:`..diagnostics.Diagnostic` errors.

Buffer-provenance taxonomy (the PR 6 classes):

- ``"numpy"``        — a host ``np.ndarray`` owning its data. Donating
  it is flagged: on the CPU backend the implicit ``device_put`` may
  zero-copy alias it, and donated state should be device-resident
  anyway.
- ``"host-view"``    — a host array that does NOT own its data
  (``device_get`` zero-copy views, slices). The most dangerous class:
  the donated buffer and the view share bytes.
- ``"host-backed"``  — a cpu-backend ``jax.Array`` *recorded* as
  zero-copying host memory (``note_transfer`` from ``Plan.place``, or
  anything created under :func:`track_host_transfers`).
- ``"owned"``        — recorded runtime-owned: the output of
  ``utils.memory.owned_on_device`` (the PR 6 fix — committed buffers
  the runtime allocated itself).
- ``"device"``       — a non-CPU-backend ``jax.Array``: the transfer
  copied host→HBM, always safe.
- ``"runtime"``      — a cpu ``jax.Array`` with no provenance record:
  the common safe case (any jnp computation result).

Provenance cannot be introspected from a live ``jax.Array`` (the CPU
client's zero-copy alias is invisible from the Python side), so it is
*recorded at the transfer site*: ``Plan.place`` notes its host→device
puts, ``owned_on_device`` notes its laundered copies, and
:func:`track_host_transfers` wraps ``jax.device_put`` /
``jax.make_array_from_callback`` for tests and forensics. Records live
in a ``WeakValueDictionary`` — they die with the array.

``Trainer.__init__`` runs :func:`check_donation` (provenance + alias
checks; no tracing) over its donated state once at compile time,
gated by ``FLAGS_static_verify`` — zero steady-state cost.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .diagnostics import Diagnostic

# id(array) -> (kind, weakref to the array). WeakValueDictionary drops
# the entry when the array dies, so a recycled id can never resolve to
# a stale kind; the kind string rides in a parallel dict pruned lazily.
_records: "weakref.WeakValueDictionary[int, Any]" = \
    weakref.WeakValueDictionary()
_kinds: dict = {}
_lock = threading.Lock()


def _note(x, kind: str) -> None:
    try:
        with _lock:
            _records[id(x)] = x
            _kinds[id(x)] = kind
            if len(_kinds) > 4 * (len(_records) + 64):
                # prune kinds whose arrays died (WeakValueDictionary
                # already dropped them)
                live = set(_records.keys())
                for k in list(_kinds):
                    if k not in live:
                        del _kinds[k]
    except TypeError:
        pass  # not weakref-able: nothing to record


def note_owned(x) -> Any:
    """Record ``x`` as runtime-owned (committed) — called by
    ``utils.memory.owned_on_device`` on its laundered copies."""
    _note(x, "owned")
    return x


def note_host_backed(x) -> Any:
    """Record ``x`` as a device array backed by host memory (the PR 6
    hazard class)."""
    _note(x, "host-backed")
    return x


def note_transfer(src, out) -> Any:
    """Record the provenance of one host→device transfer: when ``src``
    is a host array and ``out`` landed on the CPU backend, the client
    may have zero-copied — record ``out`` as host-backed until
    something launders it (``owned_on_device`` overrides the record).
    Non-fully-addressable results are NOT recorded: ``owned_on_device``
    deliberately passes them through unlaundered (it cannot copy leaves
    it only partially holds), so a record here would make the Trainer's
    compile-time check reject every multi-process placement."""
    import jax

    if (not isinstance(src, jax.Array)
            and isinstance(out, jax.Array) and _is_cpu(out)
            and getattr(out, "is_fully_addressable", True)):
        note_host_backed(out)
    return out


def _recorded_kind(x) -> Optional[str]:
    with _lock:
        got = _records.get(id(x))
        if got is not None and got is x:
            return _kinds.get(id(x))
    return None


def _is_cpu(x) -> bool:
    try:
        dev = next(iter(x.sharding.device_set))
    except Exception:
        return False
    return getattr(dev, "platform", None) == "cpu"


def classify_provenance(leaf) -> str:
    """Classify one leaf into the taxonomy above (module docstring)."""
    import jax

    if isinstance(leaf, np.ndarray):
        if leaf.base is not None or not leaf.flags["OWNDATA"]:
            return "host-view"
        return "numpy"
    if not isinstance(leaf, jax.Array):
        return "numpy" if hasattr(leaf, "__array_interface__") else \
            "runtime"
    rec = _recorded_kind(leaf)
    if rec is not None:
        return rec
    if not _is_cpu(leaf):
        return "device"
    return "runtime"


@contextlib.contextmanager
def track_host_transfers():
    """Record host-backed provenance for every ``jax.device_put`` /
    ``jax.make_array_from_callback`` result created in the body (tests,
    forensic repros). Reentrant; patches module attributes, so confine
    to single-threaded setup code."""
    import jax

    orig_put = jax.device_put
    orig_cb = jax.make_array_from_callback

    def put(x, *args, **kwargs):
        out = orig_put(x, *args, **kwargs)
        try:
            jax.tree_util.tree_map(note_transfer, x, out)
        except Exception:
            pass  # structure mismatch (custom trees): skip recording
        return out

    def from_callback(shape, sharding, data_callback, *a, **kw):
        out = orig_cb(shape, sharding, data_callback, *a, **kw)
        # the callback's numpy results are zero-copy candidates on cpu
        if _is_cpu(out):
            note_host_backed(out)
        return out

    jax.device_put = put
    jax.make_array_from_callback = from_callback
    try:
        yield
    finally:
        jax.device_put = orig_put
        jax.make_array_from_callback = orig_cb


# ---------------------------------------------------------------------------
# the check
# ---------------------------------------------------------------------------


def _leaves_with_paths(tree, prefix: str):
    import jax

    leaves_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in leaves_paths:
        name = prefix + jax.tree_util.keystr(path)
        out.append((name, leaf))
    return out


def _buffer_pointers(leaf) -> Tuple[int, ...]:
    """Best-effort backing-buffer addresses for alias detection: numpy
    data pointers and per-shard PJRT buffer pointers. Empty when the
    runtime doesn't expose them — the check degrades to identity."""
    import jax

    try:
        if isinstance(leaf, np.ndarray):
            return (leaf.__array_interface__["data"][0],)
        if isinstance(leaf, jax.Array) and getattr(
                leaf, "is_fully_addressable", False):
            return tuple(s.data.unsafe_buffer_pointer()
                         for s in leaf.addressable_shards)
    except Exception:
        pass
    return ()


_HAZARD_HINTS = {
    "numpy": "place the state on device (and through "
             "utils.memory.owned_on_device on the cpu backend) before "
             "donating it",
    "host-view": "copy the view to an owned array (np.array(x)) or "
                 "re-home it via utils.memory.owned_on_device",
    "host-backed": "launder through utils.memory.owned_on_device — the "
                   "cpu client zero-copied host memory into this "
                   "buffer (the PR 6 SIGSEGV class)",
}


def check_donation(args: Sequence[Any],
                   donate_argnums: Sequence[int],
                   fn=None, live: Any = None) -> List[Diagnostic]:
    """Static donation-safety check for ``fn(*args)`` compiled with
    ``donate_argnums``. ``fn`` is optional: with it, the step is traced
    once (``jax.make_jaxpr``) to flag donated-but-unused arguments;
    without it only the trace-free provenance + alias checks run (what
    the Trainer wires in at compile time). ``live`` is an optional
    pytree of buffers that must survive the step (staged prefetch
    batches, snapshot views) — a donated leaf aliasing one is an
    error. Nothing executes and nothing compiles."""
    import jax

    diags: List[Diagnostic] = []
    donate_set = set()
    for i in donate_argnums:
        j = int(i) + len(args) if int(i) < 0 else int(i)
        if 0 <= j < len(args):
            donate_set.add(j)
        else:
            diags.append(Diagnostic(
                code="PT-DON-103", severity="error",
                message=f"donate_argnums names argument {int(i)} but "
                        f"the step takes {len(args)}",
                hint="fix donate_argnums"))
    donate = sorted(donate_set)

    # -- provenance walk over donated leaves ----------------------------
    for i in donate:
        for name, leaf in _leaves_with_paths(args[i], f"arg{i}"):
            kind = classify_provenance(leaf)
            if kind in _HAZARD_HINTS:
                code = ("PT-DON-102" if kind == "host-view"
                        else "PT-DON-101")
                diags.append(Diagnostic(
                    code=code, severity="error", var=name,
                    message=f"donated leaf {name} is {kind}: donating "
                            f"hands memory the runtime does not own to "
                            f"the compiled step for reuse",
                    hint=_HAZARD_HINTS[kind]))

    # -- alias escapes: donated buffer reachable elsewhere --------------
    donated: List[Tuple[str, Any, Tuple[int, ...]]] = []
    others: List[Tuple[str, Any, Tuple[int, ...]]] = []
    for i, arg in enumerate(args):
        for name, leaf in _leaves_with_paths(arg, f"arg{i}"):
            if np.ndim(leaf) == 0 and not isinstance(leaf, np.ndarray):
                # eager scalars can legitimately be cached/shared by
                # the runtime; aliasing among them is not a hazard
                continue
            # pointers as a frozenset ONCE per leaf: the pairwise walk
            # below is O(P^2) and must not rebuild sets per comparison
            rec = (name, leaf, frozenset(_buffer_pointers(leaf)))
            (donated if i in donate else others).append(rec)
    if live is not None:
        for name, leaf in _leaves_with_paths(live, "live"):
            others.append((name, leaf,
                           frozenset(_buffer_pointers(leaf))))

    def _aliases(a, b) -> bool:
        (_, la, pa), (_, lb, pb) = a, b
        if la is lb:
            return True
        return bool(pa and pb and pa & pb)

    for j, rec in enumerate(donated):
        for other in donated[j + 1:]:
            if _aliases(rec, other):
                diags.append(Diagnostic(
                    code="PT-DON-104", severity="error", var=rec[0],
                    message=f"donated leaves {rec[0]} and {other[0]} "
                            f"share one buffer — the step would donate "
                            f"it twice",
                    hint="copy one of them before the call"))
        for other in others:
            if _aliases(rec, other):
                diags.append(Diagnostic(
                    code="PT-DON-104", severity="error", var=rec[0],
                    message=f"donated leaf {rec[0]} aliases {other[0]},"
                            f" which must survive the step — after "
                            f"donation that reference reads reused "
                            f"memory",
                    hint="copy the escaping reference (np.array / "
                         "jnp.copy) before donating"))

    # -- donated-but-unused (needs one trace) ---------------------------
    if fn is not None and donate:
        diags.extend(_check_unused(fn, args, donate))
    return diags


def _check_unused(fn, args, donate) -> List[Diagnostic]:
    import jax

    diags: List[Diagnostic] = []

    def absify(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(np.shape(leaf), leaf.dtype)
        return leaf

    try:
        abs_args = jax.tree_util.tree_map(absify, tuple(args))
        closed = jax.make_jaxpr(lambda *a: fn(*a))(*abs_args)
    except Exception as e:
        diags.append(Diagnostic(
            code="PT-DON-103", severity="warning",
            message=f"could not trace the step for the unused-donation "
                    f"check: {type(e).__name__}: {e}",
            hint="pass concrete example args, or skip fn="))
        return diags
    # duck-typed Literal test (jax.core.Literal has moved between jax
    # releases): literals carry .val, Vars do not
    def is_var(v):
        return not hasattr(v, "val")

    used = set()
    for eqn in closed.jaxpr.eqns:
        used.update(id(v) for v in eqn.invars if is_var(v))
    used.update(id(v) for v in closed.jaxpr.outvars if is_var(v))
    invars = list(closed.jaxpr.invars)
    # map flat invars back to argnums by per-arg leaf counts
    counts = [len(jax.tree_util.tree_leaves(a)) for a in args]
    pos = 0
    for i, n in enumerate(counts):
        arg_vars = invars[pos:pos + n]
        pos += n
        if i not in donate or not arg_vars:
            continue
        unused = [v for v in arg_vars if id(v) not in used]
        if unused and len(unused) == len(arg_vars):
            diags.append(Diagnostic(
                code="PT-DON-103", severity="error",
                message=f"argument {i} is donated but the step never "
                        f"reads any of its {len(arg_vars)} leaf "
                        f"buffer(s) — the donation frees nothing and "
                        f"invalidates the caller's reference for no "
                        f"benefit",
                hint="drop it from donate_argnums"))
    return diags
