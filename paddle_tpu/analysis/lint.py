"""Repo linter — AST checks for this framework's OWN invariants.

Generic linters don't know this codebase's hard-won rules; these five
were each paid for with a real bug class (codes in ``diagnostics.py``):

- **PT-LINT-301** — serialized state written through a bare
  ``open(path, "w")`` + ``json.dump``: a crash mid-write leaves a torn
  file a restarted reader trusts (the PR 2 compile-cache corruption
  class). State writes go through ``utils/atomic``. Writers that stage
  to a temp file and ``os.replace`` themselves are recognized.
- **PT-LINT-302** — wall-clock ``time.time()`` inside a telemetry span
  body (``with Span(...)`` / ``RecordEvent(...)``): spans measure with
  monotonic clocks; mixing in wall time yields negative/NTP-skewed
  durations. Timestamps belong outside the span or use
  ``time.perf_counter()``.
- **PT-LINT-303** — ``threading.Thread`` without ``name=`` (or a
  ``ThreadPoolExecutor`` without ``thread_name_prefix=``): an unnamed
  thread is undebuggable in /statusz thread dumps, py-spy profiles,
  and merged chrome-traces — an anonymous pool lane in a fleet trace
  is a lane nobody can attribute (this repo names threads ``pt-*``).
- **PT-LINT-304** — a ``jax.device_get`` result flowing into a
  donating call (``train_step`` / ``train_steps`` / ``_jit_*``):
  device_get returns zero-copy views on the CPU backend; donating the
  source invalidates them (the PR 6 snapshot SIGSEGV class).
- **PT-LINT-305** — leftover debug hooks: ``jax.debug.print``,
  ``jax.debug.breakpoint``, ``breakpoint()``, ``pdb.set_trace()``.
- **PT-LINT-310** — a ``urllib.request.urlopen`` /
  ``socket.create_connection`` call without an explicit ``timeout=``
  in the serving/telemetry/resilience/autoscale planes: an unbounded
  network wait on a gray peer (socket accepted, then silence) hangs
  the caller forever — exactly the failure mode the reliability
  plane's quarantine breaker exists to contain. Every hop carries its
  own bound.
- **PT-LINT-309** — a ``time.perf_counter()`` / ``time.time()`` delta
  taken around a jitted/compiled dispatch with no device fence before
  the stop-stamp: jax dispatch is async, so the delta times the Python
  enqueue (microseconds) instead of the device compute — a silently
  30x-flattering step time (the _train_bench docstring bug class, now
  a rule). Fence with ``jax.block_until_ready`` / ``np.asarray`` /
  ``float(loss)`` / ``.item()`` before subtracting the start stamp.

Suppression: append ``# pt-lint: disable=PT-LINT-303 <reason>`` to the
flagged line (or the line above). The reason is REQUIRED — a bare
suppression is ignored and the finding notes why. Multiple codes
comma-separate.

``tools/lint.py`` is the CLI (text or ``--format=json``); the ``lint``
stage of ``tools/ci.sh`` runs it over ``paddle_tpu/`` on every smoke+
build.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic

LINT_CODES = {
    "PT-LINT-301": "state-file write bypasses utils/atomic",
    "PT-LINT-302": "wall-clock time.time() inside a telemetry span body",
    "PT-LINT-303": "unnamed thread (Thread without name= / "
                   "ThreadPoolExecutor without thread_name_prefix)",
    "PT-LINT-304": "device_get result flows into a donating call",
    "PT-LINT-305": "leftover debug hook",
    "PT-LINT-306": "HTTP hop without trace-header propagation",
    "PT-LINT-307": "SSE/chunked response writer missing per-event "
                   "flush or trace-header echo",
    "PT-LINT-308": "attend-path QuantizedPool dispatch branch outside "
                   "ops/paged_kv.py",
    "PT-LINT-309": "timing delta around a jitted dispatch with no "
                   "device fence before the stop-stamp",
    "PT-LINT-310": "network call without an explicit timeout= in a "
                   "serving/telemetry/resilience module",
}

# callees whose arguments get donated (this repo's donating entry
# points); extend here when a new donating API lands
DONATING_CALLEES = {"train_step", "train_steps"}
DONATING_PREFIXES = ("_jit_",)

# calls that mark a function as doing its own atomic staging. The
# helpers are unambiguous by terminal name; os.replace must match its
# full dotted form — a bare terminal "replace" would let any
# str.replace() in the scope masquerade as atomic staging
ATOMIC_MARKERS = {"mkstemp", "atomic_write_text",
                  "atomic_write_bytes", "_atomic_write"}
ATOMIC_DOTTED = {"os.replace"}

SPAN_NAMES = {"Span", "RecordEvent"}

# PT-LINT-309: wrappers whose result is an ASYNC dispatcher (calling it
# returns before the device finishes), clock reads that start/stop a
# measurement, and the host-sync calls that fence a dispatch. The rule
# only trusts what it can prove in-scope: a name bound from a wrapper,
# the repo's donating entry points, or a _jit_* attribute — never
# "looks like a step function".
JIT_WRAPPERS = {"jit", "pjit", "compile_step", "steps_jit"}
TIMER_DOTTED = {"time.perf_counter", "time.time"}
FENCE_TERMINALS = {"block_until_ready", "device_get", "asarray",
                   "array", "item", "tolist"}
FENCE_BUILTINS = {"float", "int"}

# PT-LINT-306 (trace propagation) applies only to the serving/debug
# HTTP planes — the files whose request hops carry the distributed
# trace header. A POST-shaped urllib call (data=/method=) or a do_POST
# handler in these files must touch one of the TRACE_MARKERS helpers
# somewhere in its scope (telemetry.tracing's header surface).
TRACE_FILES = ("serving_router.py", "telemetry/server.py")
TRACE_MARKERS = {"_trace_headers", "trace_headers", "to_header",
                 "from_header"}

# PT-LINT-307 (streaming writers), same file set: a function that
# emits an SSE/chunked response (it mentions the text/event-stream
# content type) must FLUSH per event (a token buffered in the server
# is a token the client doesn't have — the whole point of per-token
# streaming) and touch the trace-header surface (echo X-PT-Trace) so
# the stream stays on the request's trace.
SSE_CONTENT_TYPE = "text/event-stream"

# PT-LINT-310 (bounded network I/O) applies to the planes that talk to
# possibly-gray peers: serving, telemetry, resilience, autoscale. A
# urlopen/create_connection there without timeout= waits forever on a
# wedged peer — the hang the reliability breaker quarantines, baked
# into a client call it can't see.
TIMEOUT_FILES = ("serving.py", "serving_router.py")
TIMEOUT_DIRS = ("/telemetry/", "/resilience/", "/autoscale/")

# PT-LINT-308: ops/paged_kv.py is THE storage-form dispatch boundary —
# attend() unpacks a QuantizedPool into raw (values, scales) arrays
# before anything kernel- or serving-side sees it. An isinstance branch
# on QuantizedPool anywhere else re-opens the pre-PR 15 drift hazard
# (two dispatch sites whose eligibility rules diverge silently).
POOL_DISPATCH_FILE = "ops/paged_kv.py"
POOL_DISPATCH_CLASS = "QuantizedPool"

_SUPPRESS_RE = re.compile(
    r"#\s*pt-lint:\s*disable=([A-Za-z0-9\-, ]+?)(?:\s+(.*))?$")


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _suppressions(src: str) -> Dict[int, Tuple[Set[str], str]]:
    out: Dict[int, Tuple[Set[str], str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            codes = {c.strip().upper() for c in m.group(1).split(",")
                     if c.strip()}
            out[i] = (codes, (m.group(2) or "").strip())
    return out


def _is_device_get(call: ast.Call) -> bool:
    return _terminal(call.func) == "device_get"


def _is_donating_callee(func: ast.AST) -> bool:
    name = _terminal(func)
    return (name in DONATING_CALLEES
            or any(name.startswith(p) for p in DONATING_PREFIXES))


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        norm = path.replace("\\", "/")
        self._trace_file = any(norm.endswith(f) for f in TRACE_FILES)
        self._timeout_file = (
            norm.endswith(TIMEOUT_FILES)
            or any(d in "/" + norm for d in TIMEOUT_DIRS))
        self._pool_dispatch_file = norm.endswith(POOL_DISPATCH_FILE)
        self.findings: List[Diagnostic] = []
        self._fence_fns: Set[str] = set()
        self._span_depth = 0
        # open-file bindings live per `with` body: name -> mode
        self._wfiles: List[Dict[str, str]] = []
        # per-scope ({terminal callee names}, {dotted callee names})
        self._scope_calls: List[Tuple[Set[str], Set[str]]] = []
        self._devget_names: List[Set[str]] = []

    # -- helpers ------------------------------------------------------------

    def _flag(self, code: str, node: ast.AST, message: str,
              hint: str) -> None:
        self.findings.append(Diagnostic(
            code=code, severity="error", message=message, hint=hint,
            path=self.path, line=getattr(node, "lineno", None)))

    def _scope_has_atomic(self) -> bool:
        if not self._scope_calls:
            return False
        terminals, dotted = self._scope_calls[-1]
        return bool(terminals & ATOMIC_MARKERS or dotted & ATOMIC_DOTTED)

    def _scope_has_trace_marker(self) -> bool:
        if not self._scope_calls:
            return False
        terminals, _ = self._scope_calls[-1]
        return bool(terminals & TRACE_MARKERS)

    # -- PT-LINT-309: unfenced timing around a jitted dispatch --------------

    def _scan_unfenced_timing(self, scope) -> None:
        """Linear statement-order scan of ONE scope (nested functions
        scan themselves): a ``timer_call() - <start_stamp>`` delta is
        flagged when a jitted dispatch happened since the last fence —
        the delta measured the async enqueue, not the device. Fences
        anywhere between dispatch and stop-stamp clear the hazard, so
        the standard bench shape (dispatch loop, ``float(loss)``,
        delta) stays silent."""
        jitted: Set[str] = set()
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                if _terminal(n.value.func) in JIT_WRAPPERS:
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Name):
                            jitted.add(tgt.id)
        timers: Set[str] = set()
        pending: List[Optional[str]] = [None]  # dispatch callee or None

        def is_timer(v: ast.AST) -> bool:
            return (isinstance(v, ast.Call)
                    and (_dotted(v.func) in TIMER_DOTTED
                         or _terminal(v.func) == "perf_counter"))

        def is_dispatch(call: ast.Call) -> Optional[str]:
            name = _terminal(call.func)
            if (name in jitted or _is_donating_callee(call.func)):
                return name
            # direct jax.jit(fn)(x) double-call
            if (isinstance(call.func, ast.Call)
                    and _terminal(call.func.func) in JIT_WRAPPERS):
                return _terminal(call.func.func)
            return None

        def see_exprs(node: ast.AST) -> None:
            for n in ast.walk(node):
                if (isinstance(n, ast.BinOp)
                        and isinstance(n.op, ast.Sub)
                        and isinstance(n.right, ast.Name)
                        and n.right.id in timers
                        and (is_timer(n.left)
                             or (isinstance(n.left, ast.Name)
                                 and n.left.id in timers))):
                    if pending[0]:
                        self._flag(
                            "PT-LINT-309", n,
                            f"timing delta over jitted dispatch "
                            f"{pending[0]!r} with no device fence: the "
                            f"delta measures the async enqueue, not "
                            f"the device",
                            "fence before the stop-stamp — "
                            "jax.block_until_ready(out), "
                            "np.asarray(out), float(loss) or "
                            ".item() — then subtract the start stamp")
                        pending[0] = None  # one finding per hazard
                    continue
                if not isinstance(n, ast.Call):
                    continue
                t = _terminal(n.func)
                if (t in FENCE_TERMINALS or t in self._fence_fns
                        or (isinstance(n.func, ast.Name)
                            and n.func.id in FENCE_BUILTINS
                            and n.args)):
                    pending[0] = None
                    continue
                d = is_dispatch(n)
                if d is not None:
                    pending[0] = d

        def bind_timers(stmt: ast.Assign) -> None:
            stamp = (is_timer(stmt.value)
                     or (isinstance(stmt.value, ast.IfExp)
                         and (is_timer(stmt.value.body)
                              or is_timer(stmt.value.orelse))))
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    (timers.add if stamp
                     else timers.discard)(tgt.id)

        def walk(body: Sequence[ast.stmt]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # nested scopes scan themselves
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    see_exprs(stmt.iter)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.While, ast.If)):
                    see_exprs(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        see_exprs(item.context_expr)
                    walk(stmt.body)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                else:
                    see_exprs(stmt)
                    if isinstance(stmt, ast.Assign):
                        bind_timers(stmt)

        walk(scope.body)

    # -- scopes -------------------------------------------------------------

    def _enter_scope(self, node) -> None:
        calls = [n.func for n in ast.walk(node)
                 if isinstance(n, ast.Call)]
        self._scope_calls.append(({_terminal(f) for f in calls},
                                  {_dotted(f) for f in calls}))
        self._devget_names.append(set())

    def visit_Module(self, node):
        # file-local fence helpers (benches wrap the host fetch in a
        # `_fence(out)` def): calling one fences for PT-LINT-309
        self._fence_fns = {
            fn.name for fn in ast.walk(node)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and any(isinstance(c, ast.Call)
                    and (_terminal(c.func) in FENCE_TERMINALS
                         or (isinstance(c.func, ast.Name)
                             and c.func.id in FENCE_BUILTINS
                             and c.args))
                    for c in ast.walk(fn))}
        self._enter_scope(node)
        self._scan_unfenced_timing(node)
        self.generic_visit(node)
        self._scope_calls.pop()
        self._devget_names.pop()

    def visit_FunctionDef(self, node):
        self._enter_scope(node)
        self._scan_unfenced_timing(node)
        # PT-LINT-306 (handler side): a POST dispatch handler in a
        # trace-plane file must consult the trace header (bind the
        # incoming context via tracing.from_header) — otherwise every
        # span its handlers produce silently drops off the request's
        # cross-process tree
        if (self._trace_file and node.name == "do_POST"
                and not self._scope_has_trace_marker()):
            self._flag(
                "PT-LINT-306", node,
                "do_POST handler does not propagate the trace header",
                "read headers[tracing.TRACE_HEADER], "
                "tracing.from_header + tracing.bind around the "
                "handler dispatch")
        # PT-LINT-307: an SSE/chunked response writer (it names the
        # text/event-stream content type) must flush per event and
        # echo the trace header — a buffered token defeats per-token
        # streaming, and an unechoed header drops the stream off the
        # request's trace
        if self._trace_file and any(
                isinstance(n, ast.Constant)
                and isinstance(n.value, str)
                and SSE_CONTENT_TYPE in n.value
                for n in ast.walk(node)):
            terminals, _ = self._scope_calls[-1]
            if "flush" not in terminals:
                self._flag(
                    "PT-LINT-307", node,
                    f"SSE writer {node.name!r} never flushes: tokens "
                    f"buffer server-side instead of streaming",
                    "call wfile.flush() after every data: event")
            if not self._scope_has_trace_marker():
                self._flag(
                    "PT-LINT-307", node,
                    f"SSE writer {node.name!r} does not propagate the "
                    f"trace header",
                    "echo tracing.TRACE_HEADER (ctx.to_header()) onto "
                    "the streaming response headers")
        self.generic_visit(node)
        self._scope_calls.pop()
        self._devget_names.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- with: spans + open files -------------------------------------------

    def visit_With(self, node):
        span = 0
        wf: Dict[str, str] = {}
        for item in node.items:
            if item.optional_vars is not None:
                self._bind_target(item.optional_vars, None)  # rebinds clean
            ctx = item.context_expr
            if isinstance(ctx, ast.Call):
                if _terminal(ctx.func) in SPAN_NAMES:
                    span += 1
                if _terminal(ctx.func) == "open":
                    mode = "r"
                    if len(ctx.args) >= 2 and isinstance(
                            ctx.args[1], ast.Constant):
                        mode = str(ctx.args[1].value)
                    for kw in ctx.keywords:
                        if kw.arg == "mode" and isinstance(
                                kw.value, ast.Constant):
                            mode = str(kw.value.value)
                    if mode.startswith("w") and isinstance(
                            item.optional_vars, ast.Name):
                        wf[item.optional_vars.id] = mode
        self._span_depth += span
        self._wfiles.append(wf)
        self.generic_visit(node)
        self._wfiles.pop()
        self._span_depth -= span

    # -- assignments: track device_get results ------------------------------

    def _bind(self, name: str, tainted: bool) -> None:
        """Record a name (re)binding in the current scope. A binding to
        anything but a device_get call CLEARS taint — `x = np.array(x)`
        is exactly the fix the 304 hint prescribes."""
        if not self._devget_names:
            return
        if tainted:
            self._devget_names[-1].add(name)
        else:
            self._devget_names[-1].discard(name)

    def _bind_target(self, target: ast.AST, value: Optional[ast.AST]):
        """One assignment-shaped binding: Name targets pair with their
        value (elementwise through matching tuple/list unpacking), any
        other rebinding form clears."""
        if isinstance(target, ast.Name):
            self._bind(target.id, isinstance(value, ast.Call)
                       and _is_device_get(value))
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(target.elts)
                    else [None] * len(target.elts))
            for t, v in zip(target.elts, elts):
                self._bind_target(t, v)

    def visit_Assign(self, node):
        for t in node.targets:
            self._bind_target(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._bind_target(node.target, node.value)
        self.generic_visit(node)

    def visit_For(self, node):
        # `for x in jax.device_get(tree)` iterates zero-copy views;
        # any other iterable rebinds the target clean each pass
        self._bind_target(node.target,
                          node.iter if isinstance(node.iter, ast.Call)
                          and _is_device_get(node.iter) else None)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    # -- calls: every rule's trigger site ------------------------------------

    def visit_Call(self, node):
        callee = _terminal(node.func)
        dotted = _dotted(node.func)

        # PT-LINT-305: leftover debug hooks
        if dotted in ("jax.debug.print", "jax.debug.breakpoint",
                      "pdb.set_trace") or (
                isinstance(node.func, ast.Name)
                and node.func.id == "breakpoint"):
            self._flag(
                "PT-LINT-305", node,
                f"leftover debug hook {dotted or 'breakpoint'}()",
                "remove before landing (gate behind a flag if it must "
                "stay)")

        # PT-LINT-303: unnamed threads
        if callee == "Thread" and dotted in ("threading.Thread",
                                             "Thread"):
            if not any(kw.arg == "name" for kw in node.keywords):
                self._flag(
                    "PT-LINT-303", node,
                    "threading.Thread without name=",
                    'name it "pt-<role>" so thread dumps and /statusz '
                    "stay readable")
        # PT-LINT-303 (pool form): an executor without a name prefix
        # produces anonymous ThreadPoolExecutor-N lanes in merged
        # chrome-traces (the /podz and trace fan-in pools did)
        if callee == "ThreadPoolExecutor":
            if not any(kw.arg == "thread_name_prefix"
                       for kw in node.keywords):
                self._flag(
                    "PT-LINT-303", node,
                    "ThreadPoolExecutor without thread_name_prefix=",
                    'pass thread_name_prefix="pt-<role>" so pool '
                    "lanes stay attributable in thread dumps and "
                    "merged traces")

        # PT-LINT-302: wall clock inside a span body
        if dotted == "time.time" and self._span_depth > 0:
            self._flag(
                "PT-LINT-302", node,
                "time.time() inside a telemetry span body",
                "span durations are monotonic — use "
                "time.perf_counter(), or move the wall-clock stamp "
                "outside the span")

        # PT-LINT-301: json.dump into a bare open(..., "w")
        if dotted == "json.dump" and len(node.args) >= 2:
            fobj = node.args[1]
            if (isinstance(fobj, ast.Name)
                    and any(fobj.id in wf for wf in self._wfiles)
                    and not self._scope_has_atomic()):
                self._flag(
                    "PT-LINT-301", node,
                    f"json.dump into open(..., 'w') file "
                    f"{fobj.id!r}: a crash mid-write leaves a torn "
                    f"file for the next reader",
                    "write via utils.atomic.atomic_write_text("
                    "path, json.dumps(...)) or stage + os.replace")

        # PT-LINT-306 (client side): a POST-shaped urllib call in a
        # trace-plane file whose scope never touches the trace-header
        # surface breaks cross-process propagation — every hop out of
        # the router/debug plane must carry X-PT-Trace
        if (self._trace_file
                and callee in ("Request", "urlopen")
                and dotted.startswith(("urllib.", "request."))
                and any(kw.arg in ("data", "method")
                        for kw in node.keywords)
                and not self._scope_has_trace_marker()):
            self._flag(
                "PT-LINT-306", node,
                f"HTTP request via {callee!r} built without trace-"
                f"header propagation",
                "build headers through _trace_headers(...) (or stamp "
                "tracing.current().to_header() onto "
                "tracing.TRACE_HEADER)")

        # PT-LINT-310: unbounded network I/O in the serving/telemetry/
        # resilience/autoscale planes. urlopen's timeout is also its
        # 3rd positional; create_connection's its 2nd — either form
        # counts as bounded.
        if self._timeout_file:
            unbounded = None
            if (callee == "urlopen" and len(node.args) < 3
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords)):
                unbounded = "urlopen"
            elif (callee == "create_connection"
                    and dotted in ("socket.create_connection",
                                   "create_connection")
                    and len(node.args) < 2
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords)):
                unbounded = "socket.create_connection"
            if unbounded:
                self._flag(
                    "PT-LINT-310", node,
                    f"{unbounded}() without an explicit timeout= in "
                    f"a serving/telemetry/resilience module: an "
                    f"unbounded wait on a gray peer hangs this "
                    f"caller forever",
                    "pass timeout=<seconds> — bound every hop; the "
                    "reliability plane can only quarantine hangs it "
                    "can observe")

        # PT-LINT-308: isinstance(x, QuantizedPool) outside the one
        # dispatch boundary — storage-form branches belong to
        # ops/paged_kv.py; everything downstream takes raw arrays
        if (callee == "isinstance" and not self._pool_dispatch_file
                and len(node.args) == 2):
            classes = (list(node.args[1].elts)
                       if isinstance(node.args[1], (ast.Tuple, ast.List))
                       else [node.args[1]])
            if any(_terminal(c) == POOL_DISPATCH_CLASS for c in classes):
                self._flag(
                    "PT-LINT-308", node,
                    "attend-path QuantizedPool dispatch branch outside "
                    "ops/paged_kv.py",
                    "keep storage-form dispatch at the attend boundary "
                    "(ops/paged_kv.py); pass raw (values, scales) "
                    "arrays across kernel/serving seams instead")

        # PT-LINT-304: device_get result into a donating call
        if _is_donating_callee(node.func):
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                hazard = (isinstance(arg, ast.Call)
                          and _is_device_get(arg))
                # name lookup is CURRENT-scope only: a tainted outer
                # name must not flag a nested function's unrelated
                # parameter/local of the same name (shadowing), and the
                # PR 6 hazard class is same-scope by nature
                hazard = hazard or (
                    isinstance(arg, ast.Name) and self._devget_names
                    and arg.id in self._devget_names[-1])
                if hazard:
                    self._flag(
                        "PT-LINT-304", node,
                        f"device_get result passed into donating call "
                        f"{callee!r}: device_get returns zero-copy "
                        f"views on the cpu backend and donation "
                        f"invalidates them",
                        "copy first (np.array / "
                        "utils.memory.owned_on_device)")
        self.generic_visit(node)


def lint_source(src: str, path: str = "<string>") -> List[Diagnostic]:
    """Lint one source string. Syntax errors come back as a single
    finding (a file the linter can't parse can't be certified)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Diagnostic(
            code="PT-LINT-305", severity="error", path=path,
            line=e.lineno, message=f"file does not parse: {e.msg}",
            hint="fix the syntax error")]
    linter = _Linter(path)
    linter.visit(tree)
    # the 309 scope scan emits at function-visit time, ahead of the
    # per-call visits inside the same function — re-establish the
    # documented line order before suppression filtering
    linter.findings.sort(key=lambda d: (d.line or 0, d.code))
    sup = _suppressions(src)
    out: List[Diagnostic] = []
    for d in linter.findings:
        # BOTH candidate lines are consulted: a same-line comment for a
        # different code (or a bare one) must not shadow a valid
        # reasoned suppression sitting directly above
        entries = [e for e in (sup.get(d.line), sup.get((d.line or 0) - 1))
                   if e is not None and d.code in e[0]]
        if any(reason for _, reason in entries):
            continue  # suppressed with a reason: silent
        if entries:
            d.message += (" [suppression ignored: pt-lint disable "
                          "comments require a reason]")
        out.append(d)
    return out


def lint_file(path: str) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_paths(paths: Sequence[str],
               exclude: Sequence[str] = ("__pycache__",)
               ) -> List[Diagnostic]:
    """Lint files and directory trees (``*.py`` only). Deterministic
    order: sorted paths, findings in line order per file."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in exclude)
                files.extend(os.path.join(root, n)
                             for n in sorted(names)
                             if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    out: List[Diagnostic] = []
    for f in files:
        out.extend(lint_file(f))
    return out
