"""Static Plan audit — resolve every state leaf's PartitionSpec against
the plan mesh *without placing anything* and report layout hazards
before any byte moves.

Five findings (codes in ``diagnostics.py``):

- **PT-SHARD-201 would-reshard** — a leaf already placed on the plan's
  mesh whose live sharding differs from what the plan resolves for its
  name: the compiled step's ``in_shardings`` will silently copy it
  device-to-device on every dispatch. Today this is only caught at
  runtime by ``guard_no_resharding``; the audit flags it statically.
- **PT-SHARD-202 spec dropped** — an explicit per-param spec or the
  first matching pattern rule names axes the leaf's dims don't divide
  by, so ``Plan.spec_for`` silently fell through to the next tier. The
  author asked for a layout they are not getting.
- **PT-SHARD-203 big leaf replicated** — under an fsdp plan, a leaf at
  or above ``byte_threshold`` resolved to full replication: every
  device pays its whole footprint, exactly what the plan was meant to
  avoid.
- **PT-SHARD-204 table not row-sharded under ep** — a param the plan
  registered via ``tables=`` resolved WITHOUT the ``ep`` table axis on
  its row dim under an ``ep > 1`` plan (explicit override, vocab
  indivisible, …): every device pays the whole table, exactly the HBM
  wall the ep axis exists to break.
- **PT-SHARD-205 table rows sharded over a batch axis** — a registered
  table's ROW dim is split over ``dp``/``fsdp``. Ids address rows
  globally while batch axes split the *id stream*; rows scattered over
  a batch axis make every lookup a cross-replica gather and the sparse
  exchange's shard-offset arithmetic wrong — the id-batch/table-axis
  mismatch.

``Plan.describe(params)`` embeds the audit summary (and /statusz's
sharding section rides describe), so the findings are visible on a
live run without extra wiring. Works on real arrays or anything with
``.shape``/``.dtype`` (``jax.ShapeDtypeStruct`` state templates).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .diagnostics import Diagnostic

# default replication-waste floor: 1 MiB per leaf
BIG_LEAF_BYTES = 1 << 20


def _spec_tuple(spec, ndim: int) -> tuple:
    """Normalize a PartitionSpec for comparison: tuple entries, padded
    with None to ``ndim`` (P('x') and P('x', None) are the same
    layout)."""
    t = tuple(tuple(e) if isinstance(e, (list, tuple)) else e
              for e in tuple(spec))
    return t + (None,) * (ndim - len(t))


def _leaf_bytes(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", np.dtype("float32"))
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = 4  # extended dtypes (PRNG keys)
    return int(np.prod(shape, dtype=np.int64)) * itemsize


def audit_plan(plan, state: Dict[str, Any], *,
               byte_threshold: int = BIG_LEAF_BYTES,
               specs: Optional[Dict[str, Any]] = None) -> List[Diagnostic]:
    """Audit ``name -> leaf`` state against ``plan``. Leaves may be
    live arrays (enables the would-reshard check) or abstract
    shape/dtype carriers. Returns diagnostics; places nothing.
    ``specs`` may carry already-resolved ``plan.spec_for`` results
    (``Plan.describe`` passes its own) so the audit doesn't re-resolve
    every leaf."""
    import jax
    from jax.sharding import NamedSharding

    is_table = getattr(plan, "is_table", None)
    plan_ep = int(getattr(plan, "ep", 1))
    batch_axes = set(getattr(plan, "batch_axes", ()) or ())

    def _dim0_axes(spec, ndim) -> set:
        t = _spec_tuple(spec, max(ndim, 1))
        e = t[0]
        if e is None:
            return set()
        return set(e) if isinstance(e, tuple) else {e}

    diags: List[Diagnostic] = []
    for name, leaf in state.items():
        shape = getattr(leaf, "shape", None)
        ndim = len(shape) if shape is not None else 0
        resolved = (specs[name] if specs is not None and name in specs
                    else plan.spec_for(name, leaf))

        requested = plan.requested_spec(name)
        if (requested is not None and shape is not None
                and not plan._divisible(leaf, requested)):
            diags.append(Diagnostic(
                code="PT-SHARD-202", severity="warning", var=name,
                message=f"{name}: requested spec {requested} does not "
                        f"divide shape {tuple(shape)} on this mesh — "
                        f"resolution fell through to {resolved}",
                hint="pad the dim to a multiple of the mesh axis, or "
                     "fix the rule/explicit spec"))

        if (plan.fsdp > 1 and shape is not None
                and _spec_tuple(resolved, ndim) == (None,) * ndim
                and _leaf_bytes(leaf) >= byte_threshold):
            diags.append(Diagnostic(
                code="PT-SHARD-203", severity="warning", var=name,
                message=f"{name}: {_leaf_bytes(leaf)} bytes fully "
                        f"replicated under an fsdp={plan.fsdp} plan — "
                        f"every device pays the whole leaf",
                hint="add a rule/explicit spec sharding one divisible "
                     "axis, or lower min_shard_size"))

        if is_table is not None and is_table(name):
            axes0 = _dim0_axes(resolved, ndim)
            if plan_ep > 1 and "ep" not in axes0:
                diags.append(Diagnostic(
                    code="PT-SHARD-204", severity="warning", var=name,
                    message=f"{name}: registered table resolved "
                            f"{resolved} under an ep={plan_ep} plan — "
                            f"rows are not sharded over the table "
                            f"axis, every device pays the whole "
                            f"table",
                    hint="make the vocab divisible by ep (pad the "
                         "table) and drop any explicit spec "
                         "overriding the table registration"))
            bad = axes0 & batch_axes
            if bad:
                diags.append(Diagnostic(
                    code="PT-SHARD-205", severity="error", var=name,
                    message=f"{name}: table ROWS sharded over batch "
                            f"axis {sorted(bad)} — ids address rows "
                            f"globally, so splitting the row dim over "
                            f"an id-batch axis breaks lookup/exchange "
                            f"offset arithmetic (id-batch/table-axis "
                            f"mismatch)",
                    hint="shard table rows over the 'ep' table axis "
                         "(tables= registration), never over "
                         "dp/fsdp"))

        if isinstance(leaf, jax.Array):
            sh = getattr(leaf, "sharding", None)
            if (isinstance(sh, NamedSharding) and sh.mesh == plan.mesh
                    and _spec_tuple(sh.spec, ndim)
                    != _spec_tuple(resolved, ndim)):
                diags.append(Diagnostic(
                    code="PT-SHARD-201", severity="error", var=name,
                    message=f"{name}: placed as {sh.spec} but the plan "
                            f"resolves {resolved} — every dispatch "
                            f"will reshard it device-to-device "
                            f"(guard_no_resharding would trip at "
                            f"runtime)",
                    hint="place the leaf via plan.place(), or align "
                         "the plan rule with the live placement"))
    return diags


def audit_summary(diags: List[Diagnostic],
                  limit: int = 16) -> Dict[str, Any]:
    """Compact dict for ``Plan.describe()`` / ``/statusz``."""
    return {
        "errors": sum(1 for d in diags if d.severity == "error"),
        "warnings": sum(1 for d in diags if d.severity == "warning"),
        "findings": [str(d) for d in diags[:limit]],
        "truncated": max(0, len(diags) - limit),
    }
