"""Program IR verifier — ahead-of-execution checking for the static
graph.

The reference Fluid interprets a protobuf ``ProgramDesc`` with no
pre-execution verification (reference: framework/executor.cc:149 — a
malformed program dies mid-run inside the op loop). The TensorFlow
paper's case for a declarative dataflow graph is exactly that it can be
*checked and transformed before it runs*; this module is that pass for
``static.program.Program``: pure static walks over the recorded op DAG,
no execution, returning :class:`..diagnostics.Diagnostic` records.

Checks (codes in ``diagnostics.py``):

- **PT-UBW-001** — an op reads a var that is neither a source (feed /
  param / captured const) nor written by an earlier node: undefined
  input, or use-before-write when a later node does produce it.
- **PT-DUP-002** — conflicting writes: a var written by two nodes where
  the re-writer is not an ``assign`` (the one sanctioned in-place
  update; sequential re-assigns are the optimizer's normal mutation)
  and not a write-back — a node that also reads the var it writes
  (``while``/``switch`` loop carries update in place by contract).
- **PT-DEAD-003** — ops outside the backward-reachability slice of the
  requested fetch list (persistable writes are live roots, matching
  ``executor.prune_for_fetch``). Only checked when a fetch list is
  given — without one every terminal op is a legitimate output.
- **PT-FETCH-004** — a fetch target that is not in the program, or is
  recorded but never produced by any kept node (the classic case:
  fetching a grad var from a ``clone(for_test=True)`` that cut the
  backward ops — previously a bare ``KeyError`` from inside jit
  tracing).
- **PT-SHAPE-005** — declared output shape/dtype vs re-derived abstract
  eval of the recorded fn (the same ``jax.eval_shape`` rule
  ``Program.apply`` used at record time): catches tampered metadata and
  ``eval_fn`` variants whose shapes drifted from their train twin.
- **PT-MUT-006** — a parameter var written by a node that is not an
  update op (``assign``): params may only mutate through the sanctioned
  update path.

``Executor.run`` wires :func:`verify_program` in as
verify-on-first-compile (once per program version, opt-out via
``FLAGS_static_verify``); ``debug.program_to_string`` /
``program_to_dot`` render the findings inline.
"""

from __future__ import annotations

import difflib
from typing import Dict, List, Optional, Sequence, Set

from .diagnostics import Diagnostic

# ops allowed to (re)write an existing var — the in-place update path
UPDATE_OPS = ("assign",)


def _source_names(program) -> Set[str]:
    """Vars that exist before any node runs: feeds, params (scope-backed
    persistables) and captured constants."""
    src = {n for n, v in program.vars.items()
           if getattr(v, "is_feed", False) or getattr(v, "is_param", False)}
    src.update(getattr(program, "_const_values", {}))
    return src


def _writer_map(program) -> Dict[str, List[int]]:
    out: Dict[str, List[int]] = {}
    for i, node in enumerate(program.nodes):
        for o in node.outputs:
            out.setdefault(o, []).append(i)
    return out


def _op_in_specs(program, node):
    """Rebuild the abstract input specs ``Program.apply`` evaluated the
    op under (TRACE_BATCH substituted for -1 placeholder dims)."""
    import jax
    import jax.numpy as jnp

    from ..static.program import TRACE_BATCH

    consts = getattr(program, "_const_values", {})
    specs = []
    for n in node.inputs:
        if n in consts:
            arr = jnp.asarray(consts[n])
            specs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
        else:
            v = program.vars[n]
            shape = tuple(TRACE_BATCH if d == -1 else d for d in v.shape)
            specs.append(jax.ShapeDtypeStruct(shape, v.dtype))
    return specs


def fetch_diagnostic(program, name: str) -> Diagnostic:
    """PT-FETCH-004 for one bad fetch target, with a close-name hint —
    the Executor routes its previously-opaque errors through this."""
    from ..static.program import _GradNode

    if name in program.vars:
        # recorded but unreachable: its producing node is gone (the
        # clone(for_test=True) cut) or never existed
        cut = any(isinstance(n, _GradNode) for n in program.nodes)
        why = ("its producing op is not in this program"
               + (" (a clone(for_test=True) drops backward/optimizer "
                  "ops but keeps their vars)" if not cut else ""))
        return Diagnostic(
            code="PT-FETCH-004", severity="error", var=name,
            message=f"fetch target {name!r} is recorded but never "
                    f"produced — {why}",
            hint="fetch a var produced by this program's ops, or run "
                 "the training program instead")
    close = difflib.get_close_matches(name, list(program.vars), n=3)
    hint = (f"did you mean {', '.join(repr(c) for c in close)}?"
            if close else "declare it with data()/create_parameter() or "
                          "fetch an op output")
    return Diagnostic(
        code="PT-FETCH-004", severity="error", var=name,
        message=f"fetch target {name!r} is not in the program "
                f"({len(program.vars)} vars recorded)",
        hint=hint)


def verify_program(program, fetch_list: Optional[Sequence] = None,
                   check_shapes: bool = True) -> List[Diagnostic]:
    """Run every IR check over ``program``; returns diagnostics (empty
    = clean). Pure static — nothing executes, nothing compiles."""
    from ..static.program import _GradNode

    diags: List[Diagnostic] = []
    sources = _source_names(program)
    writers = _writer_map(program)
    fetch_names = [f if isinstance(f, str) else f.name
                   for f in (fetch_list or [])]

    # -- def-use walk: UBW / DUP / MUT ----------------------------------
    written: Set[str] = set()
    first_writer: Dict[str, int] = {}
    for i, node in enumerate(program.nodes):
        if isinstance(node, _GradNode):
            reads = [node.loss_name] + list(node.param_names)
        else:
            reads = list(node.inputs)
        for n in reads:
            if n in sources or n in written:
                continue
            if n not in program.vars and n not in getattr(
                    program, "_const_values", {}):
                diags.append(Diagnostic(
                    code="PT-UBW-001", severity="error", node=i, var=n,
                    message=f"op[{i}] {node.name!r} reads {n!r}, which "
                            f"is not a var of this program",
                    hint="record the producing op first, or feed it "
                         "via data()"))
            elif any(j > i for j in writers.get(n, [])):
                diags.append(Diagnostic(
                    code="PT-UBW-001", severity="error", node=i, var=n,
                    message=f"op[{i}] {node.name!r} reads {n!r} before "
                            f"op[{min(j for j in writers[n] if j > i)}] "
                            f"writes it (use-before-write)",
                    hint="reorder the program so producers precede "
                         "consumers"))
            else:
                diags.append(Diagnostic(
                    code="PT-UBW-001", severity="error", node=i, var=n,
                    message=f"op[{i}] {node.name!r} reads {n!r}, which "
                            f"no op writes and no feed/param provides",
                    hint="the var is declared but never produced"))
        if isinstance(node, _GradNode):
            # only when the loss IS produced before this node (so the
            # generic read check above stayed silent) but every writer
            # sits past the differentiated prefix — a never-written or
            # later-written loss already got its PT-UBW-001 above
            if (node.loss_name in written
                    and all(j >= node.prefix_len
                            for j in writers.get(node.loss_name, []))):
                diags.append(Diagnostic(
                    code="PT-UBW-001", severity="error", node=i,
                    var=node.loss_name,
                    message=f"backward op[{i}] differentiates "
                            f"{node.loss_name!r}, which is not produced "
                            f"by its prefix (first {node.prefix_len} "
                            f"nodes)",
                    hint="append_backward must come after the loss ops"))
        for o in node.outputs:
            if o in written and first_writer.get(o) != i:
                # a node that also READS the var it writes is a
                # write-back by construction (while/switch loop carries:
                # outputs = carried inputs) — in-place is its contract,
                # not a conflict
                if node.name not in UPDATE_OPS and o not in reads:
                    diags.append(Diagnostic(
                        code="PT-DUP-002", severity="error", node=i,
                        var=o,
                        message=f"op[{i}] {node.name!r} re-writes "
                                f"{o!r}, already written by "
                                f"op[{first_writer[o]}] — only "
                                f"{UPDATE_OPS} ops or a write-back that "
                                f"reads its own output may update in "
                                f"place",
                        hint="give the second write a fresh output var"))
            else:
                first_writer.setdefault(o, i)
            written.add(o)
            v = program.vars.get(o)
            if (v is not None and getattr(v, "is_param", False)
                    and node.name not in UPDATE_OPS):
                diags.append(Diagnostic(
                    code="PT-MUT-006", severity="error", node=i, var=o,
                    message=f"op[{i}] {node.name!r} writes parameter "
                            f"{o!r} outside the update ops "
                            f"({', '.join(UPDATE_OPS)})",
                    hint="parameters mutate only through "
                         "Program.assign (optimizer updates)"))

    # -- fetch reachability + dead ops ----------------------------------
    produced = sources | set(writers)
    for f in fetch_names:
        if f not in program.vars or f not in produced:
            diags.append(fetch_diagnostic(program, f))
    valid_fetches = [f for f in fetch_names
                     if f in program.vars and f in produced]
    if valid_fetches:
        from ..static.executor import prune_for_fetch

        keep, _ = prune_for_fetch(program, valid_fetches)
        for i, node in enumerate(program.nodes):
            if isinstance(node, _GradNode) or i in keep:
                continue
            diags.append(Diagnostic(
                code="PT-DEAD-003", severity="warning", node=i,
                var=node.outputs[0] if node.outputs else None,
                message=f"op[{i}] {node.name!r} is dead for fetch "
                        f"{valid_fetches}: no fetch target or "
                        f"persistable write depends on it",
                hint="drop the op, or fetch one of its outputs"))

    # -- declared vs inferred shapes/dtypes -----------------------------
    if check_shapes:
        diags.extend(_check_shapes(program))
    return diags


def _check_shapes(program) -> List[Diagnostic]:
    import jax

    from ..static.program import _GradNode, _OpNode

    diags: List[Diagnostic] = []
    for i, node in enumerate(program.nodes):
        if isinstance(node, _GradNode):
            # grads mirror their params by construction
            for p, gname in zip(node.param_names, node.outputs):
                pv = program.vars.get(p)
                gv = program.vars.get(gname)
                if pv is None or gv is None:
                    continue
                if tuple(gv.shape) != tuple(pv.shape):
                    diags.append(Diagnostic(
                        code="PT-SHAPE-005", severity="error", node=i,
                        var=gname,
                        message=f"grad var {gname!r} declares shape "
                                f"{tuple(gv.shape)} but its param is "
                                f"{tuple(pv.shape)}",
                        hint="grad vars must mirror their parameter"))
            continue
        if not isinstance(node, _OpNode):
            continue
        # inputs must resolve before abstract eval can
        if any(n not in program.vars and n not in getattr(
                program, "_const_values", {}) for n in node.inputs):
            continue  # already PT-UBW-001
        try:
            out_specs = jax.eval_shape(node.fn,
                                       *_op_in_specs(program, node))
        except Exception as e:
            diags.append(Diagnostic(
                code="PT-SHAPE-005", severity="error", node=i,
                message=f"op[{i}] {node.name!r} fails abstract "
                        f"evaluation: {type(e).__name__}: {e}",
                hint="the recorded fn no longer matches its declared "
                     "inputs (did an eval_fn change arity?)"))
            continue
        flat = (out_specs if isinstance(out_specs, tuple)
                else (out_specs,))
        if len(flat) != len(node.outputs):
            diags.append(Diagnostic(
                code="PT-SHAPE-005", severity="error", node=i,
                message=f"op[{i}] {node.name!r} produces {len(flat)} "
                        f"output(s) but declares {len(node.outputs)}",
                hint="eval_fn variants must keep the train fn's "
                     "output arity"))
            continue
        for spec, oname in zip(flat, node.outputs):
            v = program.vars.get(oname)
            if v is None:
                continue
            declared, inferred = tuple(v.shape), tuple(spec.shape)
            # -1 declared dims are dynamic placeholders (the same ones
            # _op_in_specs substitutes TRACE_BATCH for on the way in) —
            # they match ANY inferred extent
            if len(declared) != len(inferred) or any(
                    d != -1 and d != s
                    for d, s in zip(declared, inferred)):
                diags.append(Diagnostic(
                    code="PT-SHAPE-005", severity="error", node=i,
                    var=oname,
                    message=f"op[{i}] {node.name!r} infers shape "
                            f"{inferred} for {oname!r} but it "
                            f"declares {declared}",
                    hint="the declared var metadata drifted from the "
                         "recorded fn"))
            elif str(spec.dtype) != str(v.dtype):
                diags.append(Diagnostic(
                    code="PT-SHAPE-005", severity="error", node=i,
                    var=oname,
                    message=f"op[{i}] {node.name!r} infers dtype "
                            f"{spec.dtype} for {oname!r} but it "
                            f"declares {v.dtype}",
                    hint="the declared var metadata drifted from the "
                         "recorded fn"))
    return diags
