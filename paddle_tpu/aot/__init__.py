"""AOT compiled-program plane: the *compiled program* as the
deployment unit (the reference's AnalysisPredictor stance, PAPER.md
layer 8).

``export_decoder`` serializes a warmed serving arena's compiled
decode/prefill executables (jax.export) + weights + config into a
committed two-phase artifact next to the checkpoint; ``load_decoder``
(``restore_and_run``) boots a serving replica from the artifact alone
— no Python model construction, no tracing — so elastic scale-up pays
artifact-load + dispatch, not trace + compile. Serving integration:
``launch.py --serve --from-artifact`` / ``serving_router.run_worker``
(PT-AOT-601 warn-once fallback to the trace path on fingerprint
mismatch).
"""

from .artifact import (ARTIFACT_FORMAT, AotCompatError, AotError,
                       artifact_dir_for_step, check_fingerprint,
                       export_decoder, fingerprint, latest_artifact,
                       read_manifest, resolve_artifact)
from .loader import AotTraceError, ModelStub, load_decoder

# the loader IS restore_and_run — the artifact-native bring-up named by
# the checkpoint plane's restore() lineage
restore_and_run = load_decoder

__all__ = [
    "ARTIFACT_FORMAT", "AotError", "AotCompatError", "AotTraceError",
    "ModelStub", "artifact_dir_for_step", "check_fingerprint",
    "export_decoder", "fingerprint", "latest_artifact", "load_decoder",
    "read_manifest", "resolve_artifact", "restore_and_run",
]
