"""AOT artifact: serialized compiled serving programs next to the
checkpoint.

The deployment unit here is the *compiled program*, not the Python
model (the reference's AnalysisPredictor stance, PAPER.md layer 8): an
artifact directory holds the ``jax.export``-serialized decode-step and
prefill-bucket executables of a warmed :class:`serving.BatchedDecoder`,
the weights/buffers snapshot they take as real arguments, and enough
host-side decoder config to rebuild the arena — so a serving replica
can boot from the artifact alone, without ever constructing (or
tracing through) the Python model object (``loader.load_decoder``).

Artifact layout (``aot_step_<N>`` next to the checkpoint's
``step_<N>``, or any standalone directory)::

    manifest.json        format, artifact id, compat fingerprint,
                         decoder config, program index, checksums,
                         plan shape, tuning-table snapshot
    state.npz            params + buffers (exotic dtypes bit-viewed)
    step_k<K>.jaxexp     serialized exported decode step (K tokens/dispatch)
    prefill_<LB>.jaxexp  serialized exported prefill, bucket length LB
    COMMITTED            written LAST in the staging dir (same two-phase
                         committed-write contract as checkpoint.py) —
                         an artifact is never observable torn

Compat: a serialized executable is only trusted under the producing
(jax, jaxlib, platform) triple — ``utils.compat.runtime_fingerprint``.
A mismatch raises :class:`AotCompatError`, which the serving bring-up
catches to fall back to the ordinary trace path (warn-once, typed
PT-AOT-601 diagnostic) rather than crash.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import EnforceError
from ..utils import compat as _compat
from ..utils.atomic import atomic_write_bytes, atomic_write_text

ARTIFACT_FORMAT = "paddle_tpu_aot/v1"
_MANIFEST = "manifest.json"
_STATE = "state.npz"
_COMMITTED = "COMMITTED"
# artifact dirs ride checkpoint naming: aot_step_<N> next to step_<N>
_AOT_RE = re.compile(r"^aot_step_(\d+)$")
_STEP_RE = re.compile(r"^step_(\d+)$")

# bit-view map for dtypes np.savez can't serialize natively — shared
# stance with checkpoint._EXOTIC (kept separate so an aot artifact
# never depends on checkpoint-module internals)
_EXOTIC = {"bfloat16": np.uint16, "float8_e4m3fn": np.uint8,
           "float8_e5m2": np.uint8}


class AotError(EnforceError):
    """Artifact unusable: missing, torn (no COMMITTED), checksum
    mismatch, or an unsupported decoder config at export."""


class AotCompatError(AotError):
    """Compat fingerprint mismatch: the artifact was produced under a
    different (jax, jaxlib, platform) triple. The serving bring-up
    treats this as "fall back to the trace path", never a crash."""


def _checksum(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _require(cond, exc, msg: str, *args) -> None:
    """enforce() with a typed exception class — readers branch on
    AotError (skip/fallback) vs AotCompatError (trace-path fallback)."""
    if not cond:
        raise exc(msg % args if args else msg)


def _encode_state(mstate) -> tuple:
    """(params, buffers) dicts -> (npz arrays, per-key dtype meta).
    Exotic dtypes (bf16/f8) are stored bit-viewed; meta records the
    true dtype for the loader's inverse view."""
    params, buffers = mstate
    arrays: Dict[str, np.ndarray] = {}
    meta: Dict[str, Dict[str, Any]] = {}
    for prefix, d in (("p", params), ("b", buffers)):
        for k, v in d.items():
            key = f"{prefix}:{k}"
            arr = np.asarray(jax.device_get(v))
            dt = str(arr.dtype)
            meta[key] = {"dtype": dt}
            view = _EXOTIC.get(dt)
            arrays[key] = arr.view(view) if view is not None else arr
    return arrays, meta


def _decode_state(npz, meta) -> tuple:
    params: Dict[str, Any] = {}
    buffers: Dict[str, Any] = {}
    for key in npz.files:
        arr = npz[key]
        dt = meta.get(key, {}).get("dtype")
        if dt and _EXOTIC.get(dt) is not None:
            import ml_dtypes

            arr = arr.view(getattr(ml_dtypes, dt))
        prefix, _, name = key.partition(":")
        (params if prefix == "p" else buffers)[name] = jnp.asarray(arr)
    return params, buffers


def _tuning_snapshot() -> Dict[str, Any]:
    """Copy of the pallas tuning table at export time — the artifact
    records WHICH tuned blocks its programs were compiled with, so a
    perf drift after a table re-tune is attributable."""
    try:
        from ..ops.pallas import tuning as _tuning

        return dict(_tuning._load())
    except Exception:
        return {}


def _plan_shape() -> Dict[str, Any]:
    """Device topology the programs were exported under (the Plan shape
    of a serving replica: today single-replica SPMD over the local
    devices — recorded so a topology change reads as a compat event,
    not a silent mis-rehydrate)."""
    return {"device_count": jax.device_count(),
            "platform": jax.default_backend()}


def _sharding_strs(exported) -> Dict[str, List[str]]:
    """Best-effort input/output sharding record (observability — the
    rehydrated call re-applies them from the serialized program
    itself)."""
    out = {}
    for field in ("in_shardings_hlo", "out_shardings_hlo"):
        val = getattr(exported, field, None)
        if val is not None:
            out[field] = [str(s) for s in val]
    return out


def fingerprint() -> Dict[str, str]:
    """This process's compat fingerprint (funnels through
    ``utils.compat.runtime_fingerprint``)."""
    return _compat.runtime_fingerprint()


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def export_decoder(decoder, directory: str, *,
                   step: Optional[int] = None,
                   buckets: Optional[List[int]] = None,
                   model_tag: Optional[str] = None) -> str:
    """Serialize ``decoder``'s compiled serving programs into
    ``directory`` (two-phase committed write; returns the final path).

    Exports the decode-step executables for k in {1, decode_steps} (the
    SLO degrade lever needs the k=1 program next to the full-k one) and
    the prefill executables for every prompt bucket the decoder has
    compiled so far plus any explicitly requested ``buckets`` (prompt
    lengths; bucketed via the decoder's own rounding). The weights ride
    along in ``state.npz`` — the compiled programs are weight-free
    (weights are real arguments), so one artifact is both the program
    store and the serving weight snapshot.

    Unsupported (typed error, never a silent partial artifact):
    speculative decoding (draft model), chunked prefill, and the paged
    prefix cache — their extra executables are not serialized yet.
    """
    _require(decoder.draft is None, AotError,
             "aot export does not cover speculative decoding (the "
             "draft/verify executables are not serialized) — export a "
             "plain decoder")
    _require(decoder.prefill_chunk is None, AotError,
             "aot export does not cover chunked prefill — export a "
             "whole-bucket-prefill decoder")
    _require(not (decoder.paged and decoder.prefix_cache), AotError,
             "aot export does not cover the paged prefix cache (suffix/"
             "restep executables are not serialized)")
    exp_mod = _compat.jax_export()
    gens = jnp.asarray(decoder._slot_gen.astype(np.uint32))

    blobs: Dict[str, bytes] = {}
    programs: Dict[str, Dict[str, str]] = {"steps": {}, "prefills": {}}
    shardings: Dict[str, Dict[str, List[str]]] = {}

    for kd in sorted({1, decoder.decode_steps}):
        fn = decoder._step_fns.get(kd)
        if fn is None:
            fn = decoder._step_fns[kd] = decoder._build_multi_step(kd)
        if decoder.paged:
            args = (decoder._mstate, decoder.pools,
                    jnp.asarray(decoder.table), decoder.tok, decoder.t,
                    gens)
        else:
            args = (decoder._mstate, decoder.caches, decoder.tok,
                    decoder.t, gens)
        exported = exp_mod.export(fn)(*args)
        fname = f"step_k{kd}.jaxexp"
        blobs[fname] = bytes(exported.serialize())
        programs["steps"][str(kd)] = fname
        shardings[fname] = _sharding_strs(exported)

    lbs = set()
    for key in decoder._prefill_cache:
        if decoder.paged and isinstance(key, tuple) and key[0] == "paged":
            lbs.add(int(key[1]))
        elif not decoder.paged and isinstance(key, int):
            lbs.add(key)
    for b in (buckets or ()):
        lbs.add(decoder._bucket_len(int(b)))
    # the router's warmup request always hits the smallest bucket —
    # cover it even on a never-warmed decoder
    lbs.add(decoder._bucket_len(1))
    for lb in sorted(lbs):
        padded = jnp.zeros((lb,), jnp.int32)
        if decoder.paged:
            fn = decoder._prefill_fn_paged(lb)
            row = jnp.zeros((decoder.n_log,), jnp.int32)
            args = (decoder._mstate, decoder.pools, row, padded, lb)
        else:
            fn = decoder._prefill_fn(lb)
            args = (decoder._mstate, decoder.caches, padded, lb, 0)
        exported = exp_mod.export(fn)(*args)
        fname = f"prefill_{lb}.jaxexp"
        blobs[fname] = bytes(exported.serialize())
        programs["prefills"][str(lb)] = fname
        shardings[fname] = _sharding_strs(exported)

    arrays, state_meta = _encode_state(decoder._mstate)

    attn_cfg: Dict[str, Any] = {"n_blocks": (
        len(decoder.pools) if decoder.paged else len(decoder.caches))}
    if decoder.paged:
        al = decoder._allocator
        attn_cfg.update(num_kv_heads=int(al.shape[2]),
                        head_dim=int(al.shape[3]))
        cache_spec = None
    else:
        # contiguous arenas: record each block's (k, v) leaf shapes so
        # the loader's model stub can mint identical zero arenas
        cache_spec = [[{"shape": list(leaf.shape),
                        "dtype": str(leaf.dtype)}
                       for leaf in jax.tree_util.tree_leaves(c)]
                      for c in decoder.caches]
    sampled_key = None
    if decoder.sampled:
        # the in-device pick chain baked the key into the exported
        # step; the HOST pick at activation needs the same key object
        try:
            sampled_key = np.asarray(
                jax.random.key_data(decoder.key)).tolist()
        except Exception:
            sampled_key = None
    decoder_cfg = {
        "slots": decoder.slots, "capacity": decoder.capacity,
        "prompt_bucket": decoder.bucket,
        "eos_id": decoder.eos_id,
        "temperature": decoder.temperature, "top_k": decoder.top_k,
        "top_p": decoder.top_p,
        "decode_steps": decoder.decode_steps,
        "paged": decoder.paged,
        "pages": (decoder._allocator.pages if decoder.paged else None),
        "page_size": (decoder.page_size if decoder.paged else None),
        "kv_dtype": (decoder._allocator.kv_dtype if decoder.paged
                     else None),
        "sampled_key": sampled_key,
        "cache_spec": cache_spec,
        **attn_cfg,
    }

    manifest = {
        "format": ARTIFACT_FORMAT,
        "step": step,
        "model_tag": model_tag,
        "fingerprint": fingerprint(),
        "plan": _plan_shape(),
        "tuning": _tuning_snapshot(),
        "decoder": decoder_cfg,
        "programs": programs,
        "shardings": shardings,
        "state_meta": state_meta,
        "checksums": {f: _checksum(b) for f, b in blobs.items()},
    }
    manifest["artifact_id"] = _checksum(json.dumps(
        {k: manifest[k] for k in ("fingerprint", "decoder", "checksums")},
        sort_keys=True).encode())[:16]
    text = json.dumps(manifest, indent=1)

    # two-phase committed write: every byte lands in the staging dir,
    # COMMITTED (carrying the manifest checksum) goes LAST, then ONE
    # atomic rename publishes marker and payload together — a reader
    # either sees a complete artifact or none (checkpoint.py contract)
    directory = os.path.abspath(directory)
    tmp = directory + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for fname, data in blobs.items():
        atomic_write_bytes(os.path.join(tmp, fname), data)
    with open(os.path.join(tmp, _STATE), "wb") as f:
        np.savez(f, **arrays)
    atomic_write_text(os.path.join(tmp, _MANIFEST), text)
    atomic_write_text(
        os.path.join(tmp, _COMMITTED),
        json.dumps({"format": ARTIFACT_FORMAT,
                    "manifest_checksum": _checksum(text.encode())}))
    if os.path.isdir(directory):
        trash = directory + ".old"
        if os.path.exists(trash):
            shutil.rmtree(trash)
        os.rename(directory, trash)
        os.replace(tmp, directory)
        shutil.rmtree(trash, ignore_errors=True)
    else:
        os.replace(tmp, directory)
    return directory


# ---------------------------------------------------------------------------
# read side
# ---------------------------------------------------------------------------

def read_manifest(directory: str) -> Dict[str, Any]:
    """Parse + verify an artifact's manifest. Typed :class:`AotError`
    on a missing/torn/corrupt artifact (COMMITTED absent, checksum
    mismatch, wrong format) — the bench's skip-cause path and the
    serving fallback both key off this."""
    _require(os.path.isdir(directory), AotError,
             "aot artifact %s: no such directory", directory)
    cpath = os.path.join(directory, _COMMITTED)
    _require(os.path.exists(cpath), AotError,
             "aot artifact %s is torn: COMMITTED marker absent (export "
             "died mid-write; the artifact must be ignored)", directory)
    try:
        with open(cpath) as f:
            commit = json.load(f)
        with open(os.path.join(directory, _MANIFEST)) as f:
            text = f.read()
    except (OSError, ValueError) as e:
        raise AotError(f"aot artifact {directory}: unreadable "
                       f"manifest/commit record ({e})")
    _require(
        _checksum(text.encode()) == commit.get("manifest_checksum"),
        AotError,
        "aot artifact %s: manifest checksum mismatch vs COMMITTED "
        "(corrupt or hand-edited artifact)", directory)
    man = json.loads(text)
    _require(man.get("format") == ARTIFACT_FORMAT, AotError,
             "aot artifact %s: format %r, this build reads %r",
             directory, man.get("format"), ARTIFACT_FORMAT)
    return man


def check_fingerprint(manifest: Dict[str, Any],
                      directory: str = "<artifact>") -> None:
    """Raise :class:`AotCompatError` unless the artifact's producing
    toolchain matches this process."""
    want = manifest.get("fingerprint") or {}
    have = fingerprint()
    drift = {k: (want.get(k), have.get(k)) for k in
             sorted(set(want) | set(have))
             if want.get(k) != have.get(k)}
    if drift:
        raise AotCompatError(
            f"aot artifact {directory}: compat fingerprint mismatch "
            + ", ".join(f"{k}: artifact={w!r} vs runtime={h!r}"
                        for k, (w, h) in drift.items())
            + " — serialized executables are only trusted under the "
            "producing toolchain; falling back to the trace path")


def load_state(directory: str, manifest: Dict[str, Any]) -> tuple:
    """The artifact's (params, buffers) snapshot as jax arrays."""
    with np.load(os.path.join(directory, _STATE)) as npz:
        return _decode_state(npz, manifest.get("state_meta", {}))


def load_programs(directory: str, manifest: Dict[str, Any]):
    """Deserialize every exported program (checksum-verified) ->
    ``(step_fns: {k: callable}, prefill_fns: {lb: callable})``. Each
    callable is ``jax.jit(exported.call)`` — jit-wrapped ONCE so the
    serving loop's per-tick dispatch hits the jit cache instead of
    re-staging the call primitive."""
    exp_mod = _compat.jax_export()
    checks = manifest.get("checksums", {})

    def _one(fname):
        try:
            with open(os.path.join(directory, fname), "rb") as f:
                data = f.read()
        except OSError as e:
            raise AotError(f"aot artifact {directory}: missing program "
                           f"{fname} ({e})")
        _require(_checksum(data) == checks.get(fname), AotError,
                 "aot artifact %s: checksum mismatch on %s (torn or "
                 "corrupt program blob)", directory, fname)
        exported = exp_mod.deserialize(bytearray(data))
        return jax.jit(exported.call)

    progs = manifest["programs"]
    step_fns = {int(k): _one(f) for k, f in progs["steps"].items()}
    prefill_fns = {int(k): _one(f) for k, f in progs["prefills"].items()}
    return step_fns, prefill_fns


# ---------------------------------------------------------------------------
# checkpoint-adjacent placement + selection
# ---------------------------------------------------------------------------

def artifact_dir_for_step(root: str, step: int) -> str:
    """Canonical artifact path for checkpoint step N: ``aot_step_<N>``
    next to ``step_<N>`` (GC in checkpoint.CheckpointManager prunes the
    pair together)."""
    return os.path.join(root, f"aot_step_{int(step)}")


def _is_committed(path: str) -> bool:
    return os.path.exists(os.path.join(path, _COMMITTED))


def latest_artifact(root: str) -> Optional[str]:
    """Newest COMMITTED ``aot_step_<N>`` under ``root`` whose
    checkpoint step is still alive. An artifact whose ``step_<N>`` dir
    was GC'd (or never committed) is NEVER selected — a stale program
    over deleted weights is exactly the torn state the committed
    two-phase path exists to prevent. Standalone artifacts (exported
    with no ``step=``, any directory name) are addressed by path, not
    through this selector."""
    try:
        names = os.listdir(root)
    except OSError:
        return None
    steps = []
    for name in names:
        m = _AOT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    for s in sorted(steps, reverse=True):
        apath = os.path.join(root, f"aot_step_{s}")
        if not _is_committed(apath):
            continue
        spath = os.path.join(root, f"step_{s}")
        if not os.path.exists(os.path.join(spath, "COMMITTED")):
            continue  # checkpoint gone/torn: stale artifact, skip
        return apath
    return None


def resolve_artifact(path: str) -> str:
    """``--from-artifact`` argument -> concrete artifact directory: a
    direct artifact dir passes through; a checkpoint root resolves via
    :func:`latest_artifact`. Typed :class:`AotError` when nothing
    selectable exists."""
    path = os.path.abspath(path)
    if os.path.exists(os.path.join(path, _MANIFEST)) or \
            os.path.exists(os.path.join(path, _COMMITTED)):
        return path
    got = latest_artifact(path)
    _require(got is not None, AotError,
             "no committed aot artifact under %s (no aot_step_<N> with "
             "a live checkpoint step; export one with "
             "aot.export_decoder)", path)
    return got
