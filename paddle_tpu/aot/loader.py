"""Trace-free serving bring-up from an AOT artifact.

:func:`load_decoder` rebuilds a ready-to-serve
:class:`serving.BatchedDecoder` from an artifact directory WITHOUT
constructing the Python model object: the "model" handed to the
decoder is a :class:`ModelStub` that only answers the host-side
questions the arena asks (cache geometry, weight/buffer snapshots) and
raises a typed :class:`AotTraceError` from every forward/trace entry
point — so if any code path would re-trace (an unseen prompt bucket,
a feature the artifact doesn't cover), it fails loudly instead of
silently recompiling, and the trace-free claim is pinned by tests that
boot from an artifact whose stub (and whose spec factory) booby-trap
tracing.

The decoder's compiled-fn caches (``_step_fns`` keyed by
tokens-per-dispatch, ``_prefill_cache`` keyed by prompt bucket) are
pre-seeded with the artifact's deserialized executables, each wrapped
``jax.jit(exported.call)`` ONCE so per-tick dispatch is a cache hit.
``warm_step()`` then dispatches the rehydrated step program — which is
what flips ``ready``/``/readyz`` — without ever touching the stub's
booby-trapped trace methods.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .artifact import (AotError, check_fingerprint, load_programs,
                       load_state, read_manifest, resolve_artifact)


class AotTraceError(AotError):
    """A trace-free (AOT-booted) replica hit a trace entry point: an
    unseen prompt bucket, an uncovered decode mode, or a code path the
    artifact does not serialize. The request should be re-routed (or
    the artifact re-exported with the missing bucket), never silently
    recompiled — the stub has no real model to trace."""


class _StubAttn:
    """Attention-shaped metadata the arena constructor reads: cache
    geometry for contiguous arenas, (num_kv_heads, head_dim) for the
    paged allocator."""

    def __init__(self, num_kv_heads: Optional[int],
                 head_dim: Optional[int], leaf_specs):
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self._leaf_specs = leaf_specs  # [{shape, dtype}, ...] or None

    def init_cache(self, batch: int, capacity: int, dtype=None):
        if self._leaf_specs is None:
            raise AotTraceError(
                "aot stub: init_cache called on a paged artifact — the "
                "paged arena mints pools from the allocator, never from "
                "the model")
        return tuple(jnp.zeros(tuple(s["shape"]), s["dtype"])
                     for s in self._leaf_specs)


class _StubBlock:
    def __init__(self, attn):
        self.self_attn = attn


def _trace_trap(name: str):
    def trap(self, *a, **k):
        raise AotTraceError(
            f"aot stub: {name} reached — this replica was booted "
            "trace-free from a serialized artifact and has no Python "
            "model to trace. An unseen prompt bucket or uncovered "
            "decode mode needs a re-export (aot.export_decoder with "
            "buckets=...) or the ordinary trace path")
    trap.__name__ = name
    return trap


class ModelStub:
    """Stands in for the model object inside an AOT-booted
    BatchedDecoder. Serves the host-side surface (``blocks`` metadata,
    ``named_parameters``/``named_buffers`` snapshots from the
    artifact); every traced-forward entry point is a booby trap."""

    def __init__(self, cfg: Dict[str, Any], params: Dict[str, Any],
                 buffers: Dict[str, Any]):
        self._params = params
        self._buffers = buffers
        n = int(cfg["n_blocks"])
        if cfg["paged"]:
            attns = [_StubAttn(int(cfg["num_kv_heads"]),
                               int(cfg["head_dim"]), None)
                     for _ in range(n)]
        else:
            spec = cfg["cache_spec"]
            attns = [_StubAttn(None, None, spec[i]) for i in range(n)]
        self.blocks = [_StubBlock(a) for a in attns]

    def named_parameters(self) -> Dict[str, Any]:
        return dict(self._params)

    def named_buffers(self) -> Dict[str, Any]:
        return dict(self._buffers)

    # every trace entry point the serving fn builders reach for —
    # set_parameters/set_buffers first (inject_state enters through
    # them before any logits method runs):
    set_parameters = _trace_trap("set_parameters")
    set_buffers = _trace_trap("set_buffers")
    _step_logits = _trace_trap("_step_logits")
    _chunk_logits = _trace_trap("_chunk_logits")
    _step_logits_paged = _trace_trap("_step_logits_paged")
    _chunk_logits_paged = _trace_trap("_chunk_logits_paged")
    _chunk_logits_rows = _trace_trap("_chunk_logits_rows")
    _chunk_logits_paged_rows = _trace_trap("_chunk_logits_paged_rows")
    forward = _trace_trap("forward")
    __call__ = _trace_trap("__call__")
    functional_call = _trace_trap("functional_call")


def load_decoder(path: str, *, check: bool = True):
    """Artifact directory (or checkpoint root) -> warmed-cache
    :class:`serving.BatchedDecoder` over a :class:`ModelStub` — the
    ``restore_and_run`` loader. No model construction, no tracing:
    the returned decoder's step/prefill caches hold the artifact's
    rehydrated executables; call ``warm_step()`` to dispatch once and
    flip ``ready``.

    ``check=False`` skips the fingerprint gate (tests only — a
    mismatched rehydrate can miscompile silently; serving always
    checks and falls back to the trace path instead)."""
    directory = resolve_artifact(path)
    man = read_manifest(directory)
    if check:
        check_fingerprint(man, directory)
    t0 = time.perf_counter()
    params, buffers = load_state(directory, man)
    cfg = man["decoder"]
    stub = ModelStub(cfg, params, buffers)

    key = None
    if cfg.get("sampled_key") is not None:
        try:
            key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(cfg["sampled_key"],
                                       np.uint32)))
        except Exception:
            key = jax.random.key(0)  # best-effort: stream differs,
            # distribution doesn't (greedy artifacts never get here)

    from .. import serving as _serving

    dec = _serving.BatchedDecoder(
        stub, int(cfg["slots"]), int(cfg["capacity"]),
        eos_id=cfg.get("eos_id"), key=key,
        temperature=float(cfg.get("temperature", 0.0)),
        top_k=int(cfg.get("top_k", 0)),
        top_p=float(cfg.get("top_p", 1.0)),
        prompt_bucket=int(cfg["prompt_bucket"]),
        pages=cfg.get("pages"),
        page_size=int(cfg.get("page_size") or 128),
        kv_dtype=cfg.get("kv_dtype"),
        decode_steps=int(cfg.get("decode_steps", 1)))

    step_fns, prefill_fns = load_programs(directory, man)
    dec._step_fns.update(step_fns)
    for lb, fn in prefill_fns.items():
        dec._prefill_cache[("paged", lb) if dec.paged else lb] = fn
    # cost-ledger provenance: the rehydrated programs register under
    # the SAME names the serving dispatch sites use, so when a tick
    # fills in their cost_analysis numbers the record still says
    # "aot" + which artifact. Zero-cost when telemetry is off.
    from ..telemetry import costs as _costs

    for kd in step_fns:
        _costs.note_aot_program(f"serving.step[k={kd}]",
                                artifact_id=man.get("artifact_id"))
    for lb in prefill_fns:
        name = (f"serving.prefill[paged,{lb}]" if dec.paged
                else f"serving.prefill[{lb}]")
        _costs.note_aot_program(name,
                                artifact_id=man.get("artifact_id"))
    # /statusz "aot" section source + bench TTFR provenance
    dec.aot_info = {
        "artifact": directory,
        "artifact_id": man.get("artifact_id"),
        "step": man.get("step"),
        "model_tag": man.get("model_tag"),
        "fingerprint": man.get("fingerprint"),
        "programs": {"steps": sorted(step_fns),
                     "prefill_buckets": sorted(prefill_fns)},
        "load_ms": (time.perf_counter() - t0) * 1e3,
    }
    return dec
