"""Autoscaling control plane — the router grows and shrinks its own
fleet.

Two layers, split so the decision core stays a pure function of
recorded inputs:

- :mod:`~paddle_tpu.autoscale.policy` — :class:`AutoscalePolicy`, a
  deterministic hysteresis-ladder + cooldown-window policy over the
  router's MEASURED signals (queue depth, dispatch-wait EWMA, load
  factor, shed deltas). No clock, no I/O: time rides in the signal
  row, so :func:`replay` over a recorded trace is bit-identical
  run-to-run.
- :mod:`~paddle_tpu.autoscale.scaler` — :class:`Scaler`, the control
  loop that snapshots ``Router.signals()``, records the rows as a
  replayable :class:`SignalTrace`, and ACTS: spawning a replica
  (pre-warmed from the AOT artifact when the spawn fn says so;
  placement stays ``/readyz``-gated exactly as at bring-up) and
  drain+retiring one on sustained headroom (fail-closed — the router
  purges the victim's placement hints the moment the drain starts).

The scale-up latency model is the MEASURED time-to-first-ready of the
last spawn (the worker's own boot stamp when reachable), fed back
into the policy's effective up-cooldown via the signal rows — never a
compile-time guess.
"""

from .policy import AutoscalePolicy, Decision, Signals, replay
from .scaler import Scaler, SignalTrace

__all__ = ["AutoscalePolicy", "Decision", "Signals", "replay",
           "Scaler", "SignalTrace"]
