"""Deterministic autoscaling policy: hysteresis ladders + cooldown
windows over the router's measured load signals.

Design rules (the never-flap contract):

- **Pure in time.** ``decide()`` reads the wall clock from the signal
  row (``sig["t"]``), never from ``time``; the only mutable state is
  the cooldown stamps and the headroom window start, all derived from
  prior rows. Replaying a recorded trace through a fresh policy
  (:func:`replay`) therefore reproduces every decision bit-identically.
- **Hysteresis.** The scale-up thresholds (``up_queue_wait_s``,
  ``up_load``) sit well ABOVE the scale-down ones
  (``down_queue_wait_s``, ``down_load``): the load band between them
  is dead — no oscillation driven by a signal hovering at one edge.
- **Cooldowns + the measured scale-up latency model.** After a scale
  up, the policy holds for ``cooldown_up_s`` PLUS the measured TTFR
  of the last artifact boot (``sig["ttfr_s"]``, recorded by the
  scaler; ``ttfr_hint_s`` until one is measured) — re-firing before
  the previous spawn could possibly have landed and relieved the
  signal is the classic thrash. Scale down needs ``headroom_hold_s``
  of SUSTAINED headroom first, then its own ``cooldown_down_s`` (also
  enforced against the last scale-up — never tear down what a spike
  just built).
- **Repair beats cooldown.** A fleet below ``min_replicas`` (replica
  deaths) scales up immediately — cooldowns model load response, not
  fault repair — but still one spawn at a time (a warming replica
  gates the next decision).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List

from ..core.enforce import enforce

# one recorded Router.signals() row (+ the scaler's derived fields:
# shed_delta, ttfr_s, warming adjusted for an in-progress spawn)
Signals = Dict[str, Any]
# one policy verdict: {"t", "action": hold|up|down, "reason", "n",
# "target"} — JSON-stable, the replay bit-identity unit
Decision = Dict[str, Any]


class AutoscalePolicy:
    """Hysteresis + cooldown scaling policy over one signal row.

    ``decide()`` is evaluated once per scaler tick and returns the
    action for THIS tick; the caller (the scaler, or :func:`replay`
    over a recorded trace) owns acting on it. All thresholds compare
    against the router's measured series: ``ewma_wait_s`` is the
    dispatch-queue wait EWMA (the same series the SLO shed ladder
    reads), load factor is in-flight over READY slots, and any shed
    since the last tick is an immediate scale-up vote (shedding while
    below max capacity means provisioning, not admission, is wrong).
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_queue_wait_s: float = 0.25, up_load: float = 2.0,
                 down_queue_wait_s: float = 0.05,
                 down_load: float = 0.5,
                 headroom_hold_s: float = 30.0,
                 cooldown_up_s: float = 10.0,
                 cooldown_down_s: float = 30.0,
                 ttfr_hint_s: float = 5.0):
        enforce(1 <= int(min_replicas) <= int(max_replicas),
                "need 1 <= min_replicas <= max_replicas, got %s..%s",
                min_replicas, max_replicas)
        enforce(down_load < up_load,
                "hysteresis needs down_load %s < up_load %s",
                down_load, up_load)
        enforce(down_queue_wait_s < up_queue_wait_s,
                "hysteresis needs down_queue_wait_s %s < "
                "up_queue_wait_s %s", down_queue_wait_s,
                up_queue_wait_s)
        enforce(headroom_hold_s >= 0 and cooldown_up_s >= 0
                and cooldown_down_s >= 0 and ttfr_hint_s >= 0,
                "windows must be >= 0")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_wait_s = float(up_queue_wait_s)
        self.up_load = float(up_load)
        self.down_queue_wait_s = float(down_queue_wait_s)
        self.down_load = float(down_load)
        self.headroom_hold_s = float(headroom_hold_s)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.ttfr_hint_s = float(ttfr_hint_s)
        self.reset()

    def reset(self) -> None:
        """Forget the cooldown stamps and headroom window — the state
        a fresh replay pass starts from."""
        self._last_up_t: Any = None
        self._last_down_t: Any = None
        self._headroom_since: Any = None

    def knobs(self) -> Dict[str, Any]:
        """The configured thresholds/windows (the /statusz payload)."""
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_queue_wait_s": self.up_queue_wait_s,
            "up_load": self.up_load,
            "down_queue_wait_s": self.down_queue_wait_s,
            "down_load": self.down_load,
            "headroom_hold_s": self.headroom_hold_s,
            "cooldown_up_s": self.cooldown_up_s,
            "cooldown_down_s": self.cooldown_down_s,
            "ttfr_hint_s": self.ttfr_hint_s,
        }

    def max_events(self, duration_s: float,
                   ttfr_s: Any = None) -> int:
        """The cooldown-implied CEILING on scale events over a window
        — the no-flap bound the bench gate asserts. One up at most per
        effective up-cooldown (cooldown + TTFR), one down at most per
        max(down cooldown, headroom hold), plus one boundary event
        each."""
        ttfr = self.ttfr_hint_s if ttfr_s is None else float(ttfr_s)
        up_period = max(1e-9, self.cooldown_up_s + ttfr)
        down_period = max(1e-9, max(self.cooldown_down_s,
                                    self.headroom_hold_s))
        return (int(duration_s / up_period) + 1
                + int(duration_s / down_period) + 1)

    def decide(self, sig: Signals) -> Decision:
        """Evaluate one signal row -> this tick's decision."""
        t = float(sig["t"])
        n = int(sig.get("replicas") or 0)
        warming = int(sig.get("warming") or 0)
        draining = int(sig.get("draining") or 0)
        slots = int(sig.get("slots") or 0)
        in_flight = int(sig.get("in_flight") or 0)
        queue_depth = int(sig.get("queue_depth") or 0)
        wait = sig.get("ewma_wait_s")
        shed = int(sig.get("shed_delta") or 0)
        ttfr = sig.get("ttfr_s")
        ttfr = self.ttfr_hint_s if ttfr is None else float(ttfr)

        def out(action: str, reason: str, target: int) -> Decision:
            if action == "up":
                self._last_up_t = t
                self._headroom_since = None
            elif action == "down":
                self._last_down_t = t
                self._headroom_since = None
            return {"t": t, "action": action, "reason": reason,
                    "n": n, "target": target}

        # fleet repair first: below the floor spawns NOW (deaths are
        # not load), one at a time; above the ceiling drains now
        if n < self.min_replicas:
            if warming == 0:
                return out("up", "below_min", n + 1)
            return out("hold", "below_min_warming", n)
        if n > self.max_replicas:
            if draining == 0:
                return out("down", "above_max", n - 1)
            return out("hold", "above_max_draining", n)

        # in-flight over READY capacity; an all-warming fleet (slots
        # == 0) with queued work reads as hot, but warming>0 already
        # holds any further spawn
        load = (in_flight / slots) if slots > 0 else float(in_flight)
        # the wait EWMA updates only ON dispatches, so it freezes at
        # its last value when traffic stops: it's a PRESENT-tense
        # signal only while work is actually in the system. Without
        # the busy gate a spike's stale-high EWMA reads as hot
        # forever and pins an idle fleet at max.
        busy = queue_depth > 0 or in_flight > 0
        hot = (shed > 0
               or (busy and wait is not None
                   and wait >= self.up_queue_wait_s)
               or load >= self.up_load)
        # true idleness (nothing in flight, nothing queued) is
        # unambiguous headroom regardless of the wait EWMA — the
        # router only updates ewma_wait_s ON dispatches, so after a
        # burst it stays stale-high forever at idle and the wait
        # condition alone would never let scale-down fire
        cold = (shed == 0 and queue_depth == 0
                and (in_flight == 0
                     or (load <= self.down_load
                         and (wait is None
                              or wait <= self.down_queue_wait_s))))

        # sustained-headroom window: any non-cold tick (or an active
        # spawn/drain, or sitting at the floor) restarts the clock
        if (cold and n > self.min_replicas and warming == 0
                and draining == 0):
            if self._headroom_since is None:
                self._headroom_since = t
        else:
            self._headroom_since = None

        if hot:
            if n >= self.max_replicas:
                return out("hold", "hot_at_max", n)
            if warming > 0:
                return out("hold", "hot_warming", n)
            if (self._last_up_t is not None
                    and t - self._last_up_t
                    < self.cooldown_up_s + ttfr):
                # the scale-up latency model: don't re-fire before the
                # last spawn (measured TTFR) plus the cooldown could
                # have relieved the signal
                return out("hold", "hot_cooldown", n)
            return out("up", "hot", n + 1)

        if (self._headroom_since is not None
                and t - self._headroom_since >= self.headroom_hold_s):
            if (self._last_down_t is not None
                    and t - self._last_down_t < self.cooldown_down_s):
                return out("hold", "cold_cooldown", n)
            if (self._last_up_t is not None
                    and t - self._last_up_t < self.cooldown_down_s):
                # never tear down what a spike just built
                return out("hold", "cold_post_up", n)
            return out("down", "sustained_headroom", n - 1)

        return out("hold", "steady", n)


def replay(policy: AutoscalePolicy,
           rows: Iterable[Signals]) -> List[Decision]:
    """Re-evaluate a recorded signal trace from a clean slate — the
    deterministic offline twin of the live loop. The trace rows carry
    every input ``decide()`` reads (including the measured ``ttfr_s``
    the scaler stamped), so for the same rows and knobs the decision
    list is bit-identical run-to-run — and identical to what the live
    scaler decided when it recorded them."""
    policy.reset()
    return [policy.decide(dict(row)) for row in rows]
