"""The autoscale control loop: watch the router's measured signals,
record them as a replayable trace, and act — spawn on sustained
pressure, drain+retire on sustained headroom.

The loop body (:meth:`Scaler.tick`) is deliberately thin:

1. snapshot ``Router.signals()`` (pure read of the poll/dispatch
   paths' own series);
2. derive the policy inputs the router can't know — the shed delta
   since the last tick, the measured TTFR of the last spawn, and an
   in-progress spawn counted as warming;
3. append the row to the :class:`SignalTrace` (JSONL when a path is
   given) — the row IS the policy's whole world, which is what makes
   :func:`~paddle_tpu.autoscale.policy.replay` bit-identical;
4. ``policy.decide(row)`` and act on up/down.

Actions run on background threads so a slow worker boot (seconds even
from an AOT artifact) never stalls the decision cadence; the policy
holds while one is in flight (warming/draining counts). Scale-up
measures its own latency — the worker's boot-to-ready stamp when the
handle exposes ``/statusz``, else the spawn wall time — and feeds it
back as the ``ttfr_s`` signal field: the scale-up latency model is
MEASURED, per the fleet actually serving, not configured. Scale-down
picks the least-loaded live replica, asks the router to drain it
(fail-closed: placement hints die immediately), waits for
``drain_done``, then removes+closes it. A victim that DIES mid-drain
is already handled: the router requeues its in-flight and
``drain_done`` reports true, so the drain thread just completes the
removal.

Chaos points (``resilience.faults``): ``autoscale.spawn`` fires before
each spawn attempt, ``autoscale.drain`` before each drain (``path`` =
the victim name) — a raising rule turns either into the
spawn-failure / drain-failure path deterministically.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import telemetry
from ..core.enforce import enforce
from ..telemetry import tracing as _tracing
from .policy import AutoscalePolicy, Decision, Signals


@telemetry.cached_instruments
def _autoscale_metrics(reg):
    return {
        "decisions": {
            action: reg.counter(
                "pt_autoscale_decisions_total",
                "scaler policy decisions by action",
                labels={"action": action})
            for action in ("hold", "up", "down")},
        "scale_ups": reg.counter(
            "pt_autoscale_scale_ups_total",
            "replicas spawned by the scaler"),
        "scale_downs": reg.counter(
            "pt_autoscale_scale_downs_total",
            "replicas drained and retired by the scaler"),
        "spawn_failures": reg.counter(
            "pt_autoscale_spawn_failures_total",
            "scale-up attempts that failed to produce a ready "
            "replica"),
        "target": reg.gauge(
            "pt_autoscale_target_replicas",
            "the policy's current replica target"),
        "ttfr": reg.gauge(
            "pt_autoscale_ttfr_seconds",
            "measured scale-up latency: last spawn's "
            "time-to-first-ready", unit="s"),
    }


class SignalTrace:
    """Append-only record of the signal rows the policy saw — the
    replay substrate. With a ``path``, every row is also persisted as
    one JSON line (``sort_keys``) as it lands, so a crashed run still
    leaves a replayable trace."""

    def __init__(self, path: Optional[str] = None):
        self.rows: List[Signals] = []
        self.path = path
        self._f = open(path, "w") if path else None

    def append(self, sig: Signals) -> None:
        self.rows.append(sig)
        if self._f is not None:
            self._f.write(json.dumps(sig, sort_keys=True) + "\n")
            self._f.flush()

    @classmethod
    def load(cls, path: str) -> "SignalTrace":
        tr = cls()
        with open(path) as f:
            tr.rows = [json.loads(line) for line in f
                       if line.strip()]
        return tr

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __iter__(self):
        return iter(self.rows)

    def __len__(self) -> int:
        return len(self.rows)


class Scaler:
    """The control loop over one :class:`~paddle_tpu.serving_router.
    Router`. ``spawn_fn`` returns ONE started+warmed replica handle
    (typically a ``spawn_replicas(..., from_artifact=..., n=1)[0]``
    closure — the artifact pre-warm path); the scaler adds it to the
    router, measures its TTFR, and feeds that into the policy's
    effective up-cooldown. Tests drive :meth:`tick` directly for
    deterministic schedules; :meth:`start` runs it on a cadence."""

    def __init__(self, router, policy: AutoscalePolicy,
                 spawn_fn: Callable[[], Any],
                 interval_s: float = 1.0,
                 trace_path: Optional[str] = None,
                 drain_timeout_s: float = 120.0,
                 retire_fn: Optional[Callable[[Any], None]] = None):
        enforce(interval_s > 0, "interval_s must be > 0, got %s",
                interval_s)
        self.router = router
        self.policy = policy
        self.spawn_fn = spawn_fn
        # how a drained replica leaves the fleet: by default its
        # handle is closed (the instance is destroyed — the artifact
        # it booted from remains on disk for the next spawn); a
        # retire_fn instead receives the still-open handle, e.g. to
        # return a pre-warmed replica to a pool
        self.retire_fn = retire_fn
        self.interval_s = float(interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.trace = SignalTrace(trace_path)
        self.decisions: List[Decision] = []
        self.events: List[Dict[str, Any]] = []
        self.spawn_failures = 0
        self.ttfr_s: Optional[float] = None
        self._shed_prev: Optional[int] = None
        self._mu = threading.Lock()
        self._spawning = False
        self._draining_name: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._bg: List[threading.Thread] = []
        # (t, live replica count) change-points — the replica-seconds
        # integral the bench's provisioning-cost gate reads; draining
        # replicas still count (they hold resources until removed)
        self.timeline: List[Tuple[float, int]] = []
        self._note_fleet()

    # -- the loop body ------------------------------------------------------

    def tick(self) -> Decision:
        """One control-loop evaluation: snapshot, derive, record,
        decide, act. Returns the decision (tests assert on it)."""
        sig = self.router.signals()
        shed = int(sig.get("shed_total") or 0)
        sig["shed_delta"] = (0 if self._shed_prev is None
                             else max(0, shed - self._shed_prev))
        self._shed_prev = shed
        with self._mu:
            if self._spawning:
                # an in-flight spawn counts as a warming replica: the
                # policy must not re-fire into it, and the recorded
                # row carries the adjustment so replay sees the same
                # world the live decision did
                sig["warming"] = int(sig.get("warming") or 0) + 1
                sig["replicas"] = int(sig.get("replicas") or 0) + 1
            if self._draining_name is not None:
                sig["draining"] = max(1, int(sig.get("draining")
                                             or 0))
        if self.ttfr_s is not None:
            sig["ttfr_s"] = self.ttfr_s
        self.trace.append(sig)
        d = self.policy.decide(sig)
        self.decisions.append(d)
        if telemetry.enabled():
            m = _autoscale_metrics()
            m["decisions"][d["action"]].inc()
            m["target"].set(d["target"])
            if d["action"] != "hold":
                _tracing.event("autoscale.decision",
                               action=d["action"],
                               reason=d["reason"], n=d["n"],
                               target=d["target"])
        if d["action"] == "up":
            self._scale_up(d)
        elif d["action"] == "down":
            self._scale_down(d)
        self._note_fleet()
        return d

    # -- actions ------------------------------------------------------------

    def _scale_up(self, d: Decision) -> None:
        with self._mu:
            if self._spawning:
                return  # belt+braces: never double-spawn
            self._spawning = True
        t = threading.Thread(target=self._spawn_bg, args=(d,),
                             daemon=True, name="pt-autoscale-spawn")
        t.start()
        self._bg.append(t)

    def _spawn_bg(self, d: Decision) -> None:
        from ..resilience import faults as _faults

        t0 = time.monotonic()
        try:
            inj = _faults.active()
            if inj is not None:
                inj.fire("autoscale.spawn")
            rep = self.spawn_fn()
            self.router.add_replica(rep)
            ttfr = self._replica_ttfr(rep, time.monotonic() - t0)
            with self._mu:
                self.ttfr_s = ttfr
            self.events.append({
                "t": time.monotonic(), "event": "scale_up",
                "replica": rep.name, "reason": d["reason"],
                "ttfr_s": ttfr})
            if telemetry.enabled():
                m = _autoscale_metrics()
                m["scale_ups"].inc()
                m["ttfr"].set(ttfr)
                _tracing.event("autoscale.scale_up",
                               replica=rep.name,
                               reason=d["reason"],
                               ttfr_s=ttfr)
        except Exception as e:
            with self._mu:
                self.spawn_failures += 1
            self.events.append({
                "t": time.monotonic(), "event": "spawn_failed",
                "error": repr(e)})
            print(f"[PT-AS-701] autoscale spawn failed (the policy "
                  f"retries after its cooldown): {e!r}",
                  file=sys.stderr)
            if telemetry.enabled():
                _autoscale_metrics()["spawn_failures"].inc()
                _tracing.event("autoscale.spawn_failed",
                               error=repr(e))
        finally:
            with self._mu:
                self._spawning = False
            self._note_fleet()

    @staticmethod
    def _replica_ttfr(rep, wall_s: float) -> float:
        """The measured TTFR: the worker's own boot-to-ready stamp
        (its /statusz aot section) when the handle is a worker
        process, else the spawn-call wall time (in-process spawns)."""
        try:
            status = rep._get("/statusz")["status"]
            ttfr_ms = status["aot"]["ttfr_ms"]
            if ttfr_ms:
                return float(ttfr_ms) / 1e3
        except Exception:
            pass
        return wall_s

    def _scale_down(self, d: Decision) -> None:
        victim = self._pick_victim()
        if victim is None:
            self.events.append({"t": time.monotonic(),
                                "event": "no_victim"})
            return
        with self._mu:
            if self._draining_name is not None:
                return  # one drain at a time
            self._draining_name = victim
        t = threading.Thread(target=self._drain_bg,
                             args=(victim, d), daemon=True,
                             name="pt-autoscale-drain")
        t.start()
        self._bg.append(t)

    def _pick_victim(self) -> Optional[str]:
        """Least-loaded live non-draining replica (ties break by
        name — deterministic), guarded by the policy floor."""
        candidates = []
        for name, row in self.router.loads().items():
            if not row["alive"] or row["draining"]:
                continue
            ld = row.get("load") or {}
            busy = (int(row.get("inflight") or 0)
                    + int(ld.get("queue_depth", 0) or 0)
                    + int(ld.get("active_slots", 0) or 0))
            candidates.append((busy, name))
        if len(candidates) <= self.policy.min_replicas:
            return None
        candidates.sort()
        return candidates[0][1]

    def _drain_bg(self, name: str, d: Decision) -> None:
        from ..resilience import faults as _faults

        try:
            inj = _faults.active()
            if inj is not None:
                inj.fire("autoscale.drain", path=name)
            self.router.drain_replica(name)
            deadline = time.monotonic() + self.drain_timeout_s
            while (time.monotonic() < deadline
                   and not self._stop.is_set()):
                if self.router.drain_done(name):
                    break
                time.sleep(min(0.05, self.interval_s))
            handle = self.router.remove_replica(
                name, close=self.retire_fn is None)
            if self.retire_fn is not None:
                self.retire_fn(handle)
            self.events.append({
                "t": time.monotonic(), "event": "scale_down",
                "replica": name, "reason": d["reason"]})
            if telemetry.enabled():
                _autoscale_metrics()["scale_downs"].inc()
                _tracing.event("autoscale.scale_down", replica=name,
                               reason=d["reason"])
        except Exception as e:
            # a drain that can't finish leaves the victim DRAINING
            # (fail-closed: it still takes no new work) and reports;
            # the dead-victim case never lands here — drain_done is
            # true for a dead replica and removal succeeds
            self.events.append({
                "t": time.monotonic(), "event": "drain_failed",
                "replica": name, "error": repr(e)})
            print(f"[PT-AS-702] autoscale drain of {name} failed: "
                  f"{e!r}", file=sys.stderr)
        finally:
            with self._mu:
                self._draining_name = None
            self._note_fleet()

    # -- accounting ---------------------------------------------------------

    def _live_count(self) -> int:
        return sum(1 for row in self.router.loads().values()
                   if row["alive"])

    def _note_fleet(self) -> None:
        n = self._live_count()
        with self._mu:
            if not self.timeline or self.timeline[-1][1] != n:
                self.timeline.append((time.monotonic(), n))

    def replica_seconds(self, until: Optional[float] = None) -> float:
        """Integral of the live replica count over time — the
        provisioning cost the bench compares against static-max."""
        with self._mu:
            points = list(self.timeline)
        if not points:
            return 0.0
        t_end = time.monotonic() if until is None else float(until)
        total = 0.0
        for (t0, n0), (t1, _) in zip(points, points[1:]):
            total += n0 * max(0.0, t1 - t0)
        total += points[-1][1] * max(0.0, t_end - points[-1][0])
        return total

    def scale_events(self) -> List[Dict[str, Any]]:
        """The acted scale events (ups + downs) — the no-flap bound
        compares their count against ``policy.max_events``."""
        return [e for e in self.events
                if e["event"] in ("scale_up", "scale_down")]

    # -- lifecycle + observability ------------------------------------------

    def start(self) -> "Scaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="pt-autoscale")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # the control loop must outlive a bad tick (a racing
                # close, a probe blip): record and keep deciding
                self.events.append({
                    "t": time.monotonic(), "event": "tick_failed",
                    "error": repr(e)})

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        for t in self._bg:
            t.join(timeout=10)
        self._bg = []
        self.trace.close()

    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "Scaler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def statusz(self) -> Dict[str, Any]:
        """The /statusz "autoscale" section."""
        with self._mu:
            spawning = self._spawning
            draining = self._draining_name
            ttfr = self.ttfr_s
            events = list(self.events[-20:])
            timeline = list(self.timeline[-50:])
        t0 = timeline[0][0] if timeline else 0.0
        return {
            "policy": self.policy.knobs(),
            "ttfr_s": ttfr,
            "spawning": spawning,
            "draining": draining,
            "spawn_failures": self.spawn_failures,
            "decisions": len(self.decisions),
            "last_decision": (self.decisions[-1]
                              if self.decisions else None),
            "scale_events": len(self.scale_events()),
            "events": events,
            "replica_seconds": round(self.replica_seconds(), 3),
            "timeline": [[round(t - t0, 3), n]
                         for t, n in timeline],
        }

    def attach(self, server) -> None:
        """Register the autoscale /statusz section on a running debug
        server (the router's own, usually)."""
        server.add_status("autoscale", self.statusz)
