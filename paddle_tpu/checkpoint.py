"""Checkpoint / resume — sharded save with resharding-on-restore.

Capability lineage (SURVEY.md §5.4): the reference checkpoints via
save/load ops orchestrated by python io.py (reference: operators/save_op.cc,
python/paddle/fluid/io.py save_persistables:460, load_persistables:693;
dygraph dict save/load in dygraph/checkpoint.py; pserver shard snapshots via
checkpoint_notify_op, operators/distributed_ops/checkpoint_notify_op.cc) and
"No optimizer-state-merging / resharding on load (shape must match)".

This module is the deliberate upgrade the survey calls for: a
tensorstore/orbax-style checkpoint keyed by logical leaf path that

- records each leaf's *sharding spec* alongside its bytes,
- restores onto ANY mesh: the saved spec is re-applied to the restore-time
  mesh when its axes exist, else the leaf is replicated (resharding on
  restore — a saved dp=8 run restores onto a tp=4 mesh),
- writes asynchronously (device→host snapshot happens synchronously so
  training can mutate state immediately; file IO runs on a thread — the
  role of the reference's async checkpoint_notify),
- is atomic (tmp dir + rename) and step-managed with GC
  (``CheckpointManager``, max_to_keep).

Layout: ``<dir>/manifest.json`` + one ``.npy`` per leaf. Multi-host: only
process 0 writes (single-host here; per-host shard writing is a future
optimization, not a correctness requirement — restore re-sharding handles
placement).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core.enforce import enforce
from .core.mesh import get_mesh

_MANIFEST = "manifest.json"

# dtypes numpy's .npy format can't round-trip natively are stored as a
# same-width uint view and restored by name
_EXOTIC = {"bfloat16": np.uint16, "float8_e4m3fn": np.uint8,
           "float8_e5m2": np.uint8}


def _leaf_paths(tree):
    """Flatten to (path-string, leaf) with '/'-joined keys."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if isinstance(p, jax.tree_util.DictKey):
                parts.append(str(p.key))
            elif isinstance(p, jax.tree_util.SequenceKey):
                parts.append(str(p.idx))
            elif isinstance(p, jax.tree_util.GetAttrKey):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        out.append(("/".join(parts) or "_root", leaf))
    return out, treedef


def _skeleton(tree, counter):
    """JSON-serializable nesting with leaf index placeholders (dict / list /
    tuple / None containers — the shapes our states use)."""
    if tree is None:
        return None
    if isinstance(tree, dict):
        # sorted keys: jax flattens dicts in sorted-key order, so skeleton
        # leaf indices must be assigned in the same order
        return {"__kind__": "dict",
                "items": {k: _skeleton(tree[k], counter)
                          for k in sorted(tree)}}
    if isinstance(tree, (list, tuple)):
        return {"__kind__": "list" if isinstance(tree, list) else "tuple",
                "items": [_skeleton(v, counter) for v in tree]}
    idx = counter[0]
    counter[0] += 1
    return {"__kind__": "leaf", "index": idx}


def _unskeleton(skel, leaves):
    if skel is None:
        return None
    kind = skel["__kind__"]
    if kind == "dict":
        return {k: _unskeleton(v, leaves) for k, v in skel["items"].items()}
    if kind == "list":
        return [_unskeleton(v, leaves) for v in skel["items"]]
    if kind == "tuple":
        return tuple(_unskeleton(v, leaves) for v in skel["items"])
    return leaves[skel["index"]]


def _spec_of(leaf) -> Optional[List[Any]]:
    """PartitionSpec of a jax.Array as JSON (list of str / [str...] / None)."""
    sharding = getattr(leaf, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    out = []
    for ax in sharding.spec:
        if ax is None:
            out.append(None)
        elif isinstance(ax, (tuple, list)):
            out.append(list(ax))
        else:
            out.append(str(ax))
    return out


def _spec_from(spec_json, mesh: Mesh) -> Optional[P]:
    """Rebuild a PartitionSpec on `mesh`; None if any axis is missing
    (→ replicate: the resharding-fallback contract)."""
    if spec_json is None:
        return None
    axes = []
    for ax in spec_json:
        if ax is None:
            axes.append(None)
        elif isinstance(ax, list):
            if not all(a in mesh.shape for a in ax):
                return None
            axes.append(tuple(ax))
        else:
            if ax not in mesh.shape:
                return None
            axes.append(ax)
    return P(*axes)


def _sanitize(path: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", path)


class _WriteHandle:
    """Join-able async-write handle that re-raises write failures (a daemon
    thread's exception would otherwise vanish into stderr and a 'successful'
    checkpoint would not exist on disk)."""

    def __init__(self, fn=None, directory: Optional[str] = None):
        self.directory = directory  # write target, for same-dir serializing
        self._exc: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        if fn is not None:
            def run():
                try:
                    fn()
                except BaseException as e:  # re-raised at join()
                    self._exc = e

            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def join(self) -> None:
        if self._thread is not None:
            self._thread.join()
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc


def save_state(directory: str, tree, *, async_save: bool = False):
    """Write a pytree checkpoint. Device→host copy happens before this
    returns (state may be mutated immediately); with ``async_save`` the file
    IO runs on a daemon thread and the returned handle's ``.join()`` waits
    (and re-raises any write failure).

    Supported containers: dict / list / tuple / None. Custom registered
    pytree nodes are rejected (loudly — a silent degrade would desync leaf
    indices); namedtuples round-trip as plain tuples.
    """
    flat, _ = _leaf_paths(tree)
    counter = [0]
    skel = _skeleton(tree, counter)
    enforce(counter[0] == len(flat),
            "tree has custom pytree nodes the checkpoint skeleton can't "
            "represent (%s skeleton leaves vs %s flattened) — use dict/"
            "list/tuple containers", counter[0], len(flat))
    # snapshot to host NOW — training may donate/overwrite these buffers
    host = jax.device_get([leaf for _, leaf in flat])
    entries = []
    seen = set()
    for (path, leaf), arr in zip(flat, host):
        arr = np.asarray(arr)
        fname = _sanitize(path) + ".npy"
        enforce(fname not in seen, "leaf path collision on %s", fname)
        seen.add(fname)
        entries.append({"path": path, "file": fname, "dtype": str(arr.dtype),
                        "shape": list(arr.shape), "spec": _spec_of(leaf)})

    def write():
        tmp = directory + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        for e, arr in zip(entries, host):
            arr = np.asarray(arr)
            view = _EXOTIC.get(e["dtype"])
            np.save(os.path.join(tmp, e["file"]),
                    arr.view(view) if view is not None else arr)
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump({"format": "paddle_tpu_ckpt/v1", "skeleton": skel,
                       "leaves": entries}, f)
        if os.path.exists(directory):
            shutil.rmtree(directory)
        os.replace(tmp, directory)

    if jax.process_index() != 0:  # non-writer hosts only snapshot
        return _WriteHandle(directory=directory)
    if async_save:
        return _WriteHandle(write, directory=directory)
    write()
    return None


def restore_state(directory: str, *, mesh: Optional[Mesh] = None,
                  shardings=None, target=None):
    """Read a checkpoint back, resharding onto ``mesh``.

    - ``shardings``: optional pytree (matching the saved tree) of
      NamedSharding/PartitionSpec overriding the saved specs.
    - otherwise each leaf's *saved* spec is re-applied to ``mesh`` (or the
      current global mesh); leaves whose axes don't exist there are
      replicated — restore works across mesh shapes, the resharding
      upgrade over the reference's shape-must-match load.
    - ``target``: optional pytree; when given, leaf dtypes/shapes are
      validated against it (catching model/checkpoint mismatch early).
    """
    mpath = os.path.join(directory, _MANIFEST)
    enforce(os.path.exists(mpath), "no checkpoint at %s", directory)
    with open(mpath) as f:
        manifest = json.load(f)
    enforce(manifest.get("format") == "paddle_tpu_ckpt/v1",
            "unknown checkpoint format %s", manifest.get("format"))
    override = None
    if shardings is not None:
        oflat, _ = _leaf_paths(shardings)
        override = dict(oflat)

    leaves = []
    for e in manifest["leaves"]:
        arr = np.load(os.path.join(directory, e["file"]))
        view = _EXOTIC.get(e["dtype"])
        if view is not None:
            import ml_dtypes

            arr = arr.view(getattr(ml_dtypes, e["dtype"]))
        sh = None
        if override is not None and e["path"] in override:
            sh = override[e["path"]]
            if isinstance(sh, P):
                sh = NamedSharding(mesh or get_mesh(), sh)
        else:
            try:
                m = mesh or get_mesh()
            except Exception:
                m = None
            if m is not None:
                spec = _spec_from(e["spec"], m)
                if spec is not None:
                    sh = NamedSharding(m, spec)
        x = jnp.asarray(arr) if sh is None else jax.device_put(arr, sh)
        leaves.append(x)

    tree = _unskeleton(manifest["skeleton"], leaves)
    if target is not None:
        tflat, _ = _leaf_paths(target)
        rflat, _ = _leaf_paths(tree)
        tmap = dict(tflat)
        for path, leaf in rflat:
            if path in tmap and hasattr(tmap[path], "shape"):
                enforce(tuple(tmap[path].shape) == tuple(leaf.shape),
                        "checkpoint leaf %s shape %s != target %s", path,
                        tuple(leaf.shape), tuple(tmap[path].shape))
                enforce(jnp.dtype(tmap[path].dtype) == jnp.dtype(leaf.dtype),
                        "checkpoint leaf %s dtype %s != target %s", path,
                        leaf.dtype, tmap[path].dtype)
    return tree


class CheckpointManager:
    """Step-numbered checkpoints with retention GC — the orchestration role
    of the reference's io.py save/load_persistables + checkpoint_notify
    rolled into one object.

    ``save`` snapshots synchronously and writes asynchronously by default;
    ``wait_until_finished`` joins outstanding writes (call before exit).
    """

    _STEP_RE = re.compile(r"^step_(\d+)$")

    def __init__(self, directory: str, max_to_keep: int = 5,
                 async_save: bool = True):
        enforce(max_to_keep >= 1, "max_to_keep must be >= 1, got %s",
                max_to_keep)
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self._pending: List[_WriteHandle] = []
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = self._STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name,
                                                 _MANIFEST)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, tree) -> None:
        # serialize writes targeting the same step dir: a second async save
        # of step N while the first is in flight would collide on the
        # shared .tmp staging path
        target = self._step_dir(step)
        still = []
        for t in self._pending:
            if t.directory == target:
                t.join()
            else:
                still.append(t)
        self._pending = still
        handle = save_state(target, tree, async_save=self.async_save)
        if isinstance(handle, _WriteHandle):
            self._pending.append(handle)
        self._gc()

    def restore(self, step: Optional[int] = None, *, mesh=None,
                shardings=None, target=None):
        self.wait_until_finished()
        if step is None:
            step = self.latest_step()
            enforce(step is not None, "no checkpoints under %s",
                    self.directory)
        return restore_state(self._step_dir(step), mesh=mesh,
                             shardings=shardings, target=target)

    def wait_until_finished(self) -> None:
        """Join outstanding writes, re-raising the first failure, then run
        a final retention pass over the now-complete step dirs."""
        pending, self._pending = self._pending, []
        first_exc = None
        for t in pending:
            try:
                t.join()
            except BaseException as e:
                first_exc = first_exc or e
        self._gc()
        if first_exc is not None:
            raise first_exc

    def _gc(self) -> None:
        # non-blocking: all_steps() only sees fully-written (renamed) dirs,
        # so in-flight saves are invisible here and get pruned by a later
        # pass — save() must never stall on its own write thread. Failed
        # handles stay pending so wait_until_finished() re-raises them.
        self._pending = [t for t in self._pending
                         if not t.done() or t._exc is not None]
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


# --- dygraph-parity convenience (reference: dygraph/checkpoint.py) ---------

def save(state_or_layer, path: str) -> None:
    """``pt.checkpoint.save(model, path)`` or ``save(state_dict, path)`` —
    the reference's save_persistables for a Layer's params+buffers."""
    state = (state_or_layer.state_dict()
             if hasattr(state_or_layer, "state_dict") else state_or_layer)
    save_state(path, state)


def load(path: str, *, mesh=None) -> Dict[str, Any]:
    """Returns the saved state dict (feed to ``Layer.load_state_dict``)."""
    return restore_state(path, mesh=mesh)
