"""Checkpoint / resume — sharded save with resharding-on-restore.

Capability lineage (SURVEY.md §5.4): the reference checkpoints via
save/load ops orchestrated by python io.py (reference: operators/save_op.cc,
python/paddle/fluid/io.py save_persistables:460, load_persistables:693;
dygraph dict save/load in dygraph/checkpoint.py; pserver shard snapshots via
checkpoint_notify_op, operators/distributed_ops/checkpoint_notify_op.cc) and
"No optimizer-state-merging / resharding on load (shape must match)".

This module is the deliberate upgrade the survey calls for: a
tensorstore/orbax-style checkpoint keyed by logical leaf path that

- records each leaf's *sharding spec* alongside its bytes,
- restores onto ANY mesh: the saved spec is re-applied to the restore-time
  mesh when its axes exist, else the leaf is replicated (resharding on
  restore — a saved dp=8 run restores onto a tp=4 mesh),
- writes asynchronously (device→host snapshot happens synchronously so
  training can mutate state immediately; file IO runs on a thread — the
  role of the reference's async checkpoint_notify),
- is atomic (tmp dir + rename) and step-managed with GC
  (``CheckpointManager``, max_to_keep).

Layout: ``<dir>/manifest.json`` + one ``.npy`` per leaf — or, for leaves
that are NOT fully addressable (multi-process sharded arrays), one
``.npy`` PER SHARD REGION: each process snapshots and writes only the
shards it owns (replica 0 of each region), the manifest records
shard→file with start offsets, and restore reassembles on any mesh.
This is the per-host write path the reference gets from each pserver
snapshotting its own shards (reference:
operators/distributed_ops/checkpoint_notify_op.cc) — no single-writer
gather, so checkpoint wall-clock and host RAM stay flat as hosts are
added (assumes the standard shared checkpoint filesystem). Writers
coordinate through the JAX coordination service (barrier), and process 0
performs the atomic rename.
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import telemetry
from .core.enforce import EnforceError, enforce
from .core.mesh import get_mesh
from .resilience import faults as _faults
from .resilience.controller import (_KV_POLICY, BarrierTimeoutError,
                                    ClientTransport,
                                    active as _fleet_active,
                                    note_barrier_timeout)
from .resilience.integrity import (ChecksumError, checksum_bytes,
                                   verify_bytes)
from .resilience.retry import retry_io
from .utils.atomic import atomic_write_bytes, atomic_write_text
from .utils.memory import owned_on_device


@telemetry.cached_instruments
def _ckpt_metrics(reg):
    """Checkpoint instrument set (only reached when telemetry is on)."""
    return {
        "saves": reg.counter("pt_checkpoint_saves_total",
                             "checkpoint writes completed"),
        "save_time": reg.histogram(
            "pt_checkpoint_save_seconds",
            "checkpoint write wall time (staging + rename; measured in "
            "the writer thread for async saves)", unit="s"),
        "bytes": reg.counter(
            "pt_checkpoint_bytes_written_total",
            "payload bytes written by this process", unit="bytes"),
        "restores": reg.counter("pt_checkpoint_restores_total",
                                "checkpoint restores completed"),
        "restore_time": reg.histogram(
            "pt_checkpoint_restore_seconds",
            "checkpoint read+reshard wall time", unit="s"),
        "checksum_failures": reg.counter(
            "pt_checkpoint_checksum_failures_total",
            "checkpoint files whose bytes failed checksum "
            "verification on restore"),
        "restore_fallbacks": reg.counter(
            "pt_checkpoint_restore_fallbacks_total",
            "CheckpointManager.restore fallbacks to an older committed "
            "step after a torn/corrupt newer one"),
        "commit_barrier": reg.histogram(
            "pt_checkpoint_commit_barrier_seconds",
            "step-agreed saves: time from this rank's last shard "
            "staged to the fleet-wide global commit landing", unit="s"),
    }

_MANIFEST = "manifest.json"
# commit marker: written LAST into the staging dir (after every shard
# and the manifest, via the shared atomic helper), so its presence in a
# published step dir certifies completeness — a dir torn by a mid-copy
# kill or a partial rsync lacks it and restore skips that step
_COMMITTED = "COMMITTED"
# fleet-level commit marker (CheckpointManager with a coordinator): the
# durable mirror of the transport's global-commit record — present only
# once EVERY live rank staged this step, so a restarted multi-host fleet
# trusts exactly the steps the whole fleet finished ("all hosts save
# step N or none"). Never written single-process.
_GLOBAL = "GLOBAL_COMMITTED"

# dtypes numpy's .npy format can't round-trip natively are stored as a
# same-width uint view and restored by name
_EXOTIC = {"bfloat16": np.uint16, "float8_e4m3fn": np.uint8,
           "float8_e5m2": np.uint8}


def _leaf_paths(tree):
    """Flatten to (path-string, leaf) with '/'-joined keys."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if isinstance(p, jax.tree_util.DictKey):
                parts.append(str(p.key))
            elif isinstance(p, jax.tree_util.SequenceKey):
                parts.append(str(p.idx))
            elif isinstance(p, jax.tree_util.GetAttrKey):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        out.append(("/".join(parts) or "_root", leaf))
    return out, treedef


def _skeleton(tree, counter):
    """JSON-serializable nesting with leaf index placeholders (dict / list /
    tuple / None containers — the shapes our states use)."""
    if tree is None:
        return None
    if isinstance(tree, dict):
        # sorted keys: jax flattens dicts in sorted-key order, so skeleton
        # leaf indices must be assigned in the same order
        return {"__kind__": "dict",
                "items": {k: _skeleton(tree[k], counter)
                          for k in sorted(tree)}}
    if isinstance(tree, (list, tuple)):
        return {"__kind__": "list" if isinstance(tree, list) else "tuple",
                "items": [_skeleton(v, counter) for v in tree]}
    idx = counter[0]
    counter[0] += 1
    return {"__kind__": "leaf", "index": idx}


def _unskeleton(skel, leaves):
    if skel is None:
        return None
    kind = skel["__kind__"]
    if kind == "dict":
        return {k: _unskeleton(v, leaves) for k, v in skel["items"].items()}
    if kind == "list":
        return [_unskeleton(v, leaves) for v in skel["items"]]
    if kind == "tuple":
        return tuple(_unskeleton(v, leaves) for v in skel["items"])
    return leaves[skel["index"]]


def _spec_of(leaf) -> Optional[List[Any]]:
    """PartitionSpec of a jax.Array as JSON (list of str / [str...] / None)."""
    sharding = getattr(leaf, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    out = []
    for ax in sharding.spec:
        if ax is None:
            out.append(None)
        elif isinstance(ax, (tuple, list)):
            out.append(list(ax))
        else:
            out.append(str(ax))
    return out


def _spec_from(spec_json, mesh: Mesh) -> Optional[P]:
    """Rebuild a PartitionSpec on `mesh`; None if any axis is missing
    (→ replicate: the resharding-fallback contract)."""
    if spec_json is None:
        return None
    axes = []
    for ax in spec_json:
        if ax is None:
            axes.append(None)
        elif isinstance(ax, list):
            if not all(a in mesh.shape for a in ax):
                return None
            axes.append(tuple(ax))
        else:
            if ax not in mesh.shape:
                return None
            axes.append(ax)
    return P(*axes)


def _sanitize(path: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", path)


_barrier_counts: Dict[str, int] = {}
# last coordination-barrier outcome, for the fleet controller's
# /statusz row ("last barrier latency" is the operator's first clue a
# peer is wedging saves) — written by _barrier/_file_barrier only
_BARRIER_STATS: Dict[str, Any] = {"last_latency_s": None,
                                  "last_tag": None, "timeouts": 0}


def barrier_stats() -> Dict[str, Any]:
    """Snapshot of the last coordination-barrier latency/tag and the
    process's barrier-timeout count (mirrors
    ``pt_barrier_timeouts_total``, readable with telemetry off)."""
    return dict(_BARRIER_STATS)


_BARRIER_SUBDIR = ".pt_barrier"
_RUN_START = time.time()  # stale-barrier sweep boundary (this process)
_swept_barrier_roots: Dict[str, float] = {}  # root -> last sweep time
_BARRIER_TIMEOUT_S = 300.0
_SWEEP_INTERVAL_S = 300.0


def _barrier_root(directory: str) -> str:
    """Where the file-barrier fallback keeps its rendezvous files:
    beside the target directory (the shared checkpoint FS)."""
    parent = os.path.dirname(os.path.abspath(directory))
    return os.path.join(parent, _BARRIER_SUBDIR)


_STALE_BARRIER_AGE_S = 60.0


def _sweep_stale_barriers(root: str, now: Optional[float] = None) -> int:
    """GC barrier litter from DEAD runs on first barrier entry: a run
    killed mid-barrier leaves its rendezvous files behind, and because
    every run restarts its per-directory sequence at 1, a stale
    ``<tag>.<rank>`` from the old run would read as "rank already
    arrived" and desync (or deadlock) the next run in the same
    directory. Stale = (older than this process's start AND at least
    ``_STALE_BARRIER_AGE_S`` old — the age floor protects a live
    peer's fresh rendezvous file from a rank whose module import
    happened after the peer already entered the job's first barrier;
    process start times are not ordered across ranks) OR older than
    the barrier timeout + slack (a barrier either completed or timed
    out by then, so its files are provably dead — this arm also
    reclaims THIS run's own accumulation across many saves, since
    manager saves target fresh step dirs and never reach the per-dir
    n-2 lazy cleanup). Re-runs per root every ``_SWEEP_INTERVAL_S``.
    Even a wrong deletion is self-healing: a live polling rank
    re-publishes its file (see ``_file_barrier``). Returns the number
    of files removed."""
    t = time.time() if now is None else now
    last = _swept_barrier_roots.get(root)
    if last is not None and t - last < _SWEEP_INTERVAL_S:
        return 0
    _swept_barrier_roots[root] = t
    cutoff = min(_RUN_START, t - _STALE_BARRIER_AGE_S)
    dead_by_timeout = t - (_BARRIER_TIMEOUT_S * 2 + 60.0)
    removed = 0
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    for name in names:
        path = os.path.join(root, name)
        try:
            mtime = os.path.getmtime(path)
            if mtime < cutoff or mtime < dead_by_timeout:
                os.unlink(path)
                removed += 1
        except OSError:
            pass  # a peer rank swept it first
    return removed


def _file_barrier(directory: str, tag: str, *,
                  rank: Optional[int] = None,
                  world: Optional[int] = None,
                  timeout_s: float = 300.0,
                  poll_s: float = 0.01) -> None:
    """Shared-filesystem barrier fallback (no coordination service):
    every rank publishes ``<root>/<tag>.<rank>`` and polls until all
    ``world`` files exist. Files persist until the NEXT sequence's lazy
    cleanup (`_next_barrier_prefix`) or a later run's stale sweep —
    deleting them inline would race ranks still polling this tag.

    Known limitation (fallback path only — jobs with a coordination
    client never come here): a job crash-restarted within the stale
    sweep's age floor (``_STALE_BARRIER_AGE_S``) of a mid-barrier kill
    can see the dead run's same-tag files as arrivals and release a
    barrier early (sequence numbers restart at 1 per process). Closing
    it needs a run-unique tag component agreed WITHOUT a coordinator —
    tracked under the ROADMAP multi-host coordinated-preemption item."""
    root = _barrier_root(directory)
    _sweep_stale_barriers(root)
    os.makedirs(root, exist_ok=True)
    rank = jax.process_index() if rank is None else rank
    world = jax.process_count() if world is None else world
    mine = os.path.join(root, f"{tag}.{rank}")
    atomic_write_text(mine, "1")
    deadline = time.monotonic() + timeout_s
    while True:
        present = sum(
            os.path.exists(os.path.join(root, f"{tag}.{r}"))
            for r in range(world))
        if present >= world:
            return
        if not os.path.exists(mine):
            # self-heal: a peer whose process started much later may
            # have swept this file as stale (start times are not
            # ordered across ranks) — a live rank simply re-publishes,
            # so a false sweep costs one poll interval, never the
            # barrier
            atomic_write_text(mine, "1")
        if time.monotonic() >= deadline:
            missing = [r for r in range(world) if not os.path.exists(
                os.path.join(root, f"{tag}.{r}"))]
            _BARRIER_STATS["timeouts"] += 1
            note_barrier_timeout()
            raise BarrierTimeoutError(tag, missing=missing,
                                      world=world,
                                      timeout_s=timeout_s)
        time.sleep(poll_s)


def _client_kv_barrier(client, tag: str, *, timeout_s: float,
                       poll_s: float = 0.02) -> None:
    """Coordination-service barrier over the service's KV store instead
    of the opaque ``wait_at_barrier``: each rank publishes an arrival
    key (retried under the bounded transport policy) and polls for its
    peers', so an expiry names exactly the ranks that never arrived —
    the same typed diagnostic the file path gives. A rank the launcher
    marked dead fails the save FAST instead of burning the whole
    timeout: its shards can never arrive, and committing without them
    would publish a torn step, so the save must die loudly, not hang
    and not half-commit."""
    from .resilience.retry import retry_io as _retry

    from .resilience.controller import (ENV_FLEET_DIR, ENV_RUN_ID,
                                        FileTransport)

    rank, world = jax.process_index(), jax.process_count()
    # ClientTransport carries the client-compat shims exactly once
    # (allow_overwrite fallback on put, try_get/blocking-get probe on
    # get) — the barrier is just its KV under a dedicated namespace
    kv = ClientTransport(client, "ckptbar")
    _retry(lambda: kv.put(f"{tag}.{rank}", "1"),
           policy=_KV_POLICY, what="ckpt.barrier")
    # lazy litter reclamation, the file-barrier n-2 proof transplanted:
    # entering sequence n proves every rank passed n-1, hence nobody
    # still polls n-2 — its arrival keys are dead weight on the
    # coordination service (3 x world keys per save, forever). Tags
    # are "ckpt_<crc>_<n>_<phase>"; each rank reclaims its OWN key.
    parts = tag.rsplit("_", 2)
    if len(parts) == 3 and parts[1].isdigit() and int(parts[1]) > 2:
        kv.delete(f"{parts[0]}_{int(parts[1]) - 2}_{parts[2]}.{rank}")

    def _is_dead(r: int) -> bool:
        # the launcher's dead markers: via the active controller when
        # one is running, else straight from the launcher's file root
        # (a job without a FleetController still deserves the fail-
        # fast — otherwise a peer's SIGKILL burns the full barrier
        # timeout before the typed error)
        ctl = _fleet_active()
        if ctl is not None:
            return ctl._marker(f"dead.{r}") is not None
        root = os.environ.get(ENV_FLEET_DIR)
        if not root:
            return False
        run_id = os.environ.get(ENV_RUN_ID) or "r0"
        return FileTransport(root, run_id).get(f"dead.{r}") is not None

    deadline = time.monotonic() + timeout_s
    while True:
        missing = [r for r in range(world)
                   if r != rank and kv.get(f"{tag}.{r}") is None]
        if not missing:
            return
        dead = [r for r in missing if _is_dead(r)]
        if dead or time.monotonic() >= deadline:
            _BARRIER_STATS["timeouts"] += 1
            note_barrier_timeout()
            raise BarrierTimeoutError(
                tag, missing=missing, world=world, timeout_s=timeout_s,
                detail=(f"rank(s) {dead} died mid-save" if dead
                        else None))
        time.sleep(poll_s)


def _barrier(tag: str, directory: str) -> None:
    """Coordination-service barrier (no device collectives — safe from the
    async writer thread); file-barrier fallback when multi-process with
    no coordination client. No-op single-process. A timeout on either
    path raises the typed :class:`resilience.BarrierTimeoutError`
    naming the missing ranks (the client path rendezvouses through the
    coordination-service KV store, so it can tell too — not just the
    file path) and bumps ``pt_barrier_timeouts_total`` — never an
    opaque transport error."""
    if jax.process_count() <= 1:
        return
    from jax._src import distributed as _dist

    client = getattr(_dist.global_state, "client", None)
    t0 = time.monotonic()
    try:
        if client is None:
            # multi-process but no coordination service: rendezvous
            # through the shared checkpoint filesystem instead of
            # silently skipping (a skipped barrier lets rank 0 rename
            # before peers finish writing their shards — a torn
            # checkpoint by construction)
            _file_barrier(directory, tag)
        elif hasattr(client, "key_value_set"):
            _client_kv_barrier(client, tag,
                               timeout_s=_BARRIER_TIMEOUT_S)
        else:
            try:
                client.wait_at_barrier(
                    tag, timeout_in_ms=int(_BARRIER_TIMEOUT_S * 1000))
            except Exception as e:
                msg = str(e).lower()
                if ("deadline" in msg or "timed out" in msg
                        or "timeout" in msg):
                    # this legacy client can't say who is missing, but
                    # the diagnostic still carries tag/world/deadline
                    _BARRIER_STATS["timeouts"] += 1
                    note_barrier_timeout()
                    raise BarrierTimeoutError(
                        tag, world=jax.process_count(),
                        timeout_s=_BARRIER_TIMEOUT_S,
                        detail=str(e)) from e
                raise
    finally:
        _BARRIER_STATS["last_latency_s"] = round(
            time.monotonic() - t0, 4)
        _BARRIER_STATS["last_tag"] = tag


def _next_barrier_prefix(directory: str) -> str:
    # tags are keyed by TARGET DIRECTORY (+ a per-directory sequence), not
    # a process-global counter: if one rank skips a save (e.g. its
    # previous write failed and raised), its barriers for OTHER
    # directories still line up with the peers' — a mismatch fails one
    # save loudly instead of desyncing every save that follows
    import zlib

    n = _barrier_counts.get(directory, 0) + 1
    _barrier_counts[directory] = n
    crc = zlib.crc32(directory.encode()) & 0xffffffff
    if n > 2:
        # lazy file-barrier litter GC: entering sequence n proves every
        # rank passed sequence n-1, which proves every rank long
        # finished polling sequence n-2 — its files are dead weight
        root = _barrier_root(directory)
        try:
            stale = f"ckpt_{crc:08x}_{n - 2}_"
            for name in os.listdir(root):
                if name.startswith(stale):
                    try:
                        os.unlink(os.path.join(root, name))
                    except OSError:
                        pass
        except OSError:
            pass
    return f"ckpt_{crc:08x}_{n}"


def _shard_regions(leaf):
    """Deterministic global enumeration of a sharded leaf's unique shard
    regions: [(region_key, start offsets, region shape)] — identical on
    every process (sharding metadata is global)."""
    imap = leaf.sharding.devices_indices_map(leaf.shape)
    regions = {}
    for idx in imap.values():
        starts = tuple((s.start or 0) for s in idx)
        if starts not in regions:
            shape = tuple(
                ((s.stop if s.stop is not None else dim) - (s.start or 0))
                for s, dim in zip(idx, leaf.shape))
            regions[starts] = shape
    return [("_".join(map(str, k)), list(k), list(v))
            for k, v in sorted(regions.items())]


def _owned_host(a) -> np.ndarray:
    """Owned host copy of a device->host snapshot. On the cpu backend
    ``device_get`` / ``shard.data`` views are ZERO-COPY aliases of the
    live device buffers; the overlapped training step the caller resumes
    may DONATE those buffers before the (possibly async) file write
    reads them — a garbage read or SIGSEGV. Copy leaf-by-leaf at
    snapshot time; results that already own their bytes (every non-cpu
    backend's D2H copy) pass through untouched."""
    a = np.asarray(a)
    return a if a.base is None else np.array(a)


def _local_shard_payload(leaf):
    """Snapshot THIS process's owned shards (replica 0 of each region —
    exactly one device globally owns each region's replica 0, so every
    region is written exactly once across the job)."""
    out = []
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue
        starts = tuple((s.start or 0) for s in shard.index)
        out.append(("_".join(map(str, starts)), _owned_host(shard.data)))
    return out


def _npy_bytes(arr: np.ndarray):
    """Serialize to .npy format in memory — one pass yields both the
    exact file bytes to checksum and the payload for the atomic write
    (no read-back verification I/O). Returns a zero-copy READ-ONLY
    memoryview (``getvalue()`` would add a second full copy of the
    leaf; native crc32c rejects writable buffers; the view keeps its
    BytesIO exporter alive)."""
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getbuffer().toreadonly()


def _write_resilient(path: str, data: bytes, point: str, inj) -> None:
    """Atomic file write under the transient-I/O retry policy, with the
    fault-injection points threaded through: ``io.slow`` may delay each
    attempt, ``point`` may raise (a retried OSError models a transient
    fault; an exhausted budget tears the save) or corrupt the bytes."""
    def attempt():
        d = data
        if inj is not None:
            inj.fire("io.slow", path=path)
            d = inj.fire(point, data=d, path=path)
        atomic_write_bytes(path, d)

    retry_io(attempt, what=point)


def _read_resilient(path: str, inj) -> bytes:
    """Whole-file read under the retry policy + injection points. The
    read bytes pass THROUGH the ``restore.read`` fire so a ``corrupt``
    rule really hands corrupted bytes to the verifier (not a silently
    discarded flag); raising rules raise either way."""
    def attempt():
        if inj is not None:
            inj.fire("io.slow", path=path)
        with open(path, "rb") as f:
            raw = f.read()
        if inj is not None:
            raw = inj.fire("restore.read", data=raw, path=path)
        return raw

    return retry_io(attempt, what="restore.read")


def _note_checksum_failure() -> None:
    if telemetry.enabled():
        _ckpt_metrics()["checksum_failures"].inc()


class _WriteHandle:
    """Join-able async-write handle that re-raises write failures (a daemon
    thread's exception would otherwise vanish into stderr and a 'successful'
    checkpoint would not exist on disk)."""

    # a wedged writer (hung filesystem, dead NFS mount) must never hang
    # close()/wait_until_finished() forever — join() is bounded and
    # raises typed on expiry. Generous by design: the commit barrier's
    # own 300s timeout fires long before this on the coordinated path.
    DEFAULT_JOIN_TIMEOUT_S = 600.0

    def __init__(self, fn=None, directory: Optional[str] = None):
        self.directory = directory  # write target, for same-dir serializing
        self._exc: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        if fn is not None:
            def run():
                try:
                    fn()
                except BaseException as e:  # re-raised at join()
                    # pt-lint: disable=PT-RACE-401 join() reads _exc only after Thread.join returns (the happens-before edge)
                    self._exc = e

            self._thread = threading.Thread(target=run, daemon=True,
                                            name="pt-ckpt-async-writer")
            self._thread.start()

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            # env read at CALL time, so the error hint's "override and
            # retry" works inside a live process
            t = (timeout if timeout is not None
                 else float(os.environ.get("PT_CKPT_JOIN_TIMEOUT_S",
                                           self.DEFAULT_JOIN_TIMEOUT_S)))
            self._thread.join(t)
            if self._thread.is_alive():
                raise EnforceError(
                    f"checkpoint writer thread still running after "
                    f"{t:.0f}s (target {self.directory or '?'}): "
                    f"wedged IO — refusing to hang teardown "
                    f"(PT_CKPT_JOIN_TIMEOUT_S overrides)")
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc


def save_state(directory: str, tree, *, async_save: bool = False,
               per_host: Optional[bool] = None):
    """Write a pytree checkpoint. Device→host copy happens before this
    returns (state may be mutated immediately); with ``async_save`` the file
    IO runs on a daemon thread and the returned handle's ``.join()`` waits
    (and re-raises any write failure).

    ``per_host``: leaves written shard-by-shard (each process writes only
    the shard regions it owns). Defaults to automatic — any leaf that is
    not fully addressable (multi-process sharded) MUST go per-host; pass
    ``True`` to force it for addressable sharded leaves too.

    Supported containers: dict / list / tuple / None. Custom registered
    pytree nodes are rejected (loudly — a silent degrade would desync leaf
    indices); namedtuples round-trip as plain tuples.

    Integrity (resilience plane): every file's bytes are checksummed
    into the manifest (non-rank-0 shards into per-rank sidecars), a
    ``COMMITTED`` marker carrying the manifest checksum is written last
    in the staging dir, and only then does the atomic rename publish
    the step. Transient I/O errors retry with capped backoff
    (``resilience.retry``); an armed ``FaultInjector`` is honored at
    ``ckpt.write`` / ``ckpt.manifest`` / ``io.slow``.
    """
    flat, _ = _leaf_paths(tree)
    counter = [0]
    skel = _skeleton(tree, counter)
    enforce(counter[0] == len(flat),
            "tree has custom pytree nodes the checkpoint skeleton can't "
            "represent (%s skeleton leaves vs %s flattened) — use dict/"
            "list/tuple containers", counter[0], len(flat))

    def sharded_mode(leaf) -> bool:
        if not isinstance(leaf, jax.Array) or leaf.is_fully_replicated:
            return False
        if not getattr(leaf, "is_fully_addressable", True):
            return True
        return bool(per_host) and isinstance(leaf.sharding, NamedSharding)

    # snapshot to host NOW — training may donate/overwrite these buffers.
    # Whole-leaf snapshots only for process-0-writable leaves (ONE batched
    # device_get so D2H transfers overlap); sharded leaves snapshot their
    # LOCAL owned shards on every process. Every snapshot is copied to an
    # OWNED host array leaf-by-leaf (sync and async paths alike): cpu-
    # backend device_get returns zero-copy views of the live buffers, and
    # the next overlapped step donating them under a view would read as
    # garbage (or SIGSEGV) at file-write time.
    entries, payload, seen = [], [], set()
    rank0 = jax.process_index() == 0
    whole = [(path, leaf) for path, leaf in flat
             if not sharded_mode(leaf)]
    whole_host = {
        p: _owned_host(v) for p, v in zip(
            [p for p, _ in whole],
            jax.device_get([leaf for _, leaf in whole]))}
    for path, leaf in flat:
        base = _sanitize(path)
        enforce(base not in seen, "leaf path collision on %s", base)
        seen.add(base)
        if path not in whole_host:
            regions = [
                {"file": f"{base}.shard_{key}.npy", "start": starts,
                 "shape": shape}
                for key, starts, shape in _shard_regions(leaf)]
            entries.append({
                "path": path, "dtype": str(np.dtype(leaf.dtype)),
                "shape": list(leaf.shape), "spec": _spec_of(leaf),
                "shards": regions})
            for key, arr in _local_shard_payload(leaf):
                payload.append((f"{base}.shard_{key}.npy", arr))
        else:
            arr = whole_host[path]
            entries.append({"path": path, "file": base + ".npy",
                            "dtype": str(arr.dtype),
                            "shape": list(arr.shape),
                            "spec": _spec_of(leaf)})
            if rank0:
                payload.append((base + ".npy", arr))

    bprefix = _next_barrier_prefix(directory)
    multi = jax.process_count() > 1

    def write():
        telem = telemetry.enabled()
        if telem:
            t0 = time.perf_counter()
        # one injector/policy resolve per write — never per file (the
        # zero-cost-when-disabled contract: unarmed runs pay a single
        # None-check here and nothing below)
        inj = _faults.active()
        tmp = directory + ".tmp"
        if rank0:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
        if multi:
            _barrier(f"{bprefix}_staged", directory)  # tmp dir exists
        checksums: Dict[str, str] = {}
        for fname, arr in payload:
            dt = str(arr.dtype)
            view = _EXOTIC.get(dt)
            data = _npy_bytes(arr.view(view) if view is not None
                              else arr)
            # checksum the TRUE bytes before the injector touches them:
            # an injected corruption models the storage tearing the
            # write, which restore-time verification must then catch
            checksums[fname] = checksum_bytes(data)
            _write_resilient(os.path.join(tmp, fname), data,
                             "ckpt.write", inj)
        if rank0:
            text = json.dumps({"format": "paddle_tpu_ckpt/v1",
                               "skeleton": skel, "leaves": entries,
                               "checksums": checksums})
            _write_resilient(os.path.join(tmp, _MANIFEST),
                             text.encode(), "ckpt.manifest", inj)
        elif checksums:
            # non-rank-0 shards: rank 0 can't know these checksums
            # without a gather, so each rank publishes a sidecar the
            # restore path merges with the manifest's own map
            _write_resilient(
                os.path.join(tmp,
                             f"checksums.{jax.process_index()}.json"),
                json.dumps(checksums).encode(), "ckpt.write", inj)
        if multi:
            _barrier(f"{bprefix}_written", directory)  # all on disk
        if rank0:
            # COMMITTED last, still inside the staging dir: its
            # presence certifies every byte above it (including the
            # manifest, whose checksum it carries) landed first. The
            # atomic rename then publishes marker and payload together.
            retry_io(lambda: atomic_write_text(
                os.path.join(tmp, _COMMITTED),
                json.dumps({"format": "paddle_tpu_ckpt/v1",
                            "manifest_checksum": checksum_bytes(
                                text.encode()),
                            "process_count": jax.process_count()})),
                what="ckpt.commit")
            enforce(not os.path.exists(directory)
                    or os.path.isdir(directory),
                    "checkpoint target %s exists and is not a "
                    "directory", directory)

            def publish():
                # re-entrant on retry: each attempt re-reads the disk
                # state, so a transient failure after the rename (old
                # dir already moved to .old) lands in the else branch
                if os.path.isdir(directory):
                    # never rmtree the live checkpoint before the
                    # rename: a kill in that window would destroy the
                    # old data with the new not yet visible. Swap via a
                    # trash name — a kill mid-swap leaves the old bytes
                    # recoverable under .old (GC restores them) and the
                    # step simply absent (restore falls back).
                    trash = directory + ".old"
                    if os.path.exists(trash):
                        shutil.rmtree(trash)
                    os.rename(directory, trash)
                    os.replace(tmp, directory)
                    shutil.rmtree(trash, ignore_errors=True)
                else:
                    os.replace(tmp, directory)

            retry_io(publish, what="ckpt.publish")
        if multi:
            _barrier(f"{bprefix}_renamed", directory)  # visible to all
        if telem:
            m = _ckpt_metrics()
            m["saves"].inc()
            m["save_time"].observe(time.perf_counter() - t0)
            m["bytes"].inc(sum(a.nbytes for _, a in payload))

    if async_save:
        # payload already holds OWNED host copies (_owned_host at
        # snapshot time, shared with the sync path) — the writer thread
        # can never read a buffer the overlapped step donated
        return _WriteHandle(write, directory=directory)
    write()
    return None


def restore_state(directory: str, *, mesh: Optional[Mesh] = None,
                  shardings=None, target=None, verify: bool = True):
    """Read a checkpoint back, resharding onto ``mesh``.

    - ``shardings``: optional pytree (matching the saved tree) of
      NamedSharding/PartitionSpec overriding the saved specs.
    - otherwise each leaf's *saved* spec is re-applied to ``mesh`` (or the
      current global mesh); leaves whose axes don't exist there are
      replicated — restore works across mesh shapes, the resharding
      upgrade over the reference's shape-must-match load.
    - ``target``: optional pytree; when given, leaf dtypes/shapes are
      validated against it (catching model/checkpoint mismatch early).
    - ``verify``: check every read file against the checksums the save
      recorded (manifest + per-rank sidecars) and the manifest itself
      against the ``COMMITTED`` marker's checksum — a torn or
      bit-flipped file raises :class:`resilience.ChecksumError` instead
      of restoring corrupt weights. Pre-integrity checkpoints carry no
      checksums and restore unverified. File reads are retried under
      the transient-I/O policy (``pt_retry_total``).
    """
    telem = telemetry.enabled()
    if telem:
        t_restore0 = time.perf_counter()
    inj = _faults.active()
    mpath = os.path.join(directory, _MANIFEST)
    enforce(os.path.exists(mpath), "no checkpoint at %s", directory)
    raw_manifest = _read_resilient(mpath, inj)
    cpath = os.path.join(directory, _COMMITTED)
    if verify and os.path.exists(cpath):
        try:
            marker = json.loads(_read_resilient(cpath, inj))
        except ValueError as e:
            _note_checksum_failure()
            raise ChecksumError(f"{cpath}: torn COMMITTED marker "
                                f"({e})") from e
        tag = marker.get("manifest_checksum")
        if tag:
            try:
                verify_bytes(raw_manifest, tag, name=mpath)
            except ChecksumError:
                _note_checksum_failure()
                raise
    try:
        manifest = json.loads(raw_manifest)
    except ValueError as e:
        # a torn manifest with no marker to catch it first
        _note_checksum_failure()
        raise ChecksumError(f"{mpath}: unparseable manifest "
                            f"({e})") from e
    enforce(manifest.get("format") == "paddle_tpu_ckpt/v1",
            "unknown checkpoint format %s", manifest.get("format"))
    checksums: Dict[str, str] = dict(manifest.get("checksums") or {})
    if verify:
        # per-rank sidecars: shard checksums from writers other than
        # the manifest's author
        try:
            names = os.listdir(directory)
        except OSError:
            names = []
        for name in sorted(names):
            if name.startswith("checksums.") and name.endswith(".json"):
                try:
                    checksums.update(json.loads(_read_resilient(
                        os.path.join(directory, name), inj)))
                except ValueError as e:
                    _note_checksum_failure()
                    raise ChecksumError(
                        f"{name}: torn checksum sidecar ({e})") from e
    override = None
    if shardings is not None:
        oflat, _ = _leaf_paths(shardings)
        override = dict(oflat)

    def _load_file(path_, dtype):
        raw = _read_resilient(path_, inj)
        tag = checksums.get(os.path.basename(path_))
        if verify and tag is not None:
            try:
                verify_bytes(raw, tag, name=path_)
            except ChecksumError:
                _note_checksum_failure()
                raise
        try:
            arr = np.load(io.BytesIO(raw))
        except ValueError as e:
            _note_checksum_failure()
            raise ChecksumError(f"{path_}: unreadable npy payload "
                                f"({e})") from e
        if _EXOTIC.get(dtype) is not None:
            import ml_dtypes

            arr = arr.view(getattr(ml_dtypes, dtype))
        return arr

    def _np_dtype(dtype):
        if _EXOTIC.get(dtype):
            import ml_dtypes

            return getattr(ml_dtypes, dtype)
        return np.dtype(dtype)

    def _assemble(e, region):
        """Copy the window ``region`` (tuple of slices with concrete
        bounds) out of the shard files, reading ONLY overlapping files —
        per-host restore IO stays O(local shards), not O(global)."""
        out = np.empty(tuple(s.stop - s.start for s in region),
                       _np_dtype(e["dtype"]))
        for rec in e["shards"]:
            src, dst = [], []
            for s, (r0, rn) in zip(region,
                                   zip(rec["start"], rec["shape"])):
                lo, hi = max(s.start, r0), min(s.stop, r0 + rn)
                if lo >= hi:
                    break
                src.append(slice(lo - r0, hi - r0))
                dst.append(slice(lo - s.start, hi - s.start))
            else:
                shard = _load_file(os.path.join(directory, rec["file"]),
                                   e["dtype"])
                out[tuple(dst)] = shard[tuple(src)]
        return out

    leaves = []
    for e in manifest["leaves"]:
        arr = None
        if "shards" not in e:
            arr = _load_file(os.path.join(directory, e["file"]),
                             e["dtype"])
        sh = None
        if override is not None and e["path"] in override:
            sh = override[e["path"]]
            if isinstance(sh, P):
                sh = NamedSharding(mesh or get_mesh(), sh)
        else:
            try:
                m = mesh or get_mesh()
            except Exception:
                m = None
            if m is not None:
                spec = _spec_from(e["spec"], m)
                if spec is not None:
                    sh = NamedSharding(m, spec)
        shape = tuple(e["shape"]) if arr is None else tuple(arr.shape)

        def _window(idx, dims):
            return tuple(
                slice(s.start or 0, s.stop if s.stop is not None else dim)
                for s, dim in zip(idx, dims))

        if sh is None:
            if arr is None:  # host value: assemble the full array
                arr = _assemble(e, tuple(slice(0, d) for d in shape))
            x = jnp.asarray(arr)
        elif arr is None:
            # per-host restore: each process reads only the shard files
            # overlapping its addressable windows
            x = jax.make_array_from_callback(
                shape, sh,
                lambda idx, _e=e, _d=shape: _assemble(_e, _window(idx, _d)))
        else:
            # make_array_from_callback works when the sharding spans
            # processes (device_put to non-addressable devices does not)
            x = jax.make_array_from_callback(
                shape, sh, lambda idx, _a=arr: _a[idx])
        # the CPU backend can zero-copy these host temporaries into the
        # device buffers; a consumer that DONATES a restored leaf (every
        # Trainer step) would then hand numpy-owned memory to the
        # runtime — the flaky restore-then-train SIGSEGV. One on-device
        # copy re-homes the bytes into runtime-owned buffers.
        leaves.append(owned_on_device(x))

    tree = _unskeleton(manifest["skeleton"], leaves)
    if target is not None:
        tflat, _ = _leaf_paths(target)
        rflat, _ = _leaf_paths(tree)
        tmap = dict(tflat)
        for path, leaf in rflat:
            if path in tmap and hasattr(tmap[path], "shape"):
                enforce(tuple(tmap[path].shape) == tuple(leaf.shape),
                        "checkpoint leaf %s shape %s != target %s", path,
                        tuple(leaf.shape), tuple(tmap[path].shape))
                enforce(jnp.dtype(tmap[path].dtype) == jnp.dtype(leaf.dtype),
                        "checkpoint leaf %s dtype %s != target %s", path,
                        leaf.dtype, tmap[path].dtype)
    if telem:
        m = _ckpt_metrics()
        m["restores"].inc()
        m["restore_time"].observe(time.perf_counter() - t_restore0)
    return tree


class CheckpointManager:
    """Step-numbered checkpoints with retention GC — the orchestration role
    of the reference's io.py save/load_persistables + checkpoint_notify
    rolled into one object.

    ``save`` snapshots synchronously and writes asynchronously by default;
    ``wait_until_finished`` joins outstanding writes (call before exit).

    ``coordinator`` (a :class:`resilience.FleetController`, normally
    wired by ``TrainLoop.run(controller=...)``) upgrades every periodic
    save to a FLEET-LEVEL TRANSACTION — two-phase step-agreed commit
    ("all hosts save step N or none"): the local write is only the
    STAGE phase, the rank publishes ``staged.<rank>`` through the
    coordination transport, and the step becomes restore-trustworthy
    for the fleet only when every live rank staged it and the single
    global commit marker lands (mirrored durably as a per-step
    ``GLOBAL_COMMITTED`` file — the transport dies with the job; the
    disk record is what a restarted fleet trusts). Restore and GC then
    consult only globally-committed steps, so a rank can never prune
    the last step a peer is still staging (the multi-host
    ``max_to_keep=1`` hazard). With no coordinator — or world 1 — every
    path is byte-for-byte the single-process manager: zero transport
    IO, no extra markers (test-pinned).
    """

    _STEP_RE = re.compile(r"^step_(\d+)$")
    # aot compiled-program artifacts (paddle_tpu.aot) live NEXT TO
    # their producing step dir and ride its retention: GC prunes
    # aot_step_N exactly when step_N falls out of retention, so a
    # serving boot can never resolve an artifact whose weights-step
    # was already deleted (aot.latest_artifact additionally refuses
    # artifacts whose companion step_N lost its COMMITTED marker)
    _AOT_RE = re.compile(r"^aot_step_(\d+)$")

    def __init__(self, directory: str, max_to_keep: int = 5,
                 async_save: bool = True, coordinator=None):
        enforce(max_to_keep >= 1, "max_to_keep must be >= 1, got %s",
                max_to_keep)
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.coordinator = coordinator
        self._pending: List[_WriteHandle] = []
        self.last_restored_step: Optional[int] = None
        self.last_commit_barrier_s: Optional[float] = None
        os.makedirs(directory, exist_ok=True)

    def _coord(self):
        """The attached coordinator when it can actually coordinate
        (multi-rank with a live transport); None selects the unchanged
        single-process paths everywhere below."""
        c = self.coordinator
        if c is None or getattr(c, "world", 1) <= 1 or \
                getattr(c, "transport", None) is None:
            return None
        return c

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def all_steps(self) -> List[int]:
        """Steps with a manifest on disk (committed or not — see
        :meth:`committed_steps` for the restore-trustworthy subset)."""
        steps = []
        for name in os.listdir(self.directory):
            m = self._STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name,
                                                 _MANIFEST)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def _is_committed(self, name: str) -> bool:
        d = os.path.join(self.directory, name)
        mpath = os.path.join(d, _MANIFEST)
        if not os.path.exists(mpath):
            return False
        if os.path.exists(os.path.join(d, _COMMITTED)):
            return True
        # no marker: legacy pre-integrity checkpoints (no checksums in
        # the manifest) predate the marker and are trusted; a
        # checksummed manifest WITHOUT its marker is a torn copy of a
        # new-format checkpoint — never trust it
        try:
            with open(mpath) as f:
                return "checksums" not in json.load(f)
        except (OSError, ValueError):
            return False

    def committed_steps(self) -> List[int]:
        """Steps whose save provably completed (``COMMITTED`` marker,
        or legacy format with no integrity metadata)."""
        steps = []
        for name in os.listdir(self.directory):
            m = self._STEP_RE.match(name)
            if m and self._is_committed(name):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def globally_committed_steps(self) -> List[int]:
        """Steps the WHOLE fleet finished saving (locally committed AND
        carrying the durable ``GLOBAL_COMMITTED`` mirror). Fleet-mode
        restore and GC consult only these; single-process managers
        never write the marker."""
        return [s for s in self.committed_steps()
                if os.path.exists(os.path.join(self._step_dir(s),
                                               _GLOBAL))]

    def promote_global(self, step: int) -> None:
        """Durably mark ``step`` globally committed. Restore-time
        promotion: the fleet just AGREED every live rank holds this
        step, which is exactly the all-ranks-staged evidence the
        save-time marker records — a crash between everyone staging
        and the marker landing must not demote the step forever."""
        d = self._step_dir(step)
        if os.path.isdir(d) and not os.path.exists(
                os.path.join(d, _GLOBAL)):
            retry_io(lambda: atomic_write_text(
                os.path.join(d, _GLOBAL),
                json.dumps({"step": int(step), "promoted": True})),
                what="ckpt.commit")

    def align_global(self, agreed: Optional[int]) -> None:
        """Reconcile this rank's durable global markers with the
        fleet's restore agreement: promote ``agreed`` (the fleet
        provably holds it) and DEMOTE every marker ABOVE it — or all
        of them when the agreement cold-starts. A stale marker from a
        dead attempt (e.g. a survivor's post-agreement commit a
        replacement rank never saw) would otherwise poison the fleet
        GC floor: ``_gc_fleet`` computes its newest-global floor from
        disk, so a stale step_100 marker makes it prune THIS run's
        fresh commits as "strictly older" — the exact data-loss class
        this layer exists to close — and ``restore(None)`` rollbacks
        would diverge ranks onto steps the fleet doesn't share.
        Demoted steps keep their local data (stage-only); they just
        stop being fleet-trusted."""
        for s in self.globally_committed_steps():
            if agreed is None or s > agreed:
                try:
                    os.unlink(os.path.join(self._step_dir(s), _GLOBAL))
                except OSError:
                    pass
        if agreed is not None:
            self.promote_global(agreed)

    def latest_step(self) -> Optional[int]:
        """Newest COMMITTED step — the only kind worth resuming from
        (a torn newer dir must not shadow restorable progress). Fleet
        mode narrows that to globally-committed: a step a peer never
        finished staging is not restorable progress for the FLEET."""
        coord = self._coord()
        steps = (self.globally_committed_steps() if coord is not None
                 else self.committed_steps())
        return steps[-1] if steps else None

    def save(self, step: int, tree, *, coordinate: bool = True) -> None:
        # serialize writes targeting the same step dir: a second async save
        # of step N while the first is in flight would collide on the
        # shared .tmp staging path.
        # ``coordinate=False`` stages locally WITHOUT the fleet
        # transaction — the clean-completion epilogue uses it (ranks
        # can complete at different final steps; a global commit there
        # would hold each rank for a step its peers never save). The
        # restore-time agreement reconciles such stage-only steps: if
        # every rank holds one, it is restored and promoted.
        target = self._step_dir(step)
        still = []
        for t in self._pending:
            if t.directory == target:
                t.join()
            else:
                still.append(t)
        self._pending = still
        coord = self._coord() if coordinate else None
        if coord is None:
            handle = save_state(target, tree,
                                async_save=self.async_save)
            if isinstance(handle, _WriteHandle):
                self._pending.append(handle)
            self._gc()
            return
        # fleet mode: stage locally, then run the two-phase global
        # commit. For async saves the device→host snapshot STILL
        # happens synchronously inside this call (save_state's
        # donation-safety contract — the next overlapped step may
        # donate the live buffers); only the file IO and the commit
        # barrier ride writer threads, so training never blocks on a
        # peer's staging. A commit that expires surfaces the typed
        # BarrierTimeoutError at the next join (wait_until_finished /
        # close).
        if self.async_save:
            inner = save_state(target, tree, async_save=True)

            def commit_after():
                inner.join()  # stage on disk (re-raises IO failures)
                self._global_commit(step, coord)

            self._pending.append(_WriteHandle(commit_after,
                                              directory=target))
        else:
            save_state(target, tree, async_save=False)
            self._global_commit(step, coord)
        self._gc()

    def _global_commit(self, step: int, coord) -> None:
        """Phases of the fleet transaction, after the local stage:
        publish ``staged.<rank>``, hold for every live rank's, land the
        global marker on the transport, then mirror it durably into the
        step dir. The ``ckpt.stage`` / ``ckpt.commit`` injection points
        bracket the two phases (delay rules widen the SIGKILL windows
        the chaos e2es aim at; raising rules model transport faults —
        the save tears, the step stays uncommitted for the fleet)."""
        inj = _faults.active()
        if inj is not None:
            inj.fire("ckpt.stage", path=self._step_dir(step))
        t0 = time.perf_counter()
        coord.note_stage(step)
        if coord.wait_global_commit(step) is None:
            # deferred to an in-flight preempt agreement (see
            # controller.wait_global_commit): the step stays staged-
            # but-uncommitted so the train loop can publish its ack
            return
        if inj is not None:
            inj.fire("ckpt.commit", path=self._step_dir(step))
        retry_io(lambda: atomic_write_text(
            os.path.join(self._step_dir(step), _GLOBAL),
            json.dumps({"step": int(step), "world": coord.world,
                        "run_id": coord.run_id})),
            what="ckpt.commit")
        self.last_commit_barrier_s = time.perf_counter() - t0
        if telemetry.enabled():
            _ckpt_metrics()["commit_barrier"].observe(
                self.last_commit_barrier_s)

    # errors that mean "this step's bytes are bad", where trying the
    # previous committed step is the right move. Config/shape errors
    # (EnforceError) would fail identically on every step and propagate.
    _FALLBACK_ERRORS = (ChecksumError, OSError, ValueError, KeyError)

    def restore(self, step: Optional[int] = None, *, mesh=None,
                shardings=None, target=None):
        """Restore ``step`` (explicit: exactly that step, integrity
        errors propagate) or, with ``step=None``, the newest committed
        checksum-valid step: a torn/corrupt newer step logs a warning,
        bumps ``pt_checkpoint_restore_fallbacks_total``, and restore
        falls back to the next older committed step — the kill-safety
        contract (never a torn restore, never data loss past the last
        commit). Fleet mode (``coordinator=``) scans only GLOBALLY
        committed steps: a step one rank holds but a peer never
        finished staging would restore the fleet into divergence.
        ``last_restored_step`` records what was restored."""
        self.wait_until_finished()
        if step is not None:
            tree = restore_state(self._step_dir(step), mesh=mesh,
                                 shardings=shardings, target=target)
            self.last_restored_step = step
            return tree
        steps = (self.globally_committed_steps()
                 if self._coord() is not None
                 else self.committed_steps())
        enforce(steps, "no checkpoints under %s", self.directory)
        last_exc: Optional[BaseException] = None
        for s in reversed(steps):
            try:
                tree = restore_state(self._step_dir(s), mesh=mesh,
                                     shardings=shardings, target=target)
                self.last_restored_step = s
                return tree
            except EnforceError:
                raise
            except self._FALLBACK_ERRORS as e:
                last_exc = e
                if telemetry.enabled():
                    _ckpt_metrics()["restore_fallbacks"].inc()
                print(f"[checkpoint] step {s} failed restore "
                      f"({type(e).__name__}: {e}); falling back to the "
                      f"previous committed step", file=sys.stderr)
        raise last_exc  # every committed step failed integrity

    def wait_until_finished(self) -> None:
        """Join outstanding writes, re-raising the first failure, then run
        a final retention pass over the now-complete step dirs."""
        pending, self._pending = self._pending, []
        first_exc = None
        for t in pending:
            try:
                t.join()
            except BaseException as e:
                first_exc = first_exc or e
        self._gc()
        if first_exc is not None:
            raise first_exc

    def _gc(self) -> None:
        # non-blocking: committed_steps() only sees fully-written
        # (renamed + COMMITTED) dirs, so in-flight saves are invisible
        # here and get pruned by a later pass — save() must never stall
        # on its own write thread. Failed handles stay pending so
        # wait_until_finished() re-raises them.
        self._pending = [t for t in self._pending
                         if not t.done() or t._exc is not None]
        if self._coord() is not None:
            self._gc_fleet()
            return
        # GC only PAST COMMITTED steps: retention counts committed
        # checkpoints, so the newest committed one survives even when
        # max_to_keep is "exceeded" by a newer save that is still
        # uncommitted/in-flight — deleting it then would leave zero
        # restorable state if that newer save tears
        steps = self.committed_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
            # the step's compiled-program artifact rides the same
            # retention — weights gone means nothing serves from it
            shutil.rmtree(os.path.join(self.directory, f"aot_step_{s}"),
                          ignore_errors=True)
        # crash litter: torn step dirs (uncommitted, no in-flight
        # writer, older than the newest committed step — provably a
        # dead save) and step_N.old trash from a kill mid-rename-swap
        # would otherwise accumulate forever across preempt/resume
        # cycles on the same directory. Litter AT OR ABOVE the newest
        # committed step is deliberately kept: the pending-handle set
        # only covers THIS process's writers, and a peer rank's
        # in-flight save always targets a step >= newest — deleting
        # there would race it (one leaked tmp dir is the cheaper
        # failure)
        newest = steps[-1] if steps else None
        pending = {t.directory for t in self._pending
                   if t.directory is not None}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            full = os.path.join(self.directory, name)
            if name.endswith(".old") and \
                    self._STEP_RE.match(name[:-len(".old")]):
                base = os.path.join(self.directory,
                                    name[:-len(".old")])
                if os.path.exists(base):
                    # swap completed (or a later save landed): the
                    # trash copy is superseded
                    shutil.rmtree(full, ignore_errors=True)
                elif os.path.exists(os.path.join(full, _MANIFEST)):
                    # kill mid-rename-swap: the .old copy IS the only
                    # surviving data for this step — honor save_state's
                    # "recoverable under .old" promise and put it back
                    try:
                        os.rename(full, base)
                    except OSError:
                        pass
                else:
                    shutil.rmtree(full, ignore_errors=True)
                continue
            base = name
            for suf in (".tmp", ".old"):
                if name.endswith(suf):
                    base = name[:-len(suf)]
                    break
            ma = self._AOT_RE.match(base)
            if ma:
                # stale artifact: its producing step_N fell out of
                # retention (or never committed) and newer committed
                # state exists — nothing may serve from it
                s = int(ma.group(1))
                if (newest is not None and s < newest
                        and not self._is_committed(f"step_{s}")):
                    shutil.rmtree(full, ignore_errors=True)
                continue
            m = self._STEP_RE.match(base)
            if (m and newest is not None and int(m.group(1)) < newest
                    and os.path.join(self.directory, base) not in pending
                    and not self._is_committed(base)):
                shutil.rmtree(full, ignore_errors=True)

    def _gc_fleet(self) -> None:
        """Fleet-mode retention: a step is prunable ONLY when strictly
        older than the newest GLOBALLY-committed step. A locally
        committed (or still-staging) step at or above that floor may be
        the fleet's next common restorable state — pruning it out from
        under a peer that hasn't finished staging is exactly the
        multi-host ``max_to_keep=1`` data-loss hazard. Retention counts
        globally committed steps; torn stages below the floor are
        provably superseded and swept."""
        gsteps = self.globally_committed_steps()
        if not gsteps:
            return  # nothing fleet-trusted yet: prune NOTHING
        newest = gsteps[-1]
        protected = set(gsteps[-self.max_to_keep:])
        pending = {t.directory for t in self._pending
                   if t.directory is not None}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            base = name
            for suf in (".tmp", ".old"):
                if name.endswith(suf):
                    base = name[:-len(suf)]
                    break
            ma = self._AOT_RE.match(base)
            if ma:
                # artifacts ride the fleet retention of their step:
                # prunable only below the globally-committed floor and
                # outside the protected window (same rule as step dirs)
                s = int(ma.group(1))
                if s < newest and s not in protected:
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
                continue
            m = self._STEP_RE.match(base)
            if not m:
                continue
            full = os.path.join(self.directory, name)
            tgt = os.path.join(self.directory, base)
            if name.endswith(".old"):
                # same .old recovery contract as the single-process GC:
                # a kill mid-rename-swap leaves the step's only copy in
                # the trash name — put it back, never erase it
                if os.path.exists(tgt):
                    shutil.rmtree(full, ignore_errors=True)
                elif os.path.exists(os.path.join(full, _MANIFEST)):
                    try:
                        os.rename(full, tgt)
                    except OSError:
                        pass
                else:
                    shutil.rmtree(full, ignore_errors=True)
                continue
            s = int(m.group(1))
            if s >= newest or s in protected or tgt in pending:
                continue
            shutil.rmtree(full, ignore_errors=True)


# --- dygraph-parity convenience (reference: dygraph/checkpoint.py) ---------

def save(state_or_layer, path: str) -> None:
    """``pt.checkpoint.save(model, path)`` or ``save(state_dict, path)`` —
    the reference's save_persistables for a Layer's params+buffers."""
    state = (state_or_layer.state_dict()
             if hasattr(state_or_layer, "state_dict") else state_or_layer)
    save_state(path, state)


def load(path: str, *, mesh=None) -> Dict[str, Any]:
    """Returns the saved state dict (feed to ``Layer.load_state_dict``)."""
    return restore_state(path, mesh=mesh)
