"""Typed configuration tree with environment-variable overrides.

Replaces the reference's scattered gflags + the ``__bootstrap__`` env whitelist
(reference: python/paddle/fluid/__init__.py:134-191, which builds
``read_env_flags`` and calls ``core.init_gflags(["--tryfromenv=..."])``).

Design: a single registry of typed flags, each overridable via ``FLAGS_<name>``
environment variables, plus structured strategy dataclasses for the compile/run
APIs (mirroring BuildStrategy / ExecutionStrategy,
reference: paddle/fluid/framework/details/build_strategy.h:36).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional

from .enforce import enforce, invalid_argument

_BOOL_TRUE = {"1", "true", "yes", "on"}
_BOOL_FALSE = {"0", "false", "no", "off"}


def _parse_bool(s: str) -> bool:
    ls = s.strip().lower()
    if ls in _BOOL_TRUE:
        return True
    if ls in _BOOL_FALSE:
        return False
    invalid_argument(f"cannot parse bool from {s!r}")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


@dataclasses.dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    value: Any = None


class FlagRegistry:
    """Registry of named typed flags, env-overridable as ``FLAGS_<name>``."""

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}

    def define(self, name: str, default: Any, help: str = "") -> None:
        enforce(name not in self._flags, "flag %s already defined", name)
        ty = type(default)
        enforce(ty in _PARSERS, "unsupported flag type %s", ty)
        flag = _Flag(name=name, default=default, type=ty, help=help)
        env = os.environ.get(f"FLAGS_{name}")
        flag.value = _PARSERS[ty](env) if env is not None else default
        self._flags[name] = flag

    def get(self, name: str) -> Any:
        enforce(name in self._flags, "unknown flag %s", name)
        return self._flags[name].value

    def set(self, name: str, value: Any) -> None:
        enforce(name in self._flags, "unknown flag %s", name)
        flag = self._flags[name]
        # Strings go through the same parser as env vars so "false"/"0"/"off"
        # behave identically everywhere.
        if isinstance(value, str):
            flag.value = _PARSERS[flag.type](value)
        else:
            flag.value = flag.type(value)

    def reset(self, name: str) -> None:
        flag = self._flags[name]
        flag.value = flag.default

    def all(self) -> Dict[str, Any]:
        return {f.name: f.value for f in self._flags.values()}

    def __contains__(self, name: str) -> bool:
        return name in self._flags


FLAGS = FlagRegistry()

# Core flags (whitelist mirroring the reference's read_env_flags).
FLAGS.define("check_nan_inf", False, "insert nan/inf checks on op outputs (debug mode)")
FLAGS.define("benchmark", False, "synchronize and time every step")
FLAGS.define("default_dtype", "float32", "default parameter dtype")
FLAGS.define("compute_dtype", "bfloat16", "default matmul/conv compute dtype on TPU")
FLAGS.define("seed", 0, "global random seed (0 = nondeterministic)")
FLAGS.define("log_level", 0, "verbosity, VLOG-style")
FLAGS.define("allocator_strategy", "pjrt", "device memory strategy (informational; PJRT owns HBM)")
FLAGS.define("compile_cache_capacity", 128, "max cached executables per Executor")
FLAGS.define("deterministic", False, "force deterministic reductions/collectives")
FLAGS.define("static_verify", True,
             "run the static analyzers (analysis/) at compile boundaries: "
             "Program IR verification on the Executor's first compile of a "
             "program version, donation-provenance checks at Trainer "
             "compile time; 0 disables all wired-in passes")


@dataclasses.dataclass
class ExecutionStrategy:
    """Runtime knobs for an executor (reference: details/execution_strategy.h)."""

    num_iteration_per_drop_scope: int = 1  # kept for API parity; XLA manages buffers
    use_experimental_executor: bool = False
    sync_every_step: bool = False  # block_until_ready each step (benchmark mode)


@dataclasses.dataclass
class BuildStrategy:
    """Compile-time strategy (reference: details/build_strategy.h:36).

    Most reference fields (fusion toggles, memory-optimize passes) are subsumed
    by XLA; retained fields are the ones that still change compilation.
    """

    reduce_strategy: str = "all_reduce"  # "all_reduce" | "reduce_scatter"
    gradient_scale_strategy: str = "coeff_one"  # "coeff_one" | "one_over_n"
    fuse_all_reduce_ops: bool = True  # grad coalescing (XLA does this; kept as hint)
    donate_inputs: bool = True  # buffer donation for train state (in-place update)
    remat_policy: Optional[str] = None  # None | "full" | "dots" — jax.checkpoint policy

    class ReduceStrategy:
        """reference: details/build_strategy.h:57 ReduceStrategy enum."""

        AllReduce = "all_reduce"
        Reduce = "reduce_scatter"

        def __init__(self, value: str = "all_reduce"):
            self.value = value

    class GradientScaleStrategy:
        """reference: details/build_strategy.h:59 GradientScaleStrategy."""

        CoeffNumDevice = "coeff_one"
        One = "one"
        Customized = "customized"

        def __init__(self, value: str = "coeff_one"):
            self.value = value


@dataclasses.dataclass
class DistributeConfig:
    """Mesh/parallelism config — the successor of DistributeTranspilerConfig
    (reference: transpiler/distribute_transpiler.py:130) expressed as mesh axes."""

    dp: int = 1  # data parallel
    tp: int = 1  # tensor parallel
    pp: int = 1  # pipeline parallel
    sp: int = 1  # sequence/context parallel
    ep: int = 1  # expert parallel

    def total(self) -> int:
        return self.dp * self.tp * self.pp * self.sp * self.ep
