"""Thin shim over ``paddle_tpu.telemetry.trace`` (the span machinery
moved there; this module keeps the historical import surface).

Parity targets (SURVEY §5.1):
  - RAII ``RecordEvent`` (reference: paddle/fluid/platform/profiler.h:81)
  - python ``fluid.profiler.profiler`` context (reference:
    python/paddle/fluid/profiler.py:222)
  - ``tools/timeline.py`` chrome://tracing export (reference:
    tools/timeline.py:131)

All of it now lives in ``telemetry.trace``, which adds span nesting and
a structured JSONL export on top; see that module. ``_events``/``_lock``
are re-exported for the fluid compat layer — the list is mutated in
place only, so these aliases never go stale.
"""

from __future__ import annotations

from ..telemetry.trace import (RecordEvent, Span, _events, _lock,
                               export_chrome_trace, export_jsonl,
                               get_events, profiler, record_event, span,
                               start_profiler, stop_profiler)

__all__ = [
    "RecordEvent", "Span", "export_chrome_trace", "export_jsonl",
    "get_events", "profiler", "record_event", "span", "start_profiler",
    "stop_profiler",
]
