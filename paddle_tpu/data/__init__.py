"""Data layer: reader decorators, feeders, datasets, ragged batching."""

from . import dataset
from .feeder import DataFeeder, DeviceLoader
from .reader import (batch, buffered, cache, chain, compose, firstn,
                     map_readers, shuffle, xmap_readers)

__all__ = [
    "dataset", "DataFeeder", "DeviceLoader", "batch", "buffered", "cache",
    "chain", "compose", "firstn", "map_readers", "shuffle", "xmap_readers",
]
