"""Data layer: reader decorators, feeders, datasets, ragged batching."""

from . import dataset
from .bpe import BPETokenizer
from .bucketing import (bucket_by_length, pad_to,
                        quantile_boundaries)
from .data_generator import MultiSlotDataGenerator
from .dataset import MultiSlotDataset, train_from_dataset
from .device_loader import (BucketPadder, DevicePrefetcher,
                            prefetch_to_device)
from .feeder import DataFeeder, DeviceLoader
from .reader import (Fake, PipeReader, batch, buffered, cache, chain,
                     compose, creator, firstn, map_readers,
                     multiprocess_reader, shuffle, xmap_readers)

__all__ = [
    "BPETokenizer", "BucketPadder", "DevicePrefetcher",
    "MultiSlotDataGenerator", "train_from_dataset",
    "bucket_by_length", "pad_to", "prefetch_to_device",
    "quantile_boundaries",
    "dataset", "MultiSlotDataset", "DataFeeder", "DeviceLoader", "batch", "buffered", "cache",
    "chain", "compose", "firstn", "map_readers", "shuffle", "xmap_readers",
    "Fake", "PipeReader", "creator", "multiprocess_reader",
]
