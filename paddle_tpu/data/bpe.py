"""Byte-level BPE tokenizer — train/encode/decode with no external
dependencies, so the causal-LM family (models/gpt.py, serving.py) has a
complete text path in-framework.

Byte-level: the base alphabet is all 256 bytes, so ANY string encodes
losslessly (no unk) and decode is exact byte reconstruction. Merges are
learned greedily on pair frequency (the standard BPE objective);
encoding applies merges by learned rank (lowest rank first), the
tie-stable order that reproduces GPT-2-style tokenizers.

Host-side by design: tokenization is IO-time work that belongs in the
input pipeline (data/ decorators), never inside jit. Green-field vs the
reference (its text path is pre-tokenized id files, reference:
python/paddle/dataset/imdb.py tokenize role + the NMT benchmark's
pre-built vocab, benchmark/fluid/models/machine_translation.py).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.enforce import enforce


class BPETokenizer:
    """``train()`` learns merges; ``encode(str) -> List[int]``,
    ``decode(ids) -> str``. Token ids: 0..255 are raw bytes, 256+ are
    merges in learned order, then specials. ``save``/``load``
    round-trip the vocabulary as JSON."""

    def __init__(self, merges: Optional[List[Tuple[int, int]]] = None,
                 specials: Sequence[str] = ()):
        self.merges: List[Tuple[int, int]] = list(merges or [])
        self._ranks: Dict[Tuple[int, int], int] = {
            tuple(m): i for i, m in enumerate(self.merges)}
        self.specials: Dict[str, int] = {}
        for s in specials:
            self.add_special(s)

    # --- vocab -------------------------------------------------------------

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges) + len(self.specials)

    def add_special(self, token: str) -> int:
        """Register a special token (e.g. "<|eos|>"); returns its id.
        Specials are matched exactly and never split."""
        if token in self.specials:
            return self.specials[token]
        tid = 256 + len(self.merges) + len(self.specials)
        self.specials[token] = tid
        return tid

    # --- train -------------------------------------------------------------

    def train(self, texts: Iterable[str], vocab_size: int,
              min_pair_count: int = 2) -> "BPETokenizer":
        """Learn ``vocab_size - 256 - len(specials)`` merges from
        ``texts`` (greedy highest-count pair, ties by first-seen order
        via Counter insertion). Stops early when no pair reaches
        ``min_pair_count``."""
        enforce(vocab_size > 256 + len(self.specials),
                "vocab_size %s leaves no room for merges over the 256 "
                "byte alphabet + %s specials", vocab_size,
                len(self.specials))
        enforce(not self.merges,
                "train() on an already-trained tokenizer (merges=%s)",
                len(self.merges))
        from collections import defaultdict

        seqs = [list(t.encode("utf-8")) for t in texts]
        n_merges = vocab_size - 256 - len(self.specials)
        # incremental pair counts (the standard BPE-trainer
        # optimization): a merge only re-counts the sequences that
        # CONTAIN the merged pair — O(affected) per merge, not
        # O(corpus); `where` is the pair -> sequence-index inverted
        # index that finds them without a scan
        seq_counts = [Counter(zip(s, s[1:])) for s in seqs]
        counts: Counter = Counter()
        where = defaultdict(set)
        for i, c in enumerate(seq_counts):
            counts.update(c)
            for p in c:
                where[p].add(i)
        for _ in range(n_merges):
            if not counts:
                break
            pair, cnt = counts.most_common(1)[0]
            if cnt < min_pair_count:
                break
            new_id = 256 + len(self.merges)
            self.merges.append(pair)
            self._ranks[pair] = len(self.merges) - 1
            for i in list(where.get(pair, ())):
                old = seq_counts[i]
                counts.subtract(old)
                seqs[i] = _apply_merge(seqs[i], pair, new_id)
                new = Counter(zip(seqs[i], seqs[i][1:]))
                seq_counts[i] = new
                counts.update(new)
                for p in old:
                    if p not in new:
                        where[p].discard(i)
                for p in new:
                    where[p].add(i)
            counts = +counts  # drop <= 0 entries (subtract leftovers)
        # specials keep ids ABOVE the merge range: reassign after train
        self.specials = {s: 256 + len(self.merges) + i
                         for i, s in enumerate(self.specials)}
        return self

    # --- encode/decode -----------------------------------------------------

    def encode(self, text: str) -> List[int]:
        out: List[int] = []
        for chunk, is_special in self._split_specials(text):
            if is_special:
                out.append(self.specials[chunk])
                continue
            ids = list(chunk.encode("utf-8"))
            while len(ids) > 1:
                # lowest-rank applicable merge first (the learned order)
                best = min(zip(ids, ids[1:]),
                           key=lambda p: self._ranks.get(p, 1 << 60))
                if best not in self._ranks:
                    break
                ids = _apply_merge(ids, best,
                                   256 + self._ranks[best])
            out.extend(ids)
        return out

    def decode(self, ids: Sequence[int]) -> str:
        inv_special = {v: k for k, v in self.specials.items()}
        data = bytearray()
        text: List[str] = []

        def flush():
            if data:
                text.append(bytes(data).decode("utf-8",
                                               errors="replace"))
                data.clear()

        for tid in ids:
            tid = int(tid)
            if tid in inv_special:
                flush()
                text.append(inv_special[tid])
            else:
                data.extend(self._expand(tid))
        flush()
        return "".join(text)

    def _expand(self, tid: int) -> bytes:
        enforce(0 <= tid < 256 + len(self.merges),
                "token id %s outside vocab (%s)", tid, self.vocab_size)
        if tid < 256:
            return bytes([tid])
        a, b = self.merges[tid - 256]
        return self._expand(a) + self._expand(b)

    def _split_specials(self, text: str):
        if not self.specials:
            yield text, False
            return
        # longest-first exact matching
        toks = sorted(self.specials, key=len, reverse=True)
        i, start = 0, 0
        while i < len(text):
            hit = next((t for t in toks if text.startswith(t, i)), None)
            if hit is not None:
                if i > start:
                    yield text[start:i], False
                yield hit, True
                i += len(hit)
                start = i
            else:
                i += 1
        if start < len(text):
            yield text[start:], False

    # --- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        from ..utils.atomic import atomic_write_text

        atomic_write_text(path, json.dumps(
            {"merges": self.merges, "specials": self.specials}))

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            d = json.load(f)
        tok = cls([tuple(m) for m in d["merges"]])
        tok.specials = {k: int(v) for k, v in d["specials"].items()}
        return tok


def _apply_merge(ids: List[int], pair: Tuple[int, int],
                 new_id: int) -> List[int]:
    out: List[int] = []
    i = 0
    while i < len(ids):
        if (i + 1 < len(ids) and ids[i] == pair[0]
                and ids[i + 1] == pair[1]):
            out.append(new_id)
            i += 2
        else:
            out.append(ids[i])
            i += 1
    return out
