"""Datasets — capability analog of paddle.dataset.* (reference:
python/paddle/dataset/ — mnist, cifar, imdb, wmt14/16, uci_housing, ...).

This environment has no network egress, so loaders follow a two-tier policy:
real files when present under ``~/.cache/paddle_tpu/dataset`` (same idea as
the reference's paddle.dataset.common.DATA_HOME download cache), else
deterministic *synthetic* datasets with the same shapes/dtypes/reader
contract — sufficient for convergence smoke tests (tests/book analog) and
benchmarking input pipelines.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Iterator, Tuple

import numpy as np

DATA_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


# --- MNIST -----------------------------------------------------------------

def _mnist_files(mode: str):
    base = os.path.join(DATA_HOME, "mnist")
    imgs = os.path.join(base, f"{mode}-images-idx3-ubyte.gz")
    lbls = os.path.join(base, f"{mode}-labels-idx1-ubyte.gz")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return imgs, lbls
    return None


def _read_idx_images(path):
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    with gzip.open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data


def _synthetic_mnist(n: int, seed: int):
    """Class-conditional synthetic digits: each class k has a fixed random
    prototype; samples are noisy prototypes. Linearly separable enough to
    train real models to high accuracy — the convergence-smoke role of
    tests/book/test_recognize_digits.py."""
    rng = np.random.default_rng(seed)
    protos = rng.uniform(0.0, 1.0, (10, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int64)
    noise = rng.normal(0.0, 0.35, (n, 28, 28)).astype(np.float32)
    images = protos[labels] + noise
    images = (images - 0.5) / 0.5
    return images.astype(np.float32), labels


def mnist(mode: str = "train", synthetic_size: int = 4096) -> Callable:
    """Reader creator yielding (image(784,) float32 in [-1,1], label int64).
    Mirrors paddle.dataset.mnist.train()/test() (reference:
    python/paddle/dataset/mnist.py)."""
    files = _mnist_files("train" if mode == "train" else "t10k")

    def reader() -> Iterator[Tuple[np.ndarray, int]]:
        if files is not None:
            images = _read_idx_images(files[0]).astype(np.float32)
            labels = _read_idx_labels(files[1]).astype(np.int64)
            images = (images / 255.0 - 0.5) / 0.5
        else:
            images, labels = _synthetic_mnist(
                synthetic_size, seed=0 if mode == "train" else 1)
        for img, lbl in zip(images, labels):
            yield img.reshape(-1), int(lbl)

    return reader


# --- CIFAR-like ------------------------------------------------------------

def cifar10(mode: str = "train", synthetic_size: int = 2048) -> Callable:
    """(image(3,32,32) float32, label int64) — paddle.dataset.cifar analog."""

    def reader():
        rng = np.random.default_rng(7 if mode == "train" else 8)
        protos = rng.uniform(-1, 1, (10, 3, 32, 32)).astype(np.float32)
        for _ in range(synthetic_size):
            lbl = int(rng.integers(0, 10))
            img = protos[lbl] + rng.normal(0, 0.4, (3, 32, 32)).astype(np.float32)
            yield img, lbl

    return reader


# --- ImageNet-shaped synthetic (bench input) -------------------------------

def fake_imagenet(batch_hw: int = 224, num_classes: int = 1000,
                  size: int = 1024, seed: int = 0) -> Callable:
    def reader():
        rng = np.random.default_rng(seed)
        for _ in range(size):
            img = rng.normal(0, 1, (3, batch_hw, batch_hw)).astype(np.float32)
            yield img, int(rng.integers(0, num_classes))

    return reader


# --- sequence / NMT-shaped synthetic ---------------------------------------

def synthetic_translation(vocab_size: int = 1000, size: int = 2048,
                          min_len: int = 4, max_len: int = 30,
                          seed: int = 0) -> Callable:
    """(src_ids, trg_ids) variable length — the wmt14 reader contract
    (reference: python/paddle/dataset/wmt14.py). Target = reversed source
    (a learnable synthetic task)."""

    def reader():
        rng = np.random.default_rng(seed)
        for _ in range(size):
            n = int(rng.integers(min_len, max_len + 1))
            src = rng.integers(2, vocab_size, n).astype(np.int64)
            trg = src[::-1].copy()
            yield src, trg

    return reader


# --- CTR-shaped synthetic (DeepFM input) -----------------------------------

def synthetic_ctr(num_sparse_fields: int = 26, sparse_dim: int = 100000,
                  num_dense: int = 13, size: int = 4096, seed: int = 0) -> Callable:
    """(dense(13,), sparse_ids(26,), label) — Criteo-shaped
    (reference: PS/CTR pipeline, data_feed.cc MultiSlot)."""

    def reader():
        rng = np.random.default_rng(seed)
        w_d = rng.normal(0, 1, num_dense)
        w_s = rng.normal(0, 1, num_sparse_fields)
        for _ in range(size):
            dense = rng.normal(0, 1, num_dense).astype(np.float32)
            sparse = rng.integers(0, sparse_dim, num_sparse_fields)
            logit = dense @ w_d + ((sparse % 7) - 3) @ w_s * 0.2
            label = int(logit + rng.normal(0, 1) > 0)
            yield dense, sparse.astype(np.int64), label

    return reader


class MultiSlotDataset:
    """Dataset-style UX over the native C++ feed (reference:
    python/paddle/fluid/dataset.py:21 InMemoryDataset/QueueDataset —
    set_filelist/set_batch_size/set_thread then iterate). Parsing and
    batching happen in C++ worker threads (paddle_tpu.native)."""

    def __init__(self):
        self._files = []
        self._slots = []
        self._batch_size = 1
        self._threads = 2
        self._queue_capacity = 8
        self._drop_last = True

    def set_filelist(self, files):
        self._files = list(files)
        return self

    def set_use_var(self, slots):
        """slots: [(name, 'u'|'f'), ...] in file order (the reference binds
        slots to program vars; here names key the yielded dict)."""
        self._slots = list(slots)
        return self

    def set_batch_size(self, bs: int):
        self._batch_size = bs
        return self

    def set_thread(self, n: int):
        self._threads = n
        return self

    def set_queue_capacity(self, n: int):
        self._queue_capacity = n
        return self

    def set_drop_last(self, drop: bool):
        self._drop_last = drop
        return self

    def __iter__(self):
        from .. import native

        feed = native.MultiSlotFeed(
            self._files, self._slots, self._batch_size,
            num_threads=self._threads, queue_capacity=self._queue_capacity,
            drop_last=self._drop_last)
        try:
            yield from feed
        finally:
            feed.close()


def train_from_dataset(trainer, dataset: "MultiSlotDataset",
                       batch_transform, epochs: int = 1,
                       on_step=None):
    """Dataset-based training driver — the AsyncExecutor/dataset-training
    UX (reference: framework/async_executor.h:62 + executor.py
    train_from_dataset: C++ threads parse+batch while the device trains).

    ``batch_transform(raw)`` maps the feed's {slot: (values, lengths)} dict
    to the trainer's batch format. Returns the number of steps run.

    Honors the ambient :class:`resilience.PreemptionHandler` when one
    is installed (resolved once — no handler, no per-step resilience
    code): on signal the loop finishes the in-flight step and returns
    early so the caller can checkpoint within the grace window."""
    from ..resilience import preemption as _preemption

    pre = _preemption.active()
    steps = 0
    for _ in range(epochs):
        for raw in dataset:
            loss, metrics = trainer.train_step(batch_transform(raw))
            steps += 1
            if on_step is not None:
                on_step(steps, loss, metrics)
            if pre is not None and pre.requested():
                return steps
    return steps
